"""Measured step-time attribution for the flagship train step.

`ANALYSIS_MFU.md`'s budget table models where the 350M step time goes;
this tool replaces the model with a measurement: it traces a few steps
with ``jax.profiler.trace`` and aggregates device-plane op durations from
the xplane proto (parsed via tensorflow.tsl's ``xplane_pb2`` — the same
artifact xprof/tensorboard reads). The reference ships CUDA-event timers
around its kernels (`csrc/includes/Timer.h`); under XLA the equivalent
visibility comes from the profiler's per-op device timeline.

Prints ONE JSON line: {"metric": "GPT-2 350M step-time attribution",
"ms_per_step": ..., "categories": {...}, "top_ops": [...]}.

Usage: python benchmarks/profile_step.py [--steps 3] [--keep-trace DIR]
"""

import argparse
import glob
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def classify(name):
    """Coarse HLO-op category from the (fusion) op name."""
    n = name.lower()
    if "flash" in n or "custom-call" in n or "custom_call" in n:
        return "custom-call (pallas)"
    if any(k in n for k in ("all-reduce", "all-gather", "reduce-scatter",
                            "all-to-all", "collective")):
        return "collective"
    if "dot" in n or "conv" in n or "matmul" in n:
        return "matmul"
    if any(k in n for k in ("copy", "transpose", "bitcast", "reshape")):
        return "layout/copy"
    if any(k in n for k in ("dynamic-update-slice", "dynamic-slice",
                            "scatter", "gather")):
        return "slice/gather"
    if "infeed" in n or "outfeed" in n or "send" in n or "recv" in n:
        return "host-transfer"
    return "elementwise/other"


def aggregate_xplanes(trace_dir):
    """Mean per-device op durations by name across all xplane files.

    Returns ``(per_name_ps, device_total_ps, n_device_planes)`` — sums
    are divided by the number of device planes so multi-chip traces
    (one plane per chip, each recording the full per-shard step) report
    one device's step time, comparable to ANALYSIS_MFU's budget. Only
    device planes count — host threads are bookkeeping.
    """
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(
        trace_dir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        raise FileNotFoundError(f"no .xplane.pb under {trace_dir}")
    per_name = {}
    total = 0
    n_planes = 0
    for path in paths:
        space = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
        for plane in space.planes:
            pname = plane.name
            if not ("TPU" in pname or "GPU" in pname
                    or "/device:" in pname):
                continue
            n_planes += 1
            meta = {m.id: m.name for m in plane.event_metadata.values()}
            for line in plane.lines:
                # XLA-op lines carry the per-op events; "Steps"/"XLA
                # Modules" lines would double-count the same wall time.
                if "xla op" not in line.name.lower():
                    continue
                for ev in line.events:
                    name = meta.get(ev.metadata_id, str(ev.metadata_id))
                    dur = ev.duration_ps
                    per_name[name] = per_name.get(name, 0) + dur
                    total += dur
    if n_planes > 1:
        per_name = {k: v / n_planes for k, v in per_name.items()}
        total /= n_planes
    return per_name, total, n_planes


def emit(payload):
    print(json.dumps(payload), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--keep-trace", default=None,
                    help="persist the raw trace under this dir (a fresh "
                         "run-specific subdir — re-running never "
                         "aggregates a previous run's xplanes)")
    args = ap.parse_args()

    import bench  # repo-root bench: subprocess backend probe

    # Probe in a subprocess (a wedged tunnel blocks forever in-process);
    # fall back to the CPU plumbing check rather than bench.py's
    # cached-row short-circuit — a profile must be live or not at all.
    if bench.probe_platform() is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    platform = devices[0].platform
    on_tpu = platform == "tpu"

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (
        GPT2LMHead, gpt2_350m, gpt2_tiny, init_gpt2_params,
        make_gpt2_loss_fn)

    chunk = int(os.environ.get("BENCH_LOSS_CHUNK", "0"))
    chunk_tag = f", chunked-CE{chunk}" if chunk else ""
    if on_tpu:
        cfg_fn, bs, seq = gpt2_350m, 8, 1024
        label = f"GPT-2 350M (bf16, seq1024, bs8{chunk_tag})"
    else:  # CPU plumbing check
        cfg_fn, bs, seq = gpt2_tiny, 2, 64
        label = f"GPT-2 tiny (cpu-smoke{chunk_tag})"

    cfg = cfg_fn(n_positions=seq, use_flash_attention=on_tpu,
                 loss_chunk=chunk)
    model = GPT2LMHead(cfg)
    bench.hb(f"profile: init params ({label})")
    params = init_gpt2_params(model, jax.random.PRNGKey(0), seq_len=seq)
    bench.hb("profile: params ready; building engine")
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": bs, "bf16": {"enabled": True},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "steps_per_print": 10 ** 9},
        loss_fn=make_gpt2_loss_fn(model), params=params)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (bs, seq)).astype(np.int32)}

    for i in range(2):  # compile + warm
        float(engine.train_batch(batch))
        bench.hb(f"profile: warmup {i + 1}/2 done")

    if args.keep_trace:
        os.makedirs(args.keep_trace, exist_ok=True)
        trace_dir = tempfile.mkdtemp(prefix="run_", dir=args.keep_trace)
    else:
        trace_dir = tempfile.mkdtemp(prefix="ds_tpu_prof_")
    with jax.profiler.trace(trace_dir):
        for _ in range(args.steps):
            loss = engine.train_batch(batch)
        float(loss)
    bench.hb("profile: trace captured; aggregating xplanes")

    per_name, total_ps, n_planes = aggregate_xplanes(trace_dir)
    cats = {}
    for name, ps in per_name.items():
        cats[classify(name)] = cats.get(classify(name), 0) + ps
    ms = 1e-9  # ps -> ms
    top = sorted(per_name.items(), key=lambda kv: -kv[1])[:15]
    out = {
        "metric": f"{label} step-time attribution (device op time)",
        "steps": args.steps,
        "device_planes": n_planes,
        "device_ms_per_step": round(total_ps * ms / args.steps, 3),
        "categories_ms_per_step": {
            k: round(v * ms / args.steps, 3)
            for k, v in sorted(cats.items(), key=lambda kv: -kv[1])},
        "top_ops_ms_per_step": [
            [n[:80], round(ps * ms / args.steps, 3)] for n, ps in top],
    }
    if not on_tpu:
        # The CPU backend writes host-thread planes only (no XLA-op
        # device plane), so the smoke validates trace+parse plumbing,
        # not attribution values.
        out["smoke"] = True
        out["note"] = "cpu trace has no device plane; plumbing check only"
    emit(out)
    if not args.keep_trace:
        import shutil
        shutil.rmtree(trace_dir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
