"""ZeRO-Offload: train a model bigger than HBM would otherwise allow on
ONE chip — fp32 masters + Adam moments live in host RAM, updated by the
native C++ AVX/OpenMP Adam; the device holds bf16 params + activations
(the reference's 13B-params-on-one-V100 capability,
`docs/_tutorials/zero-offload.md`).

Usage: python examples/zero_offload_gpt2.py [--size 350m|760m|1.5b]
       [--steps N] [--seq_len 1024]
"""
import argparse
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", default="760m",
                        choices=["tiny", "350m", "760m", "1.5b"])
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=1024)
    import deepspeed_tpu
    deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()

    deepspeed_tpu.parallel.initialize_distributed()
    import jax
    from deepspeed_tpu.models.gpt2 import (
        GPT2LMHead, gpt2_1_5b, gpt2_350m, gpt2_760m, gpt2_tiny,
        init_gpt2_params, make_gpt2_loss_fn)

    cfg_fn = {"tiny": gpt2_tiny, "350m": gpt2_350m, "760m": gpt2_760m,
              "1.5b": gpt2_1_5b}[args.size]
    on_tpu = jax.devices()[0].platform == "tpu"
    if args.size == "tiny":
        args.seq_len = min(args.seq_len, 64)
    cfg = cfg_fn(n_positions=max(args.seq_len, 64), remat=True,
                 use_flash_attention=on_tpu)
    model = GPT2LMHead(cfg)
    params = init_gpt2_params(model, jax.random.PRNGKey(0),
                              seq_len=args.seq_len)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    print(f"{args.size}: {n_params / 1e6:.0f}M params "
          f"(fp32 master+moments = {n_params * 12 / 1e9:.1f} GB in host "
          f"RAM, bf16 weights = {n_params * 2 / 1e9:.1f} GB in HBM)")

    config = getattr(args, "deepspeed_config", None) or {
        "train_batch_size": args.batch_size,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "cpu_offload": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 5,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, config=config, loss_fn=make_gpt2_loss_fn(model),
        params=params)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, cfg.vocab_size, (args.batch_size, args.seq_len)).astype(np.int32)}
    float(engine.train_batch(batch))  # compile
    t0 = time.perf_counter()
    for step in range(args.steps):
        loss = engine.train_batch(batch)
    loss = float(loss)
    dt = time.perf_counter() - t0
    tps = args.batch_size * args.seq_len * args.steps / dt
    print(f"loss {loss:.4f}; {tps:,.0f} tokens/sec/chip with host-offloaded "
          f"optimizer")


if __name__ == "__main__":
    main()
