"""The composition showcase: 3D (dp x pp x tp), long-context (pp x sp),
and the round-5 four-axis dp x pp x tp x ep block (TP attention + MoE
FFN), all through the one public entry point.

Runs on the virtual CPU mesh out of the box:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        PYTHONPATH=. python examples/parallel_3d_long_context.py

On real hardware, drop the env overrides and size the mesh to the slice.
"""
import argparse

import numpy as np


def train(engine, batch, steps, tag):
    losses = [float(engine.train_batch(batch)) for _ in range(steps)]
    print(f"{tag}: loss {losses[0]:.4f} -> {losses[-1]:.4f} "
          f"({steps} steps)")
    return losses


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=10)
    import deepspeed_tpu
    deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()

    from deepspeed_tpu.parallel.mesh import (build_mesh,
                                             initialize_distributed)
    from deepspeed_tpu.parallel.pipe_sp import sp_pipeline_module
    from deepspeed_tpu.parallel.pipe_tp import tp_pipeline_module
    initialize_distributed()      # multi-host rendezvous (no-op solo)
    import jax

    rng = np.random.default_rng(0)
    vocab, d_model, n_head, seq = 64, 16, 4, 32
    rows, micro = 8, 2
    config = {"train_batch_size": rows,
              "gradient_accumulation_steps": micro,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    batch = {"input_ids": rng.integers(0, vocab,
                                       (rows, seq)).astype(np.int32)}

    # ---- 1. true 3D: data x pipe x tensor parallel -------------------
    engine3d, _, _, _ = deepspeed_tpu.initialize(
        config=config,
        model=tp_pipeline_module(vocab, d_model, n_head, seq),
        mesh=build_mesh({"pipe": 2, "model": 2, "data": 2},
                        devices=jax.devices()[:8]))
    train(engine3d, batch, args.steps, "3D  (pipe2 x model2 x data2)")

    # ---- 2. long context: pipe x sequence parallel -------------------
    engine_sp, _, _, _ = deepspeed_tpu.initialize(
        config=config,
        model=sp_pipeline_module(vocab, d_model, n_head, seq),
        mesh=build_mesh({"pipe": 2, "seq": 2, "data": 2},
                        devices=jax.devices()[:8]))
    train(engine_sp, batch, args.steps, "SP  (pipe2 x seq2 x data2)")

    # ---- 3. four axes: data x pipe x tensor x expert (round 5) -------
    # TP attention + expert-parallel MoE FFN in ONE pipeline block; the
    # data axis collapses to 1 on an 8-device mesh but remains a real
    # axis of the compiled program (size it up on larger slices).
    import functools
    from deepspeed_tpu.moe.layer import MoEConfig
    from deepspeed_tpu.parallel.pipe_tp_moe import TPMoEBlockLayer
    moe_block = functools.partial(
        TPMoEBlockLayer,
        moe=MoEConfig(num_experts=4, top_k=2, capacity_factor=2.0))
    engine4d, _, _, _ = deepspeed_tpu.initialize(
        config=config,
        model=tp_pipeline_module(vocab, d_model, n_head, seq,
                                 block_cls=moe_block),
        mesh=build_mesh({"data": 1, "pipe": 2, "model": 2, "expert": 2},
                        devices=jax.devices()[:8]))
    train(engine4d, batch, args.steps,
          "4D  (pipe2 x model2 x expert2, MoE FFN)")


if __name__ == "__main__":
    main()
