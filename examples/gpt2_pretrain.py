"""GPT-2 pretraining with ZeRO — the minimal end-to-end example.

Usage: python examples/gpt2_pretrain.py [--size tiny|125m|350m]
       [--steps N] [--deepspeed_config config.json]
"""
import argparse

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", default="tiny",
                        choices=["tiny", "125m", "350m"])
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=128)
    import deepspeed_tpu
    deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()

    # multi-host launches (deepspeed-tpu --hostfile ...) must join the
    # cluster BEFORE any jax call initializes the backend
    deepspeed_tpu.parallel.initialize_distributed()
    import jax
    from deepspeed_tpu.models.gpt2 import (
        GPT2LMHead, gpt2_125m, gpt2_350m, gpt2_tiny, init_gpt2_params,
        make_gpt2_loss_fn)

    cfg_fn = {"tiny": gpt2_tiny, "125m": gpt2_125m, "350m": gpt2_350m}
    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = cfg_fn[args.size](n_positions=max(args.seq_len, 64),
                            use_flash_attention=on_tpu)
    model = GPT2LMHead(cfg)
    params = init_gpt2_params(model, jax.random.PRNGKey(0),
                              seq_len=args.seq_len)

    config = getattr(args, "deepspeed_config", None) or {
        "train_batch_size": args.batch_size,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "optimizer": {"type": "Adam", "params": {"lr": 3e-4}},
        "gradient_clipping": 1.0,
        "steps_per_print": 10,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, config=config, loss_fn=make_gpt2_loss_fn(model),
        params=params)

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"input_ids": rng.integers(
            0, cfg.vocab_size,
            (args.batch_size, args.seq_len)).astype(np.int32)}
        loss = engine.train_batch(batch)
    print(f"final loss after {args.steps} steps: {float(loss):.4f}")


if __name__ == "__main__":
    main()
