"""BERT MLM pretraining on the fused transformer layer.

Usage: python examples/bert_mlm_pretrain.py [--steps N]
"""
import argparse

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--large", action="store_true",
                        help="BERT-Large instead of the tiny test size")
    import deepspeed_tpu
    deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()

    deepspeed_tpu.parallel.initialize_distributed()
    import jax
    from deepspeed_tpu.models.bert import (
        BertForMaskedLM, bert_large, bert_tiny, init_bert_params,
        make_bert_mlm_loss_fn)

    cfg = (bert_large if args.large else bert_tiny)(
        max_position_embeddings=max(args.seq_len, 64))
    model = BertForMaskedLM(cfg)
    params = init_bert_params(model, jax.random.PRNGKey(0),
                              seq_len=args.seq_len)
    config = getattr(args, "deepspeed_config", None) or {
        "train_batch_size": args.batch_size,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        args=args, config=config, loss_fn=make_bert_mlm_loss_fn(model),
        params=params)

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        ids = rng.integers(0, cfg.vocab_size,
                           (args.batch_size, args.seq_len)).astype(np.int32)
        labels = np.full_like(ids, -100, dtype=np.int64)
        mask = rng.random(ids.shape) < 0.15
        labels[mask] = ids[mask]
        loss = engine.train_batch({"input_ids": ids, "labels": labels})
    print(f"final loss after {args.steps} steps: {float(loss):.4f}")


if __name__ == "__main__":
    main()
