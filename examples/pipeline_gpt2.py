"""GPT-2 over a pipe x data mesh: the compiled 1F1B pipeline.

Usage: python examples/pipeline_gpt2.py [--pipe 2] [--steps N]
(device count must be divisible by --pipe)
"""
import argparse

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--pipe", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--microbatches", type=int, default=4)
    parser.add_argument("--seq_len", type=int, default=64)
    import deepspeed_tpu
    deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()

    deepspeed_tpu.parallel.initialize_distributed()
    import jax
    from deepspeed_tpu.models.gpt2 import gpt2_tiny
    from deepspeed_tpu.models.gpt2_pipe import gpt2_pipeline_module

    n_dev = jax.device_count()
    assert n_dev % args.pipe == 0, (n_dev, args.pipe)
    config = getattr(args, "deepspeed_config", None) or {
        "train_batch_size": args.batch_size,
        "gradient_accumulation_steps": args.microbatches,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 10,
        "mesh": {"pipe": args.pipe, "data": n_dev // args.pipe},
    }
    module = gpt2_pipeline_module(gpt2_tiny(n_layer=4),
                                  seq_len=args.seq_len)
    engine, _, _, _ = deepspeed_tpu.initialize(args=args, config=config,
                                               model=module)

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"input_ids": rng.integers(
            0, 255, (args.batch_size, args.seq_len)).astype(np.int32)}
        loss = engine.train_batch(batch)
    print(f"final loss after {args.steps} steps: {float(loss):.4f}")


if __name__ == "__main__":
    main()
