"""Multi-node transports: command construction for ssh/pdsh/gcloud.

Analog of the reference's ``launcher/multinode_runner.py`` (PDSHRunner:35,
OpenMPIRunner:78, MVAPICHRunner:118). The MPI runners have no TPU
equivalent — JAX rendezvous replaces mpirun — so the set here is plain
ssh (one connection per host), pdsh (parallel ssh fan-out), and the
GCE-native ``gcloud compute tpus tpu-vm ssh --worker=all``. All runners
only *construct* command lines (unit-testable with zero network).
"""

import os
import shlex

from deepspeed_tpu.launcher.runner import EXPORT_ENVS


class MultiNodeRunner:
    def __init__(self, args, world_info, master_addr, master_port):
        self.args = args
        self.world_info = world_info
        self.master_addr = master_addr
        self.master_port = master_port
        self.user_script = args.user_script
        self.user_args = list(args.user_args)
        self.ds_env = {}   # .deepspeed_env vars, set by runner.main

    def exports(self, env):
        """Env vars worth forwarding to remote shells: the EXPORT_ENVS
        prefix allowlist plus every .deepspeed_env key (reference
        runner.py:26-30 propagates the user's file verbatim)."""
        out = {}
        for key, val in env.items():
            if key in self.ds_env or \
                    any(key == e or key.startswith(e) for e in EXPORT_ENVS):
                out[key] = val
        return out

    def _worker_cmd(self, node_rank):
        """The per-host python command every transport wraps."""
        return [
            "python", "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info}",
            f"--node_rank={node_rank}",
            f"--master_addr={self.master_addr}",
            f"--master_port={self.master_port}",
            self.user_script,
        ] + self.user_args

    def get_cmd(self, env, active_resources):
        raise NotImplementedError


class SSHRunner(MultiNodeRunner):
    """One ssh invocation per host, backgrounded by the caller's shell.
    get_cmd returns the command for node 0; get_all_cmds covers the pod."""

    name = "ssh"

    def backend_exists(self):
        from shutil import which
        return which("ssh") is not None

    def get_all_cmds(self, env, active_resources):
        exports = " ".join(f"export {k}={shlex.quote(v)};"
                           for k, v in self.exports(env).items())
        cmds = []
        for rank, host in enumerate(active_resources):
            worker = " ".join(map(shlex.quote, self._worker_cmd(rank)))
            cmds.append(["ssh", "-o", "StrictHostKeyChecking=no", host,
                         f"cd {shlex.quote(os.getcwd())}; {exports} "
                         f"{worker}"])
        return cmds

    def get_cmd(self, env, active_resources):
        return self.get_all_cmds(env, active_resources)[0]


class PDSHRunner(MultiNodeRunner):
    """Parallel-ssh fan-out (the reference's default, multinode_runner
    .py:35). %n expands to the pdsh node index → node_rank."""

    name = "pdsh"

    def backend_exists(self):
        from shutil import which
        return which("pdsh") is not None

    def get_cmd(self, env, active_resources):
        # Mutates the caller's env: Popen must see PDSH_RCMD_TYPE or pdsh
        # falls back to its compiled default (rsh).
        env["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        exports = " ".join(f"export {k}={shlex.quote(v)};"
                           for k, v in self.exports(env).items())
        worker = " ".join(map(shlex.quote, self._worker_cmd("%n")))
        return ["pdsh", "-f", "1024", "-w", hosts,
                f"cd {shlex.quote(os.getcwd())}; {exports} {worker}"]


class GCloudRunner(MultiNodeRunner):
    """GCE TPU-VM native transport: one gcloud invocation reaches every
    worker of the pod slice (the TPU equivalent of the reference's pdsh
    broadcast). Worker index comes from the TPU metadata env on each VM."""

    name = "gcloud"

    def __init__(self, args, world_info, master_addr, master_port,
                 tpu_name=None, zone=None):
        super().__init__(args, world_info, master_addr, master_port)
        self.tpu_name = tpu_name or os.environ.get("TPU_NAME", "tpu-vm")
        self.zone = zone or os.environ.get("TPU_ZONE")

    def backend_exists(self):
        from shutil import which
        return which("gcloud") is not None

    def get_cmd(self, env, active_resources):
        exports = " ".join(f"export {k}={shlex.quote(v)};"
                           for k, v in self.exports(env).items())
        # On each worker the agent env provides its index. The node-rank
        # token must stay double-quoted (NOT shlex-quoted) so the remote
        # shell expands $TPU_WORKER_ID.
        parts = []
        for tok in self._worker_cmd("$TPU_WORKER_ID"):
            if "$TPU_WORKER_ID" in tok:
                parts.append('"--node_rank=$TPU_WORKER_ID"')
            else:
                parts.append(shlex.quote(tok))
        worker = " ".join(parts)
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", self.tpu_name,
               "--worker=all"]
        if self.zone:
            cmd.append(f"--zone={self.zone}")
        cmd += ["--command",
                f"cd {shlex.quote(os.getcwd())}; {exports} {worker}"]
        return cmd
