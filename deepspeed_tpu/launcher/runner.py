"""deepspeed_tpu launcher: TPU-pod job runner.

Analog of the reference launcher (`launcher/runner.py` — hostfile parsing
:115, include/exclude filtering, world-info encoding; `bin/deepspeed`).
Differences forced by the platform: a TPU host runs ONE process that owns
all its local chips (JAX's process model), so "slots" count chips per host
for accounting/filtering but spawning is per-host, and the rendezvous is
``jax.distributed.initialize(coordinator_address, num_processes,
process_id)`` rather than MASTER_ADDR/RANK env rendezvous.
"""

import argparse
import base64
import collections
import json
import os
import shlex
import subprocess
import sys

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHONPATH", "PATH", "JAX_", "XLA_", "TPU_", "LIBTPU_"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
DEFAULT_COORDINATOR_PORT = 29500


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher (reference launcher/runner.py)")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="MPI-style hostfile: '<host> slots=<n>' lines")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="NODE[:SLOT,SLOT]@NODE... inclusion filter")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="NODE[:SLOT,SLOT]@NODE... exclusion filter")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="limit to first N nodes")
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus",
                        help="limit chips per node (slot count)")
    parser.add_argument("--master_addr", type=str, default="",
                        help="coordinator address (default: first host)")
    parser.add_argument("--master_port", type=int,
                        default=DEFAULT_COORDINATOR_PORT)
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "gcloud"],
                        help="multi-node transport")
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("user_script", type=str,
                        help="training script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """Parse an MPI-style hostfile into an ordered {host: slots} dict
    (reference ``fetch_hostfile``, runner.py:115)."""
    if not os.path.isfile(hostfile_path):
        logger.warning(f"Unable to find hostfile at {hostfile_path}")
        return None
    resource_pool = collections.OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd:
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(
                    f"Hostfile is not formatted correctly, got line: "
                    f"{line!r} (expected '<host> slots=<n>')")
            if hostname in resource_pool:
                raise ValueError(
                    f"Hostfile contains duplicate hosts: {hostname}")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_filter(filter_str):
    """'host1:0,2@host2' → {host1: [0, 2], host2: []}"""
    mapping = collections.OrderedDict()
    if not filter_str:
        return mapping
    for term in filter_str.split("@"):
        term = term.strip()
        if ":" in term:
            host, slots = term.split(":")
            mapping[host] = [int(s) for s in slots.split(",")]
        else:
            mapping[term] = []
    return mapping


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    """Apply --include/--exclude NODE:SLOT filters (reference
    runner.py:120-250 semantics): include and exclude are mutually
    exclusive; bare NODE means every slot on it."""
    active = collections.OrderedDict(
        (host, list(range(slots))) for host, slots in resource_pool.items())
    inc = _parse_filter(inclusion)
    exc = _parse_filter(exclusion)
    if inc and exc:
        raise ValueError("--include and --exclude are mutually exclusive")

    if inc:
        filtered = collections.OrderedDict()
        for host, slots in inc.items():
            if host not in active:
                raise ValueError(f"include host {host} not in hostfile")
            bad = [s for s in slots if s not in active[host]]
            if bad:
                raise ValueError(f"include slots {bad} not on host {host}")
            filtered[host] = slots if slots else active[host]
        return filtered

    for host, slots in exc.items():
        if host not in active:
            raise ValueError(f"exclude host {host} not in hostfile")
        if not slots:
            del active[host]
        else:
            bad = [s for s in slots if s not in active[host]]
            if bad:
                raise ValueError(f"exclude slots {bad} not on host {host}")
            active[host] = [s for s in active[host] if s not in slots]
            if not active[host]:
                del active[host]
    return active


def encode_world_info(active_resources):
    """base64(json({host: [slots]})) — the reference's world_info wire
    format (runner.py / launch.py)."""
    world_info = json.dumps(
        {host: slots for host, slots in active_resources.items()})
    return base64.urlsafe_b64encode(world_info.encode()).decode()


def decode_world_info(encoded):
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


def load_deepspeed_env(base_dir=None):
    """Read ``.deepspeed_env`` (KEY=VALUE lines) for propagation to remote
    hosts (reference runner.py:26-30)."""
    candidates = [base_dir or os.getcwd(), os.path.expanduser("~")]
    env = collections.OrderedDict()
    for d in candidates:
        path = os.path.join(d, DEEPSPEED_ENVIRONMENT_NAME)
        if os.path.isfile(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line and "=" in line and not line.startswith("#"):
                        key, val = line.split("=", 1)
                        env[key] = val
            break
    return env


def apply_node_limits(resource_pool, num_nodes, num_slots):
    """--num_nodes/--num_gpus truncation (reference runner.py)."""
    pool = collections.OrderedDict(resource_pool)
    if num_nodes > 0:
        pool = collections.OrderedDict(list(pool.items())[:num_nodes])
    if num_slots > 0:
        pool = collections.OrderedDict(
            (h, min(s, num_slots)) for h, s in pool.items())
    return pool


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if resource_pool is None:
        # Single node, all local chips: exec in-process launcher.
        from deepspeed_tpu.launcher import launch
        cmd_args = ["--node_rank", "0", "--nnodes", "1"]
        if args.master_addr:
            cmd_args += ["--master_addr", args.master_addr]
        cmd_args += ["--master_port", str(args.master_port),
                     args.user_script] + args.user_args
        return launch.main(cmd_args)

    resource_pool = apply_node_limits(resource_pool, args.num_nodes,
                                      args.num_gpus)
    active = parse_inclusion_exclusion(resource_pool, args.include,
                                       args.exclude)
    if not active:
        raise ValueError("no resources left after include/exclude filters")
    master_addr = args.master_addr or next(iter(active))

    from deepspeed_tpu.launcher.multinode_runner import (
        GCloudRunner, PDSHRunner, SSHRunner)
    runner_cls = {"ssh": SSHRunner, "pdsh": PDSHRunner,
                  "gcloud": GCloudRunner}[args.launcher]
    runner = runner_cls(args, world_info=encode_world_info(active),
                        master_addr=master_addr,
                        master_port=args.master_port)
    env = dict(os.environ)
    runner.ds_env = load_deepspeed_env()
    env.update(runner.ds_env)
    if isinstance(runner, SSHRunner):
        # One connection per host — every node must be spawned, not just
        # rank 0, or the jax.distributed rendezvous waits forever.
        cmds = runner.get_all_cmds(env, active)
    else:
        cmds = [runner.get_cmd(env, active)]
    procs = []
    for cmd in cmds:
        logger.info(f"launcher cmd: {' '.join(map(shlex.quote, cmd))}")
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main() or 0)
