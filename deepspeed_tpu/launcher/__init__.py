from deepspeed_tpu.launcher.runner import (
    fetch_hostfile, parse_inclusion_exclusion, encode_world_info,
    decode_world_info)
