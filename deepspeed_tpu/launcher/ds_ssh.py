"""Broadcast a shell command to every host in the hostfile.

TPU-pod analog of the reference's `bin/ds_ssh` (a pdsh wrapper,
reference bin/ds_ssh:1-24): uses pdsh when available, plain ssh per host
otherwise, and runs locally when no hostfile exists.
Usage: ds_tpu_ssh [-H hostfile] <command...>
"""
import argparse
import shutil
import subprocess
import sys

from deepspeed_tpu.launcher.runner import DLTS_HOSTFILE, fetch_hostfile


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="run a command on every host in the hostfile")
    parser.add_argument("-H", "--hostfile", default=DLTS_HOSTFILE)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    cmd = " ".join(args.command)

    pool = fetch_hostfile(args.hostfile)
    if not pool:
        print(f"Missing hostfile at {args.hostfile}, executing locally")
        return subprocess.call(cmd, shell=True)

    hosts = list(pool)
    if shutil.which("pdsh"):
        return subprocess.call(
            ["pdsh", "-R", "ssh", "-w", ",".join(hosts), cmd])
    rc = 0
    for host in hosts:
        print(f"--- {host} ---", flush=True)
        r = subprocess.call(
            ["ssh", "-o", "StrictHostKeyChecking=no", host, cmd])
        rc = rc or r
    return rc


if __name__ == "__main__":
    sys.exit(main())
