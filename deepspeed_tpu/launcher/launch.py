"""Per-node spawner: one worker process per TPU host.

Analog of the reference's ``launcher/launch.py:23-132`` (the
torch.distributed.launch-alike that spawns one process per GPU with
RANK/LOCAL_RANK env). On TPU, JAX owns every chip on the host, so this
spawns exactly ONE user process per node and provides the
``jax.distributed`` rendezvous env instead:

  DS_TPU_COORDINATOR  host:port of process 0
  DS_TPU_NUM_PROCESSES  total hosts
  DS_TPU_PROCESS_ID     this host's index
  RANK / WORLD_SIZE     kept for user-script compatibility

`deepspeed_tpu.parallel.mesh.initialize_distributed` consumes these.
"""

import argparse
import os
import signal
import subprocess
import sys

from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", type=str, default=None,
                        help="base64 {host: [slots]} (multi-node)")
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--nnodes", type=int, default=-1)
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def build_env(args):
    env = dict(os.environ)
    if args.world_info:
        world = decode_world_info(args.world_info)
        nnodes = len(world)
    else:
        nnodes = max(args.nnodes, 1)
    env["DS_TPU_COORDINATOR"] = f"{args.master_addr}:{args.master_port}"
    env["DS_TPU_NUM_PROCESSES"] = str(nnodes)
    env["DS_TPU_PROCESS_ID"] = str(args.node_rank)
    # Compatibility names (one process per host ⇒ rank == node_rank).
    env["RANK"] = str(args.node_rank)
    env["LOCAL_RANK"] = "0"
    env["WORLD_SIZE"] = str(nnodes)
    env["MASTER_ADDR"] = args.master_addr
    env["MASTER_PORT"] = str(args.master_port)
    return env


def main(args=None):
    args = parse_args(args)
    env = build_env(args)
    cmd = [sys.executable, "-u", args.user_script] + list(args.user_args)
    logger.info(f"launch node_rank={args.node_rank}: {' '.join(cmd)}")
    process = subprocess.Popen(cmd, env=env)

    def forward_signal(signum, frame):
        process.send_signal(signum)

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, forward_signal)
        except ValueError:
            pass  # not in main thread (tests)
    process.wait()
    return process.returncode


if __name__ == "__main__":
    sys.exit(main() or 0)
