"""Expert parallelism inside the compiled pipeline (pipe x expert).

The GSPMD MoE layer (`moe/layer.py`) relies on auto-sharding; the
pipeline body runs inside ``shard_map`` where every mesh axis is manual,
so expert parallelism there must be written with explicit collectives.
This module provides that form (the composition the reference never had —
its MoE postdates v0.3.2, and its pipeline engine is stage-process-based,
`runtime/pipe/engine.py:1-80`):

- expert-banked weights (leaves named ``expert_*``) are sharded over the
  ``expert`` mesh axis by the pipeline's body specs
  (`runtime/pipe/pipeline.py:body_param_specs`): each device holds
  ``E_local = E / ep`` experts;
- tokens stay replicated across the expert axis; each device runs its
  local experts on the dispatch slice it owns and a single ``psum``
  combines expert outputs (the all_to_all-free EP variant — right for
  pipeline microbatches, which are small);
- a gradient-psum on the shared inputs/params makes AD exact: the local
  expert paths produce *partial* cotangents for replicated tensors, and
  ``psum_grad`` sums them across the expert axis during the backward
  (forward is the identity, so compute cost is one collective in bwd).
"""

import jax
import jax.numpy as jnp
from jax import lax
import flax.linen as nn

from deepspeed_tpu.moe.layer import MoEConfig, compute_capacity, top_k_gating
# Canonical home is parallel/collectives.py (shared with the TP pipeline
# layers); re-exported here for back-compat with round-3 imports.
from deepspeed_tpu.parallel.collectives import (  # noqa: F401
    axis_is_manual, matmul_psum_overlap, overlap_plan, psum_combine,
    psum_grad)


class ExpertParallelFFNLayer:
    """Pipeline body layer: pre-LN MoE FFN block, manual expert parallel.

    Param leaves:
      ``ln_scale/ln_bias`` [M]        replicated
      ``gate``             [M, E]     replicated (grad psum'd over expert)
      ``expert_w1/b1/w2/b2`` [E, ...] sharded over ``expert`` by the body
                                      specs; this layer sees E_local
    Must run inside the pipeline's ``shard_map`` on a mesh with an
    ``expert`` axis (size may be 1).

    Activations may be a plain [B, S, M] array or a ``(hidden, aux)``
    tuple: in tuple form the Switch load-balancing loss accumulates into
    ``aux`` (weighted by ``MoEConfig.aux_loss_weight``) and rides the
    pipeline to the loss (prologue emits ``(h, 0.0)``; the loss adds the
    scalar — see ``test_expert_pipe.py`` for the module shape).
    """

    def __init__(self, d_model, hidden_dim, moe: MoEConfig = None,
                 axis_name="expert"):
        self.d_model = d_model
        self.hidden_dim = hidden_dim
        self.moe = moe or MoEConfig()
        self.axis_name = axis_name

    def init(self, rng, x):
        M, H, E = self.d_model, self.hidden_dim, self.moe.num_experts
        ks = jax.random.split(rng, 3)
        init = nn.initializers.normal(0.02)
        return {
            "ln_scale": jnp.ones((M,), jnp.float32),
            "ln_bias": jnp.zeros((M,), jnp.float32),
            "gate": init(ks[0], (M, E), jnp.float32),
            "expert_w1": init(ks[1], (E, M, H), jnp.float32),
            "expert_b1": jnp.zeros((E, H), jnp.float32),
            "expert_w2": init(ks[2], (E, H, M), jnp.float32),
            "expert_b2": jnp.zeros((E, M), jnp.float32),
        }

    def apply(self, params, x, rng=None):
        # Tuple activations carry the Switch load-balancing aux loss
        # through the pipeline: layers take/return (hidden, aux_scalar)
        # and the module's epilogue/loss adds it (the pipeline's
        # activation pytrees ppermute transparently). Plain-array x skips
        # the aux entirely.
        aux_in = None
        if isinstance(x, tuple):
            x, aux_in = x
        ax = self.axis_name
        cfg = self.moe
        e_loc = params["expert_w1"].shape[0]     # E / ep after sharding
        dtype = x.dtype

        mean = x.mean(-1, keepdims=True)
        var = ((x - mean) ** 2).mean(-1, keepdims=True)
        h = (x - mean) * jax.lax.rsqrt(var + 1e-5)
        h = (h * params["ln_scale"] + params["ln_bias"]).astype(dtype)

        # Outside the pipeline's shard_map (build-time shape inference,
        # the sequential test oracle) the layer runs the full bank
        # replicated, no collectives. The pipeline declares its mesh axes
        # manual via parallel.collectives.manual_axes — an explicit flag,
        # not the round-3 NameError probe.
        bound = axis_is_manual(ax)
        rank = lax.axis_index(ax) if bound else 0
        plan = overlap_plan("expert_combine") if bound else None
        if plan is not None and plan.chunks <= 1:
            plan = None

        gate = params["gate"]
        if bound:
            # Partial cotangents from the local-expert paths below must
            # sum across the expert axis; the residual path outside stays
            # untouched. Under an overlap plan the backward all-reduces
            # become chunked ppermute rings.
            if plan is not None:
                h = psum_grad(h, ax, chunks=plan.chunks,
                              bidirectional=plan.bidirectional)
                gate = psum_grad(gate, ax, chunks=plan.chunks,
                                 bidirectional=plan.bidirectional)
            else:
                h = psum_grad(h, ax)
                gate = psum_grad(gate, ax)

        C = compute_capacity(x.shape[1], cfg, deterministic=rng is None)
        logits = h.astype(jnp.float32) @ gate
        dispatch, combine, aux = top_k_gating(logits, cfg.top_k, C)

        # Slice this rank's experts out of the (replicated) routing tensors.
        off = rank * e_loc
        disp_l = lax.dynamic_slice_in_dim(dispatch.astype(dtype), off,
                                          e_loc, axis=2)
        comb_l = lax.dynamic_slice_in_dim(combine.astype(dtype), off,
                                          e_loc, axis=2)

        w1 = params["expert_w1"].astype(dtype)
        w2 = params["expert_w2"].astype(dtype)
        b1 = params["expert_b1"].astype(dtype)
        b2 = params["expert_b2"].astype(dtype)

        de = jnp.einsum("bsec,bsm->becm", disp_l, h)
        hh = jax.nn.gelu(jnp.einsum("becm,emh->bech", de, w1) +
                         b1[None, :, None])
        eo = jnp.einsum("bech,ehm->becm", hh, w2) + b2[None, :, None]
        if plan is not None:
            # The combine einsum is a batched matmul over the flattened
            # (e_loc, C) contraction; matmul_psum_overlap fuses it with
            # the cross-expert reduction as chunked ppermute rings
            # overlapping the per-chunk matmuls.
            B_, S_, _, C_ = comb_l.shape
            M_ = eo.shape[-1]
            y = matmul_psum_overlap(
                comb_l.reshape(B_, S_, e_loc * C_),
                eo.reshape(B_, e_loc * C_, M_), ax,
                chunks=plan.chunks, bidirectional=plan.bidirectional)
        else:
            y = jnp.einsum("bsec,becm->bsm", comb_l, eo)
            if bound:
                y = psum_combine(y, ax)          # combine across experts
        out = x + y.astype(x.dtype)
        if aux_in is None:
            return out
        if bound:
            # The aux is computed from the FULL (replicated) routing
            # tensors, so each expert rank's backward already carries the
            # complete aux gradient — but it flows into the psum_grad'd
            # h/gate, which sums cotangents across ranks. Pre-scale the
            # differentiable path by 1/ep (value restored via
            # stop_gradient) so psum_grad's sum lands at exactly 1x.
            n = lax.psum(1, ax)
            aux = aux / n + lax.stop_gradient(aux * (1.0 - 1.0 / n))
        return out, aux_in + cfg.aux_loss_weight * aux
