from deepspeed_tpu.moe.layer import (MoE, MoEConfig, compute_capacity,
                                     moe_param_spec, top_k_gating)

__all__ = ["MoE", "MoEConfig", "compute_capacity", "moe_param_spec",
           "top_k_gating"]
