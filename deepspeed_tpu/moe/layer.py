"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

The reference at v0.3.2 has no MoE (DeepSpeed-MoE arrived later); this
module provides the capability TPU-first so the framework's 5-axis mesh
(``data/model/pipe/seq/expert``, `parallel/mesh.py`) is fully usable:

- GShard/Switch-style static-shape dispatch: top-k routing with a fixed
  per-expert capacity, expressed as one-hot dispatch/combine einsums so
  every op is a dense MXU matmul (no gather/scatter, no dynamic shapes
  under jit);
- expert parallelism = sharding the expert-banked weights ``[E, ...]`` and
  the dispatched activations ``[B, E, C, M]`` over the ``expert`` axis —
  GSPMD inserts the all_to_all that hand-written MoE frameworks code
  explicitly;
- Switch-transformer load-balancing auxiliary loss.

Shapes: tokens [B, S, M], E experts, capacity C = ceil(k * S * cf / E).
"""

import dataclasses
import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2                   # 1 = Switch, 2 = GShard
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.float32


def compute_capacity(seq_len, cfg: MoEConfig, deterministic):
    cf = cfg.eval_capacity_factor if deterministic else cfg.capacity_factor
    cap = max(cfg.min_capacity,
              int(math.ceil(cfg.top_k * seq_len * cf / cfg.num_experts)))
    return min(cap, seq_len)


def top_k_gating(logits, top_k, capacity):
    """Static-shape top-k routing.

    ``logits`` [B, S, E] → (dispatch [B, S, E, C] one-hot, combine
    [B, S, E, C] gate-weighted, aux_loss scalar). Tokens over capacity are
    dropped (their combine weight is zero) — Switch/GShard semantics.
    """
    B, S, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    dispatch = jnp.zeros((B, S, E), jnp.float32)
    gates = jnp.zeros((B, S, E), jnp.float32)
    masked = probs
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)                     # [B, S]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        dispatch = dispatch + onehot
        gates = gates + probs * onehot
        masked = masked * (1.0 - onehot)

    if top_k > 1:
        # GShard top-2: renormalize over the *selected* experts BEFORE
        # capacity dropping, so a token whose first choice overflows still
        # routes through its second choice with the proportional weight
        # (not an inflated 1.0) — the dropped mass is lost, as in GShard.
        # Deliberate divergence from the reference's top2gating, which
        # renormalizes AFTER the capacity mask (second-choice gate becomes
        # 1.0 on overflow); curves differ under overflow (COVERAGE.md).
        denom = gates.sum(-1, keepdims=True)
        gates = gates / jnp.maximum(denom, 1e-9)
    # top_k == 1 keeps the raw router probability (Switch): scaling the
    # expert output by it is what routes task-loss gradient into the gate.

    # Position of each token within its expert's queue (per batch row,
    # sequence order — the deterministic tie-break the papers use).
    position_in_expert = (jnp.cumsum(dispatch, axis=1) - 1.0) * dispatch
    within_capacity = (position_in_expert < capacity) * dispatch
    gates = gates * within_capacity

    pos = jax.nn.one_hot(position_in_expert.astype(jnp.int32), capacity,
                         dtype=jnp.float32) * within_capacity[..., None]
    dispatch_tensor = pos                                    # [B,S,E,C]
    combine_tensor = gates[..., None] * pos                  # [B,S,E,C]

    # Switch aux loss: E * Σ_e fraction_dispatched_e * mean_prob_e
    # (computed on the pre-capacity top-1 assignment).
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), E, dtype=jnp.float32)
    fraction = top1.mean(axis=(0, 1))
    mean_prob = probs.mean(axis=(0, 1))
    aux_loss = E * jnp.sum(fraction * mean_prob)
    return dispatch_tensor, combine_tensor, aux_loss


class MoE(nn.Module):
    """Expert-parallel MoE FFN block.

    ``__call__(x, deterministic)`` with x [B, S, M] → (y [B, S, M],
    aux_loss). Expert weights are banked on a leading E dim; shard it over
    the ``expert`` axis with :func:`moe_partition_specs`.
    """

    config: MoEConfig
    hidden_dim: int              # expert FFN hidden size
    activation: Callable = jax.nn.gelu

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        B, S, M = x.shape
        E = cfg.num_experts
        C = compute_capacity(S, cfg, deterministic)
        dtype = cfg.dtype

        wg = self.param("gate", nn.initializers.normal(0.02), (M, E))
        w1 = self.param("expert_w1", nn.initializers.normal(0.02),
                        (E, M, self.hidden_dim))
        b1 = self.param("expert_b1", nn.initializers.zeros,
                        (E, self.hidden_dim))
        w2 = self.param("expert_w2", nn.initializers.normal(0.02),
                        (E, self.hidden_dim, M))
        b2 = self.param("expert_b2", nn.initializers.zeros, (E, M))

        logits = x.astype(jnp.float32) @ wg
        dispatch, combine, aux = top_k_gating(logits, cfg.top_k, C)
        dispatch = dispatch.astype(dtype)
        combine = combine.astype(dtype)
        xc = x.astype(dtype)

        # Token dispatch / expert FFN / combine — all dense einsums. With
        # w*/[B,E,C,M] sharded over ``expert``, GSPMD lowers the transitions
        # to all_to_all over the expert axis.
        de = jnp.einsum("bsec,bsm->becm", dispatch, xc)
        h = self.activation(
            jnp.einsum("becm,emh->bech", de, w1.astype(dtype)) +
            b1.astype(dtype)[None, :, None])
        eo = jnp.einsum("bech,ehm->becm", h, w2.astype(dtype)) + \
            b2.astype(dtype)[None, :, None]
        y = jnp.einsum("bsec,becm->bsm", combine, eo)
        return y.astype(x.dtype), cfg.aux_loss_weight * aux


def moe_param_spec(name, leaf, expert_axis="expert", model_axis=None):
    """PartitionSpec for one MoE param leaf (by reference-free naming
    convention: 'gate', 'expert_*')."""
    ndim = getattr(leaf, "ndim", 0)
    if name.startswith("expert_") and ndim >= 2:
        # Bank dim over the expert axis; optionally shard the FFN hidden
        # dim over model too (expert + tensor parallel compose).
        spec = [expert_axis] + [None] * (ndim - 1)
        if model_axis is not None and ndim == 3:
            spec[2 if name.endswith("w1") else 1] = model_axis
        return P(*spec)
    if name.startswith("expert_") and ndim >= 1:
        return P(expert_axis)
    return P()
