"""Flight recorder: an always-on black box for "why did the run stop".

The telemetry layer answers "how fast was the run"; this module answers
the question the repo's own history keeps asking — two real
collective-rendezvous deadlocks (caught only statically), a week of
silent TPU-tunnel stalls, guards that see bad *values* but not absent
*progress*. The :class:`FlightRecorder` keeps a bounded in-memory ring
of

- recent telemetry **events** (it plugs into the session's exporter
  fan-out, so it sees exactly what the JSONL log sees),
- **phase-span transitions** (enter/exit of every ``session.span``
  scope, fed by `telemetry/spans.py`), and
- the compiled step's **collective confessions**
  (`parallel/collectives.py:SiteRecord` — which sites emitted which
  rings, captured at trace time),

and on demand dumps all of it — plus ``faulthandler``-style stacks of
every live Python thread and the per-thread in-flight span path —
atomically (tmp + rename, the resilience-checkpoint contract) to a
crash-dump directory. ``ds_tpu_metrics postmortem <dump>`` renders a
dump; `telemetry/watchdog.py` fires one on hangs.

Dumps are triggered by (see :func:`install_crash_hooks`):

- an **unhandled exception** (chained ``sys.excepthook``),
- **SIGTERM** (dump first, then the chained preemption handler runs) and
  **SIGQUIT** (dump + thread stacks on stderr; the process keeps
  running — the operator's "where is it stuck" signal),
- a **health-guard abort** (the engine dumps before raising), and
- the **hang watchdog** expiring.

Everything here is exception-contained: forensics must never be the
thing that kills the run.
"""

import json
import os
import signal
import sys
import threading
import time
import traceback
import collections

from deepspeed_tpu.telemetry.spans import live_phase_paths
from deepspeed_tpu.utils.logging import logger

FLIGHT_SCHEMA = "ds-tpu-flight/1"


def thread_stacks():
    """``faulthandler``-style stacks of all live Python threads, as
    structured data: ``[{name, ident, daemon, stack: [lines...]}]``."""
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        t = names.get(ident)
        out.append({
            "name": t.name if t is not None else f"thread-{ident}",
            "ident": ident,
            "daemon": bool(t.daemon) if t is not None else None,
            "stack": [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)],
        })
    return out


class FlightRecorder:
    """Bounded black-box ring + atomic crash dumps.

    Implements the exporter protocol (``export``/``close``) so a
    :class:`~deepspeed_tpu.telemetry.events.EventLog` fans events into
    the ring exactly like any other exporter; phase transitions and
    collective confessions arrive through the session hooks.
    """

    def __init__(self, dump_dir, history=512, meta=None):
        self.dump_dir = str(dump_dir)
        self.meta = dict(meta or {})
        self._events = collections.deque(maxlen=int(history))
        self._phases = collections.deque(maxlen=int(history))
        self._collectives = []
        self._lock = threading.Lock()
        self._dumps = 0

    # -- exporter protocol (events fan in) ----------------------------
    def export(self, event):
        with self._lock:
            self._events.append(dict(event))

    def close(self):
        pass

    # -- session hooks -------------------------------------------------
    def record_phase(self, kind, path, duration_s=None):
        """One span transition: ``kind`` is ``"enter"`` or ``"exit"``."""
        rec = {"t": time.time(), "kind": kind, "path": path}
        if duration_s is not None:
            rec["duration_s"] = round(duration_s, 6)
        with self._lock:
            self._phases.append(rec)

    def record_collectives(self, records):
        """Stamp the step's trace-time :class:`SiteRecord` confessions
        (the last recorded set wins — one compiled step, one set)."""
        rows = []
        for r in records:
            if isinstance(r, dict):
                rows.append(dict(r))
            else:
                rows.append({"site": r.site, "axis": r.axis,
                             "primitive": r.primitive, "chunks": r.chunks,
                             "hops": r.hops, "chained": r.chained})
        with self._lock:
            if rows or not self._collectives:
                self._collectives = rows

    # -- dumping -------------------------------------------------------
    def snapshot(self, reason, extra=None):
        """The dump payload as a dict (no I/O)."""
        with self._lock:
            events = list(self._events)
            phases = list(self._phases)
            collectives = list(self._collectives)
        names = {t.ident: t.name for t in threading.enumerate()}
        in_flight = {names.get(ident, f"thread-{ident}"): path
                     for ident, path in live_phase_paths().items()}
        snap = {
            "schema": FLIGHT_SCHEMA,
            "reason": reason,
            "t": time.time(),
            "pid": os.getpid(),
            "meta": dict(self.meta),
            "in_flight_phases": in_flight,
            "threads": thread_stacks(),
            "events": events,
            "phase_log": phases,
            "collectives": collectives,
        }
        if extra:
            snap.update(extra)
        return snap

    def dump(self, reason, extra=None):
        """Atomically write one dump file; returns its path (or None —
        a failing dump logs one warning and never raises)."""
        try:
            snap = self.snapshot(reason, extra=extra)
            os.makedirs(self.dump_dir, exist_ok=True)
            with self._lock:
                self._dumps += 1
                seq = self._dumps
            tag = str(reason).replace(":", "-").replace("/", "-")
            pidx = self.meta.get("process_index", 0)
            name = (f"flight-p{int(pidx):05d}-{tag}-"
                    f"{int(snap['t'] * 1000)}-{seq}.json")
            path = os.path.join(self.dump_dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            logger.warning("flight recorder: dumped %s record to %s",
                           reason, path)
            return path
        except Exception as e:   # pragma: no cover - disk-full etc.
            logger.warning("flight recorder: dump failed (%s)", e)
            return None

    # -- crash hooks ---------------------------------------------------
    def install(self, signals=(signal.SIGTERM, getattr(signal, "SIGQUIT",
                                                       None))):
        install_crash_hooks(self, signals=signals)
        return self

    def uninstall(self):
        uninstall_crash_hooks(self)


def read_dump(path):
    """Parse + validate one flight-recorder dump (the ``postmortem``
    CLI's loader). Raises ``ValueError`` on a non-dump JSON file."""
    with open(path) as f:
        dump = json.load(f)
    if not isinstance(dump, dict) or \
            dump.get("schema") != FLIGHT_SCHEMA:
        raise ValueError(
            f"{path} is not a flight-recorder dump "
            f"(expected schema {FLIGHT_SCHEMA!r}, "
            f"got {dump.get('schema') if isinstance(dump, dict) else dump!r})")
    return dump


# ---------------------------------------------------------------------------
# process-level crash hooks (one set per process; re-install swaps the
# target recorder, so tests / multiple engines never stack handlers)
# ---------------------------------------------------------------------------

_hooks = {"recorder": None, "excepthook": None, "signals": {}}


def _on_unhandled(exc_type, exc, tb):
    rec = _hooks["recorder"]
    if rec is not None:
        try:
            rec.dump("exception", extra={"exception": {
                "type": exc_type.__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(exc_type, exc, tb),
            }})
        except Exception:   # pragma: no cover
            pass
    prev = _hooks["excepthook"]
    (prev or sys.__excepthook__)(exc_type, exc, tb)


def _on_signal(signum, frame):
    rec = _hooks["recorder"]
    name = signal.Signals(signum).name
    if rec is not None:
        try:
            rec.dump(f"signal:{name}")
        except Exception:   # pragma: no cover
            pass
    prev = _hooks["signals"].get(signum, (None,))[0]
    if signum == getattr(signal, "SIGQUIT", None):
        # Operator "where is it stuck" signal: stacks on stderr too
        # (the satellite faulthandler registration prints the same when
        # no recorder is installed), then keep running.
        try:
            import faulthandler
            faulthandler.dump_traceback(file=sys.stderr)
        except Exception:   # pragma: no cover
            pass
        if callable(prev):
            prev(signum, frame)
        return
    if callable(prev):
        prev(signum, frame)     # e.g. the preemption latch
    elif prev == signal.SIG_DFL:
        # restore + re-deliver so default semantics (terminate) hold
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_crash_hooks(recorder, signals=(signal.SIGTERM,
                                           getattr(signal, "SIGQUIT",
                                                   None))):
    """Point the process crash hooks at ``recorder``. First call chains
    ``sys.excepthook`` and the given signals; later calls only swap the
    recorder (handlers never stack). Off the main thread, signal
    chaining degrades to excepthook-only (CPython restriction)."""
    _hooks["recorder"] = recorder
    if _hooks["excepthook"] is None and sys.excepthook is not _on_unhandled:
        _hooks["excepthook"] = sys.excepthook
        sys.excepthook = _on_unhandled
    if threading.current_thread() is not threading.main_thread():
        return
    for sig in signals:
        if sig is None or sig in _hooks["signals"]:
            continue
        try:
            prev = signal.signal(sig, _on_signal)
        except (ValueError, OSError):   # pragma: no cover - exotic envs
            continue
        _hooks["signals"][sig] = (prev,)


def uninstall_crash_hooks(recorder=None):
    """Restore the chained hooks (tests). A no-op when ``recorder`` is
    given and is not the installed one."""
    if recorder is not None and _hooks["recorder"] is not recorder:
        return
    _hooks["recorder"] = None
    if _hooks["excepthook"] is not None:
        sys.excepthook = _hooks["excepthook"]
        _hooks["excepthook"] = None
    if threading.current_thread() is threading.main_thread():
        for sig, (prev,) in list(_hooks["signals"].items()):
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):   # pragma: no cover
                pass
            _hooks["signals"].pop(sig, None)
