"""Pluggable telemetry exporters: JSONL file, console table, Prometheus
textfile.

All three are push-style (``export(event)`` per event) plus ``close()``
for final flushes. The matrix:

==================  =========================  =======================
exporter            carries                    consumer
==================  =========================  =======================
JsonlExporter       every event, verbatim      ``ds_tpu_metrics``,
                                               offline analysis
ConsoleExporter     one compact line/event     humans tailing a run
PrometheusTextfile  registry snapshot           node_exporter textfile
Exporter            (metrics, not events)       collector / scrapers
==================  =========================  =======================

The Prometheus exporter is event-*triggered* but registry-*sourced*: it
rewrites the textfile atomically (tmp + rename, the collector contract)
every ``write_every`` events and on close.
"""

import atexit
import json
import os
import sys

# Events worth an fsync: the ones a postmortem needs to out-survive the
# process that wrote them. Everything else gets flush-per-line only.
DURABLE_EVENTS = frozenset({
    "run_start", "health_guard", "recompile", "preemption", "watchdog",
    "anomaly", "restart", "recovery_ladder", "checkpoint_fallback",
    # serving fleet (ISSUE 17): replica deaths and aborted requests are
    # exactly the events a post-incident aggregate must not lose
    "replica_dead", "request_aborted", "scheduler_incomplete",
})


class JsonlExporter:
    """Append one JSON line per event; flushed per write so ``tail -f``
    and a mid-run ``ds_tpu_metrics summary`` always see whole lines.

    The tail of a crashed run must not die in buffers: the first open
    registers an atexit close, and :data:`DURABLE_EVENTS` (guard trips,
    recompiles, preemption, watchdog/anomaly firings) additionally
    ``fsync`` so they reach disk even if the process is killed next."""

    def __init__(self, path):
        self.path = str(path)
        self._f = None

    def export(self, event):
        if self._f is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._f = open(self.path, "a")
            atexit.register(self.close)
        self._f.write(json.dumps(event, default=str) + "\n")
        self._f.flush()
        if event.get("event") in DURABLE_EVENTS:
            os.fsync(self._f.fileno())

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None


class ConsoleExporter:
    """One aligned ``[telemetry]`` line per event (scalars only —
    nested payloads like the step's phase dict are summarized)."""

    def __init__(self, stream=None, events=None):
        self.stream = stream
        self.events = set(events) if events else None

    def export(self, event):
        kind = event.get("event", "?")
        if self.events is not None and kind not in self.events:
            return
        out = self.stream or sys.stderr
        parts = []
        for k, v in event.items():
            if k in ("schema", "event", "t"):
                continue
            if isinstance(v, float):
                parts.append(f"{k}={v:.6g}")
            elif isinstance(v, (str, int, bool)) or v is None:
                parts.append(f"{k}={v}")
            elif isinstance(v, dict) and all(
                    isinstance(x, (int, float)) for x in v.values()):
                body = " ".join(f"{kk}={vv:.4g}" if isinstance(vv, float)
                                else f"{kk}={vv}"
                                for kk, vv in v.items())
                parts.append(f"{k}=[{body}]")
            else:
                parts.append(f"{k}=...")
        print(f"[telemetry] {kind:<16s} " + " ".join(parts), file=out)

    def close(self):
        pass


class PrometheusTextfileExporter:
    """Write ``registry.to_prometheus()`` to ``path`` atomically every
    ``write_every`` events (and on close). Point a node_exporter
    ``--collector.textfile.directory`` at the parent dir."""

    def __init__(self, path, registry, write_every=20):
        self.path = str(path)
        self.registry = registry
        self.write_every = max(1, int(write_every))
        self._n = 0

    def export(self, event):
        self._n += 1
        if self._n % self.write_every == 0:
            self.write()

    def write(self):
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.registry.to_prometheus())
        os.replace(tmp, self.path)

    def close(self):
        self.write()
