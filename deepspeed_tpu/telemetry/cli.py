"""`ds_tpu_metrics`: tail / summarize / diff / aggregate telemetry
JSONL logs, and render flight-recorder postmortems.

Five subcommands over the schema-versioned event log a run writes when
``telemetry.jsonl_path`` is set (`telemetry/events.py`):

- ``ds_tpu_metrics summary LOG`` — step count, wall time, step-time
  stats (mean/p50/p95), per-phase breakdown with shares, tokens/sec,
  and an MFU estimate (the ANALYSIS_MFU.md accounting: achieved TFLOPS
  = tokens/sec x flops/token; MFU = achieved / peak, default peak 197
  TFLOPS — one v5e chip's bf16 ceiling), plus recompile / health-guard /
  checkpoint event counts.
  Serving logs (``decode_step`` events from the continuous-batching
  scheduler, `inference/scheduler.py`) get a serve-mode summary
  instead: tokens/sec, per-token latency p50/p95/p99 (each token's
  latency is its decode step's host wall), mean batch occupancy, and
  queue depth. Fleet logs (router events from `inference/router.py`)
  add a fleet block: requests/completions by reason, replica deaths by
  cause, redispatches, aborts, shed/defer backpressure, and
  per-request latency percentiles.
- ``ds_tpu_metrics tail LOG -n 20`` — the last N events, one line each.
- ``ds_tpu_metrics diff A B`` — per-metric regression table between two
  runs; ``--fail-over PCT`` exits 1 when mean step time regressed more.
- ``ds_tpu_metrics aggregate LOG...`` — merge per-host logs of ONE run
  (events carry ``process_index``/``hostname``), print the per-step
  cross-host skew table and the straggler ranking (mean wall excess
  over the fastest host at each shared step). Serving-fleet logs (one
  per replica, plus the router's) aggregate into per-replica decode
  throughput rows and the merged fleet block instead. A torn heartbeat
  file (a replica killed mid-``os.replace``) gets one bounded re-read
  retry before being reported as no-heartbeat.
- ``ds_tpu_metrics postmortem DUMP`` — render a flight-recorder crash
  dump (`telemetry/flight.py`): what fired, the watchdog's verdict,
  every thread's in-flight phase path and stack, the last collective
  confessions, and the event-timeline tail.

Exit codes: 0 ok, 1 no step events (summary) / regression past
``--fail-over`` (diff) / no overlapping steps (aggregate), 2 usage
errors / unreadable files.

flops/token resolution for MFU (first hit wins): ``--flops-per-token``
flag > the run's ``compile`` event > its ``run_start`` event. Without
any, the summary reports throughput but skips MFU.
"""

import argparse
import json
import os
import sys

from deepspeed_tpu.telemetry.events import SCHEMA_VERSION

# One v5e chip's bf16 peak (ANALYSIS_MFU.md) — override per target chip.
DEFAULT_PEAK_TFLOPS = 197.0


def read_events(path):
    """Parse a JSONL log, skipping blank/corrupt lines (a live run may
    be mid-write on the last line)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                evt = json.loads(line)
            except ValueError:
                continue
            if isinstance(evt, dict):
                events.append(evt)
    return events


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _resolve_flops_per_token(events, flops_per_token=None):
    if flops_per_token:
        return float(flops_per_token)
    for kind in ("compile", "run_start"):
        for evt in events:
            if evt.get("event") == kind and evt.get("flops_per_token"):
                return float(evt["flops_per_token"])
    return None


def _wire_bytes_per_step(events):
    """Per-step collective wire accounting from the run's ``compile``
    event (`collective_bytes_by_dtype`): total bytes moved by collectives
    per step, and how many of them travel in 1-byte quantized form
    (u8/s8/f8 element dtypes — the int8/fp8 wire codecs)."""
    for evt in reversed(events):
        bd = evt.get("collective_bytes_by_dtype") \
            if evt.get("event") == "compile" else None
        if not bd:
            continue
        total = wire = 0
        for op, per_dtype in bd.items():
            if not isinstance(per_dtype, dict):   # the "total" rollup
                continue
            for dt, b in per_dtype.items():
                total += int(b)
                if dt in ("u8", "s8") or dt.startswith("f8"):
                    wire += int(b)
        return {"total_bytes": total, "quantized_bytes": wire,
                "quantized_share": (wire / total) if total else 0.0}
    return None


def _kernel_summary(events):
    """Pallas kernel facts from the latest ``compile`` event carrying
    the sub-``pallas_call`` analysis (`analysis/kernels.py`
    ``KernelAnalysis.to_dict`` form, stamped by the engine's compile
    audit or a ``--kernels`` serve audit): per-kernel VMEM working set
    and elided-DMA fraction, plus the VMEM high-water across kernels
    and the byte-weighted elision rollup."""
    for evt in reversed(events):
        ks = evt.get("kernels") if evt.get("event") == "compile" else None
        if not ks or not ks.get("kernels"):
            continue
        per = {
            name: {"vmem_bytes": int(kd.get("vmem_bytes") or 0),
                   "elided_dma_fraction": kd.get("elided_dma_fraction")}
            for name, kd in ks["kernels"].items()}
        dense = int(ks.get("dense_bytes") or 0)
        dma = int(ks.get("dma_bytes") or 0)
        return {
            "per_kernel": per,
            "vmem_high_water_bytes": max(
                (k["vmem_bytes"] for k in per.values()), default=0),
            "vmem_budget_bytes": int(ks.get("vmem_budget_bytes") or 0),
            "elided_dma_fraction": (1.0 - dma / dense) if dense else None,
            "expected_elision": ks.get("expected_elision"),
        }
    return None


def _summarize_fleet(events):
    """Fleet block: router-level serving events (`inference/router.py`
    — replica deaths, drains/redispatches, aborts, shed/defer
    backpressure, per-request latency). None when the log carries no
    fleet events at all."""
    kinds = {}
    for e in events:
        kinds.setdefault(e.get("event"), []).append(e)
    done = (kinds.get("fleet_done") or [None])[-1] or {}
    completes = kinds.get("request_complete", [])
    deaths = kinds.get("replica_dead", [])
    if not done and not (completes or deaths or
                         kinds.get("fleet_redispatch")):
        return None
    lat = sorted(float(e["latency_s"]) for e in completes
                 if e.get("latency_s") is not None)
    reasons = {}
    for e in completes:
        r = e.get("finish_reason", "?")
        reasons[r] = reasons.get(r, 0) + 1
    causes = {}
    for e in deaths:
        c = e.get("cause", "?")
        causes[c] = causes.get(c, 0) + 1
    recover = [float(e["time_to_recover_s"])
               for e in kinds.get("replica_recovered", [])
               if e.get("time_to_recover_s")]
    return {
        "requests": done.get("requests", len(completes)),
        "completions": len(completes) or done.get("completions", 0),
        "finish_reasons": reasons,
        "replicas": done.get("replicas"),
        "replicas_dead": {
            "count": len(deaths) or done.get("replicas_dead", 0),
            "by_cause": causes,
        },
        "redispatched": len(kinds.get("fleet_redispatch", ()))
        or done.get("redispatched_total", 0),
        "aborted": len(kinds.get("request_aborted", ()))
        or done.get("aborted", 0),
        "shed": len(kinds.get("fleet_shed", ())) or done.get("shed", 0),
        "defers": len(kinds.get("fleet_defer", ()))
        or done.get("defers", 0),
        "timeouts": len(kinds.get("request_timeout", ()))
        or done.get("timeouts", 0),
        "request_latency_s": {
            "p50": _percentile(lat, 0.50),
            "p95": _percentile(lat, 0.95),
            "p99": _percentile(lat, 0.99),
            "max": lat[-1] if lat else None,
        },
        "mean_time_to_recover_s": (sum(recover) / len(recover))
        if recover else None,
        "ok": done.get("ok"),
    }


def _summarize_disagg(events):
    """Disaggregated-tier block: per-tier rows (prefill vs decode)
    with the TTFT split and queue waits by tier, plus the handoff
    ledger. Reads the router's ``request_prefilled`` /
    ``request_complete`` / ``disagg_done`` events and the tier
    workers' ``prefill_step`` / ``decode_step`` events — a merged
    ``aggregate`` over router + per-tier worker JSONLs sees both
    sides; a single worker log still gets its own tier's row. None
    when the log carries no disaggregation events at all."""
    kinds = {}
    for e in events:
        kinds.setdefault(e.get("event"), []).append(e)
    done = (kinds.get("disagg_done") or [None])[-1] or {}
    prefilled = kinds.get("request_prefilled", [])
    if not done and not prefilled and not (
            kinds.get("prefill_step") or kinds.get("disagg_reprefill")):
        return None
    completes = kinds.get("request_complete", [])

    def _pct(vals):
        vals = sorted(vals)
        return {"p50": _percentile(vals, 0.50),
                "p95": _percentile(vals, 0.95),
                "p99": _percentile(vals, 0.99),
                "max": vals[-1] if vals else None}

    # TTFT: stamped on request_prefilled as the token leaves the
    # prefill tier; completion records echo it for single-log reads
    ttft = [float(e["ttft_s"]) for e in prefilled
            if e.get("ttft_s") is not None]
    if not ttft:
        ttft = [float(e["ttft_s"]) for e in completes
                if e.get("ttft_s") is not None]
    qw_prefill = [float(e["queue_wait_s"]) for e in prefilled
                  if e.get("queue_wait_s") is not None]
    qw_decode = [float(e["decode_queue_wait_s"]) for e in completes
                 if e.get("decode_queue_wait_s") is not None]
    by_tier = {}
    for kind in ("fleet_dispatch", "fleet_redispatch"):
        for e in kinds.get(kind, ()):
            t = e.get("tier")
            if t:
                row = by_tier.setdefault(
                    t, {"dispatched": 0, "redispatched": 0})
                row["dispatched" if kind == "fleet_dispatch"
                    else "redispatched"] += 1
    pre_steps = kinds.get("prefill_step", [])
    dec_steps = kinds.get("decode_step", [])
    pre_wall = [float(e["wall_s"]) for e in pre_steps
                if e.get("wall_s") is not None]
    dec_wall = [float(e["wall_s"]) for e in dec_steps
                if e.get("wall_s") is not None]
    handoffs = done.get("handoffs", len(prefilled))
    handoff_bytes = done.get("handoff_bytes", sum(
        int(e.get("handoff_bytes") or 0) for e in prefilled))
    return {
        "tiers": {
            "prefill": {
                "steps": len(pre_steps),
                "step_s": _pct(pre_wall),
                "wall_s": sum(pre_wall),
                "dispatched": by_tier.get("prefill", {}).get(
                    "dispatched", 0),
                "redispatched": by_tier.get("prefill", {}).get(
                    "redispatched", 0),
                "queue_wait_s": _pct(qw_prefill),
            },
            "decode": {
                "steps": len(dec_steps),
                "step_s": _pct(dec_wall),
                "wall_s": sum(dec_wall),
                "dispatched": by_tier.get("decode", {}).get(
                    "dispatched", 0),
                "redispatched": by_tier.get("decode", {}).get(
                    "redispatched", 0),
                "queue_wait_s": _pct(qw_decode),
            },
        },
        "ttft_s": _pct(ttft),
        "handoffs": handoffs,
        "handoff_bytes": handoff_bytes,
        "handoff_bytes_per_session": (handoff_bytes / handoffs)
        if handoffs else None,
        "handoff_corrupt": done.get(
            "handoff_corrupt", len(kinds.get("handoff_corrupt", ()))),
        "reprefills": len(kinds.get("disagg_reprefill", ()))
        or done.get("redispatched_total", 0),
        "resumed_from_park": done.get("resumed_from_park", 0),
        "dead_by_tier": done.get("dead_by_tier") or {},
        "ok": done.get("ok"),
    }


def summarize(events, flops_per_token=None, peak_tflops=DEFAULT_PEAK_TFLOPS):
    """Aggregate a run's events into the summary dict. None when the
    log holds neither step events nor resilience events (a supervisor's
    log is all restarts and recoveries — still worth a summary)."""
    steps = [e for e in events if e.get("event") == "step"]
    decode = [e for e in events if e.get("event") == "decode_step"]
    fleet = _summarize_fleet(events)
    disagg = _summarize_disagg(events)
    if not steps and (decode or fleet or disagg):
        serve = _summarize_serve(decode, fleet=fleet)
        if serve is not None:
            serve["kernels"] = _kernel_summary(events)
            serve["disagg"] = disagg
        return serve
    if not steps and not any(
            e.get("event") in ("restart", "recovery_ladder",
                               "checkpoint_fallback", "supervisor_done")
            for e in events):
        return None
    walls = sorted(float(e["wall_s"]) for e in steps
                   if e.get("wall_s") is not None)
    total_s = sum(walls)
    phases = {}
    for evt in steps:
        for name, secs in (evt.get("phases") or {}).items():
            phases.setdefault(name, []).append(float(secs))
    phase_stats = {
        name: {"total_s": sum(vals),
               "mean_s": sum(vals) / len(vals),
               "share": (sum(vals) / total_s) if total_s else 0.0}
        for name, vals in sorted(phases.items())}
    guard_actions = {}
    for evt in events:
        if evt.get("event") == "health_guard":
            action = evt.get("action", "?")
            guard_actions[action] = guard_actions.get(action, 0) + 1
    restart_causes = {}
    ladder_tiers = {}
    recover_secs = []
    for evt in events:
        kind = evt.get("event")
        if kind == "restart":
            cause = evt.get("cause", "?")
            restart_causes[cause] = restart_causes.get(cause, 0) + 1
            if evt.get("time_to_recover_s") is not None:
                recover_secs.append(float(evt["time_to_recover_s"]))
        elif kind == "recovery_ladder":
            tier = evt.get("tier", "?")
            ladder_tiers[tier] = ladder_tiers.get(tier, 0) + 1
    saves = [e for e in events if e.get("event") == "checkpoint_save"]
    save_secs = [float(e["duration_s"]) for e in saves
                 if e.get("duration_s") is not None]
    tokens = sum(int(e.get("tokens") or 0) for e in steps)
    tokens_per_s = tokens / total_s if total_s and tokens else None
    fpt = _resolve_flops_per_token(events, flops_per_token)
    mfu = None
    if tokens_per_s and fpt:
        achieved_tflops = tokens_per_s * fpt / 1e12
        mfu = {"flops_per_token": fpt,
               "peak_tflops": float(peak_tflops),
               "achieved_tflops": achieved_tflops,
               "mfu": achieved_tflops / float(peak_tflops)}
    losses = [float(e["loss"]) for e in steps
              if e.get("loss") is not None]
    return {
        "schema": SCHEMA_VERSION,
        "steps": len(steps),
        "flavor": steps[-1].get("flavor") if steps else None,
        "wall_s": total_s,
        "step_s": {
            "mean": (total_s / len(walls)) if walls else None,
            "p50": _percentile(walls, 0.50),
            "p95": _percentile(walls, 0.95),
            "min": walls[0] if walls else None,
            "max": walls[-1] if walls else None,
        },
        "phases": phase_stats,
        "tokens": tokens or None,
        "tokens_per_s": tokens_per_s,
        "mfu": mfu,
        "collective_wire": _wire_bytes_per_step(events),
        "kernels": _kernel_summary(events),
        "last_loss": losses[-1] if losses else None,
        "events": {
            "recompile": sum(1 for e in events
                             if e.get("event") == "recompile"),
            "health_guard": guard_actions,
            "checkpoint_save": {
                "count": len(saves),
                "mean_s": (sum(save_secs) / len(save_secs))
                if save_secs else None,
            },
            "checkpoint_load": sum(
                1 for e in events if e.get("event") == "checkpoint_load"),
            "checkpoint_fallback": sum(
                1 for e in events
                if e.get("event") == "checkpoint_fallback"),
            "restart": {
                "count": sum(restart_causes.values()),
                "by_cause": restart_causes,
                "mean_time_to_recover_s": (
                    sum(recover_secs) / len(recover_secs))
                if recover_secs else None,
            },
            "recovery_ladder": {
                "count": sum(ladder_tiers.values()),
                "by_tier": ladder_tiers,
            },
        },
    }


def _summarize_serve(decode, fleet=None):
    """Serve-mode summary over ``decode_step`` events. Per-token latency
    samples: every token a decode step produced experienced that step's
    host wall, so the sample list is each step's wall repeated
    ``tokens`` times — the open-loop analog of per-request latency
    without having to join request ids across events."""
    walls = sorted(float(e["wall_s"]) for e in decode
                   if e.get("wall_s") is not None)
    total_s = sum(walls)
    tokens = sum(int(e.get("tokens") or 0) for e in decode)
    lat = sorted(x for e in decode if e.get("wall_s") is not None
                 for x in [float(e["wall_s"])] * int(e.get("tokens") or 0))
    occ = [float(e["occupancy"]) for e in decode
           if e.get("occupancy") is not None]
    qd = [float(e["queue_depth"]) for e in decode
          if e.get("queue_depth") is not None]
    # Paged-KV extras: a paged scheduler stamps each decode_step with
    # the allocator census and the cumulative radix counters, so the
    # last event carries the final tallies and the per-step series
    # gives resident cache bytes per live session (the paged win: only
    # occupied pages count, not max_seq rows).
    pg_events = [e for e in decode if e.get("pages_resident") is not None]
    paging = None
    if pg_events:
        last = pg_events[-1]
        hits = int(last.get("prefix_hits") or 0)
        misses = int(last.get("prefix_misses") or 0)
        # free + resident excludes the reserved trash page 0
        n_pages = int(last["pages_free"]) + \
            int(last["pages_resident"]) + 1
        page_bytes = float(last.get("cache_bytes") or 0) / max(n_pages, 1)
        per_sess = [int(e["pages_resident"]) * page_bytes
                    / int(e.get("batch") or 1)
                    for e in pg_events if int(e.get("batch") or 0)]
        paging = {
            "pages": {"free": int(last["pages_free"]),
                      "resident": int(last["pages_resident"]),
                      "total": n_pages},
            "prefix": {"hits": hits, "misses": misses,
                       "hit_rate": hits / (hits + misses)
                       if (hits + misses) else None},
            "sessions_admitted": int(last.get("sessions_admitted") or 0),
            "sessions_parked_host": int(
                last.get("sessions_parked_host") or 0),
            "cache_bytes_total": int(last.get("cache_bytes") or 0),
            "cache_bytes_per_session": {
                "mean": (sum(per_sess) / len(per_sess))
                if per_sess else None,
                "max": max(per_sess) if per_sess else None,
            },
        }
    # Speculative extras: a speculative scheduler stamps each
    # decode_step with the round's accept tallies and the draft/verify
    # wall split, so the summary can report accepted tokens per round
    # (the speedup lever) and where the wall went.
    sp_events = [e for e in decode
                 if e.get("accepted_tokens") is not None]
    speculative = None
    if sp_events:
        acc = sum(int(e["accepted_tokens"]) for e in sp_events)
        drafts = sum(int(e.get("accepted_drafts") or 0)
                     for e in sp_events)
        drafted = sum(int(e.get("draft_tokens") or 0)
                      for e in sp_events)
        row_rounds = sum(int(e.get("batch") or 0) for e in sp_events)
        dw = sum(float(e.get("draft_wall_s") or 0) for e in sp_events)
        vw = sum(float(e.get("verify_wall_s") or 0) for e in sp_events)
        speculative = {
            "rounds": len(sp_events),
            "row_rounds": row_rounds,
            "accepted_tokens": acc,
            "mean_accepted": acc / row_rounds if row_rounds else None,
            "draft_efficiency": drafts / drafted if drafted else None,
            "draft_len_last": int(sp_events[-1].get("draft_len") or 0),
            "wall_split": {
                "draft_s": dw, "verify_s": vw,
                "draft_frac": dw / (dw + vw) if (dw + vw) else None},
            "effective_tokens_per_s": acc / (dw + vw)
            if (dw + vw) else None,
        }
    return {
        "schema": SCHEMA_VERSION,
        "mode": "serve",
        "flavor": "serve",
        "steps": len(decode),
        "wall_s": total_s,
        "step_s": {
            "mean": (total_s / len(walls)) if walls else None,
            "p50": _percentile(walls, 0.50),
            "p95": _percentile(walls, 0.95),
            "min": walls[0] if walls else None,
            "max": walls[-1] if walls else None,
        },
        "tokens": tokens or None,
        "tokens_per_s": tokens / total_s if total_s and tokens else None,
        "phases": {},   # serve steps have no train phases; diff expects the key
        "latency_s": {
            "mean": (sum(lat) / len(lat)) if lat else None,
            "p50": _percentile(lat, 0.50),
            "p95": _percentile(lat, 0.95),
            "p99": _percentile(lat, 0.99),
        },
        "batch_occupancy": {
            "mean": (sum(occ) / len(occ)) if occ else None,
            "min": min(occ) if occ else None,
            "max": max(occ) if occ else None,
        },
        "queue_depth": {
            "mean": (sum(qd) / len(qd)) if qd else None,
            "max": max(qd) if qd else None,
        },
        "paging": paging,
        "speculative": speculative,
        "fleet": fleet,
        "mfu": None,
    }


def _fmt_s(v):
    if v is None:
        return "-"
    return f"{v * 1e3:.2f}ms" if v < 1.0 else f"{v:.3f}s"


def print_serve_summary(s, out=None):
    print(f"serve summary (schema {s['schema']})", file=out)
    print(f"  decode steps {s['steps']}, wall {s['wall_s']:.3f}s, "
          f"step time mean {_fmt_s(s['step_s']['mean'])} "
          f"p50 {_fmt_s(s['step_s']['p50'])} "
          f"p95 {_fmt_s(s['step_s']['p95'])}", file=out)
    if s["tokens"]:
        print(f"  tokens {s['tokens']}, throughput "
              f"{s['tokens_per_s']:,.1f} tokens/s", file=out)
    lat = s["latency_s"]
    if lat["p50"] is not None:
        print(f"  per-token latency p50 {_fmt_s(lat['p50'])} "
              f"p95 {_fmt_s(lat['p95'])} p99 {_fmt_s(lat['p99'])}",
              file=out)
    occ = s["batch_occupancy"]
    if occ["mean"] is not None:
        print(f"  batch occupancy mean {occ['mean'] * 100:.1f}% "
              f"(min {occ['min'] * 100:.0f}%, max {occ['max'] * 100:.0f}%)",
              file=out)
    qd = s["queue_depth"]
    if qd["mean"] is not None:
        print(f"  queue depth mean {qd['mean']:.2f}, max {qd['max']:.0f}",
              file=out)
    pg = s.get("paging")
    if pg:
        cps = pg["cache_bytes_per_session"]
        mean_kb = (f"{cps['mean'] / 1024:.1f}KB"
                   if cps["mean"] is not None else "-")
        print(f"  paged KV: {pg['pages']['resident']}/"
              f"{pg['pages']['total']} pages resident, cache "
              f"{mean_kb}/session (pool "
              f"{pg['cache_bytes_total'] / 1024:.0f}KB)", file=out)
        pf = pg["prefix"]
        rate = (f"{pf['hit_rate'] * 100:.0f}%"
                if pf["hit_rate"] is not None else "-")
        print(f"  prefix cache: {pf['hits']} hits / {pf['misses']} "
              f"misses (hit rate {rate}), sessions admitted "
              f"{pg['sessions_admitted']}, parked to host "
              f"{pg['sessions_parked_host']}", file=out)
    sp = s.get("speculative")
    if sp:
        mean = (f"{sp['mean_accepted']:.3f}"
                if sp["mean_accepted"] is not None else "-")
        eff = (f"{sp['draft_efficiency'] * 100:.1f}%"
               if sp["draft_efficiency"] is not None else "-")
        print(f"  speculative: {sp['accepted_tokens']} tokens over "
              f"{sp['row_rounds']} row-round(s), mean accepted {mean} "
              f"tokens/round, draft efficiency {eff}, draft window "
              f"{sp['draft_len_last']}", file=out)
        ws = sp["wall_split"]
        frac = (f"{ws['draft_frac'] * 100:.0f}%"
                if ws["draft_frac"] is not None else "-")
        etps = (f"{sp['effective_tokens_per_s']:,.1f}"
                if sp["effective_tokens_per_s"] is not None else "-")
        print(f"  speculative wall: draft {_fmt_s(ws['draft_s'])} / "
              f"verify {_fmt_s(ws['verify_s'])} ({frac} drafting), "
              f"effective {etps} tokens/s", file=out)
    if s.get("kernels"):
        print_kernel_block(s["kernels"], out=out)
    if s.get("fleet"):
        print_fleet_block(s["fleet"], out=out)
    if s.get("disagg"):
        print_disagg_block(s["disagg"], out=out)


def print_disagg_block(dg, out=None):
    bps = dg.get("handoff_bytes_per_session")
    print(f"  disagg: {dg['handoffs']} handoff(s), "
          f"{dg['handoff_bytes'] / 1024:,.1f}KB"
          + (f" ({bps / 1024:.1f}KB/session)" if bps else "")
          + f", {dg['handoff_corrupt']} corrupt, "
          f"{dg['resumed_from_park']} resumed from park", file=out)
    tt = dg["ttft_s"]
    if tt["p50"] is not None:
        print(f"  disagg ttft p50 {_fmt_s(tt['p50'])} "
              f"p95 {_fmt_s(tt['p95'])} p99 {_fmt_s(tt['p99'])}",
              file=out)
    for tier in ("prefill", "decode"):
        row = dg["tiers"][tier]
        qs = row["queue_wait_s"]
        dead = (dg.get("dead_by_tier") or {}).get(tier, 0)
        line = (f"  {tier} tier: {row['steps']} step(s), "
                f"wall {row['wall_s']:.3f}s, step p50 "
                f"{_fmt_s(row['step_s']['p50'])} p95 "
                f"{_fmt_s(row['step_s']['p95'])}, dispatched "
                f"{row['dispatched']} (redispatched "
                f"{row['redispatched']}, dead {dead})")
        if qs["p50"] is not None:
            line += (f", queue wait p50 {_fmt_s(qs['p50'])} "
                     f"p95 {_fmt_s(qs['p95'])}")
        print(line, file=out)


def print_kernel_block(kn, out=None):
    budget = kn.get("vmem_budget_bytes") or 0
    frac = kn.get("elided_dma_fraction")
    frac_s = f"{frac * 100:.1f}%" if frac is not None else "-"
    line = (f"  kernels: VMEM high-water "
            f"{kn['vmem_high_water_bytes'] / 1024:,.1f}KB")
    if budget:
        line += f" / {budget / (1 << 20):.0f}MB budget"
    line += f", elided DMA {frac_s}"
    if kn.get("expected_elision") is not None:
        line += f" (contract >= {kn['expected_elision'] * 100:.1f}%)"
    print(line, file=out)
    for name, kd in kn["per_kernel"].items():
        ef = kd.get("elided_dma_fraction")
        ef_s = f"{ef * 100:5.1f}%" if ef is not None else "    -"
        print(f"    {name:<14s} VMEM {kd['vmem_bytes'] / 1024:>9,.1f}KB  "
              f"elided DMA {ef_s}", file=out)


def print_fleet_block(fl, out=None):
    rd = fl["replicas_dead"]
    causes = ", ".join(f"{k}={v}" for k, v in
                       sorted(rd["by_cause"].items())) or "none"
    reasons = ", ".join(f"{k}={v}" for k, v in
                        sorted(fl["finish_reasons"].items())) or "-"
    print(f"  fleet: {fl['requests']} request(s) -> "
          f"{fl['completions']} completion(s) [{reasons}], "
          f"{fl['redispatched']} redispatch(es), {fl['aborted']} "
          f"aborted, {fl['shed']} shed, {fl['timeouts']} timeout(s), "
          f"{fl['defers']} defer episode(s)", file=out)
    ttr = fl["mean_time_to_recover_s"]
    print(f"  fleet replicas: {fl['replicas'] or '?'} total, "
          f"{rd['count']} dead [{causes}]"
          + (f", mean recover {_fmt_s(ttr)}" if ttr else ""), file=out)
    rl = fl["request_latency_s"]
    if rl["p50"] is not None:
        print(f"  fleet request latency p50 {_fmt_s(rl['p50'])} "
              f"p95 {_fmt_s(rl['p95'])} p99 {_fmt_s(rl['p99'])} "
              f"max {_fmt_s(rl['max'])}", file=out)


def print_summary(s, out=None):
    if s.get("mode") == "serve":
        return print_serve_summary(s, out)
    print(f"run summary ({s['flavor'] or 'unknown'} flavor, schema "
          f"{s['schema']})", file=out)
    print(f"  steps {s['steps']}, wall {s['wall_s']:.3f}s, "
          f"step time mean {_fmt_s(s['step_s']['mean'])} "
          f"p50 {_fmt_s(s['step_s']['p50'])} "
          f"p95 {_fmt_s(s['step_s']['p95'])}", file=out)
    if s["phases"]:
        print("  phase breakdown (host wall, share of step time):",
              file=out)
        for name, ps in s["phases"].items():
            print(f"    {name:<14s} mean {_fmt_s(ps['mean_s']):>10s}  "
                  f"total {_fmt_s(ps['total_s']):>10s}  "
                  f"{ps['share'] * 100:5.1f}%", file=out)
    if s["tokens_per_s"]:
        print(f"  throughput {s['tokens_per_s']:,.0f} tokens/s", file=out)
    if s["mfu"]:
        m = s["mfu"]
        print(f"  MFU {m['mfu'] * 100:.1f}% "
              f"({m['achieved_tflops']:.1f} / {m['peak_tflops']:.0f} "
              f"TFLOPS at {m['flops_per_token']:,.0f} flops/token)",
              file=out)
    if s.get("collective_wire"):
        w = s["collective_wire"]
        print(f"  collective wire {w['total_bytes'] / 1024:,.1f}KB/step, "
              f"{w['quantized_bytes'] / 1024:,.1f}KB "
              f"({w['quantized_share'] * 100:.1f}%) in 1-byte quantized "
              f"form", file=out)
    if s.get("kernels"):
        print_kernel_block(s["kernels"], out=out)
    ev = s["events"]
    guards = ", ".join(f"{k}={v}" for k, v in
                       sorted(ev["health_guard"].items())) or "none"
    save_mean = ev["checkpoint_save"]["mean_s"]
    print(f"  events: {ev['recompile']} recompile(s), health guards "
          f"[{guards}], {ev['checkpoint_save']['count']} checkpoint "
          f"save(s)"
          + (f" (mean {_fmt_s(save_mean)})" if save_mean else "")
          + f", {ev['checkpoint_load']} load(s)", file=out)
    rst = ev.get("restart") or {}
    lad = ev.get("recovery_ladder") or {}
    fallbacks = ev.get("checkpoint_fallback", 0)
    if rst.get("count") or lad.get("count") or fallbacks:
        causes = ", ".join(f"{k}={v}" for k, v in
                           sorted((rst.get("by_cause") or {}).items())) \
            or "none"
        tiers = ", ".join(f"{k}={v}" for k, v in
                          sorted((lad.get("by_tier") or {}).items())) \
            or "none"
        ttr = rst.get("mean_time_to_recover_s")
        print(f"  resilience: {rst.get('count', 0)} restart(s) [{causes}]"
              + (f" mean recover {_fmt_s(ttr)}" if ttr else "")
              + f", {lad.get('count', 0)} recovery ladder load(s) "
              f"[{tiers}], {fallbacks} checkpoint fallback(s)", file=out)
    if s["last_loss"] is not None:
        print(f"  last loss {s['last_loss']:.6g}", file=out)


# Metrics the diff table compares; (label, getter, lower_is_better).
def _diff_rows(a, b):
    def step_stat(s, key):
        return s["step_s"][key]

    rows = [
        ("step_s.mean", step_stat(a, "mean"), step_stat(b, "mean"), True),
        ("step_s.p50", step_stat(a, "p50"), step_stat(b, "p50"), True),
        ("step_s.p95", step_stat(a, "p95"), step_stat(b, "p95"), True),
        ("tokens_per_s", a["tokens_per_s"], b["tokens_per_s"], False),
        ("mfu", a["mfu"]["mfu"] if a["mfu"] else None,
         b["mfu"]["mfu"] if b["mfu"] else None, False),
    ]
    for name in sorted(set(a["phases"]) | set(b["phases"])):
        rows.append((f"phase.{name}.mean_s",
                     a["phases"].get(name, {}).get("mean_s"),
                     b["phases"].get(name, {}).get("mean_s"), True))
    sa, sb = a.get("speculative"), b.get("speculative")
    if sa or sb:
        rows.append(("speculative.mean_accepted",
                     (sa or {}).get("mean_accepted"),
                     (sb or {}).get("mean_accepted"), False))
        rows.append(("speculative.effective_tokens_per_s",
                     (sa or {}).get("effective_tokens_per_s"),
                     (sb or {}).get("effective_tokens_per_s"), False))
    return rows


def diff_summaries(a, b):
    """Regression table between run A (baseline) and run B. Returns
    (rows, step_mean_delta_pct); each row is
    {metric, a, b, delta_pct, regression}."""
    out = []
    step_mean_delta = None
    for metric, va, vb, lower_better in _diff_rows(a, b):
        delta = None
        if va and vb:
            delta = (vb - va) / va * 100.0
        regression = None
        if delta is not None:
            regression = delta > 0 if lower_better else delta < 0
        if metric == "step_s.mean":
            step_mean_delta = delta
        out.append({"metric": metric, "a": va, "b": vb,
                    "delta_pct": delta, "regression": regression})
    return out, step_mean_delta


def print_diff(rows, out=None):
    print(f"{'metric':<24s} {'A':>12s} {'B':>12s} {'delta':>9s}",
          file=out)
    for r in rows:
        def fmt(v):
            if v is None:
                return "-"
            return f"{v:.5g}"
        delta = "-" if r["delta_pct"] is None else f"{r['delta_pct']:+.1f}%"
        mark = " <-- regression" if r["regression"] else ""
        print(f"{r['metric']:<24s} {fmt(r['a']):>12s} "
              f"{fmt(r['b']):>12s} {delta:>9s}{mark}", file=out)


def print_tail(events, as_json, out=None):
    if as_json:
        print(json.dumps(events, indent=2, default=str), file=out)
        return
    for evt in events:
        extra = " ".join(
            f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in evt.items()
            if k not in ("schema", "event", "t", "phases")
            and isinstance(v, (str, int, float, bool)))
        print(f"{evt.get('t', 0):.3f} {evt.get('event', '?'):<16s} "
              f"{extra}", file=out)


# ---------------------------------------------------------------------------
# aggregate: multi-host skew + straggler ranking
# ---------------------------------------------------------------------------

def host_label(events, path):
    """Identity of the process that wrote this log: the run_start (or any
    step) event's hostname/process_index stamp, else the file name."""
    for kind in ("run_start", "step"):
        for evt in events:
            if evt.get("event") == kind and \
                    evt.get("process_index") is not None:
                host = evt.get("hostname") or "host"
                return f"{host}/p{evt['process_index']}"
    return os.path.basename(path)


def aggregate(logs, no_heartbeat=()):
    """Merge per-host logs of one run. ``logs`` is ``[(label, events)]``;
    returns the aggregation dict, or None when no step appears in at
    least two logs (nothing cross-host to compare) and no host is known
    dead. ``no_heartbeat`` lists hosts that never produced a usable
    log/heartbeat (``{"host", "status": "no-heartbeat", "reason"}``
    rows) — a crashed host must show up in the report, not crash it.

    The straggler ranking orders hosts by mean *excess* wall — how much
    slower than the fastest host they were, averaged over every shared
    step — which is robust to a globally slow phase (all hosts slow
    together shows zero excess everywhere).
    """
    hosts = [dict(row) for row in no_heartbeat]
    per_step = {}
    serve_hosts = []
    all_events = []
    for label, events in logs:
        all_events.extend(events)
        decode = [e for e in events if e.get("event") == "decode_step"
                  and e.get("wall_s") is not None]
        prefill = [e for e in events if e.get("event") == "prefill_step"
                   and e.get("wall_s") is not None]
        if decode:
            d_walls = [float(e["wall_s"]) for e in decode]
            toks = sum(int(e.get("tokens") or 0) for e in decode)
            serve_hosts.append({
                "host": label,
                "decode_steps": len(decode),
                "tokens": toks,
                "tokens_per_s": (toks / sum(d_walls))
                if sum(d_walls) and toks else None,
                "last_step": decode[-1].get("step"),
            })
        if prefill:
            # a disaggregated prefill-tier worker log: no decode steps,
            # one prefill_step per admission
            p_walls = [float(e["wall_s"]) for e in prefill]
            serve_hosts.append({
                "host": label,
                "tier": "prefill",
                "decode_steps": 0,
                "prefill_steps": len(prefill),
                "tokens": None,
                "tokens_per_s": None,
                "prefills_per_s": (len(prefill) / sum(p_walls))
                if sum(p_walls) else None,
                "last_step": prefill[-1].get("step"),
            })
        steps = [e for e in events if e.get("event") == "step"
                 and e.get("wall_s") is not None]
        if steps or (not decode and not prefill):
            walls = [float(e["wall_s"]) for e in steps]
            hosts.append({
                "host": label,
                "steps": len(steps),
                "mean_wall_s": sum(walls) / len(walls) if walls else None,
                "last_step": steps[-1].get("step") if steps else None,
            })
        for e in steps:
            per_step.setdefault(int(e.get("step", -1)),
                                {})[label] = float(e["wall_s"])
    fleet = _summarize_fleet(all_events)
    disagg = _summarize_disagg(all_events)
    shared = {s: w for s, w in per_step.items() if len(w) >= 2}
    if not shared and not no_heartbeat and not serve_hosts \
            and fleet is None and disagg is None:
        return None
    step_rows = []
    excess = {h["host"]: [] for h in hosts}
    slow_count = {h["host"]: 0 for h in hosts}
    for s in sorted(shared):
        walls = shared[s]
        fastest = min(walls.values())
        slowest = max(walls, key=walls.get)
        step_rows.append({"step": s, "walls": walls,
                          "skew_s": max(walls.values()) - fastest,
                          "slowest": slowest})
        slow_count[slowest] += 1
        for label, w in walls.items():
            excess[label].append(w - fastest)
    ranking = [{"host": label,
                "mean_excess_s": sum(ex) / len(ex),
                "slowest_steps": slow_count[label],
                "shared_steps": len(ex)}
               for label, ex in excess.items() if ex]
    ranking.sort(key=lambda r: -r["mean_excess_s"])
    return {"schema": SCHEMA_VERSION, "hosts": hosts,
            "steps": step_rows, "straggler_ranking": ranking,
            "serve_hosts": serve_hosts, "fleet": fleet,
            "disagg": disagg}


def print_aggregate(agg, n_steps=10, out=None):
    n_logs = len(agg["hosts"]) + len(agg.get("serve_hosts") or ())
    print(f"cross-host aggregation ({n_logs} host logs, "
          f"schema {agg['schema']})", file=out)
    for h in agg["hosts"]:
        if h.get("status") == "no-heartbeat":
            print(f"  {h['host']:<24s} NO HEARTBEAT "
                  f"({h.get('reason', 'missing')}) — host crashed "
                  f"before/while reporting", file=out)
            continue
        mean = _fmt_s(h["mean_wall_s"])
        print(f"  {h['host']:<24s} {h['steps']} step(s), "
              f"mean {mean}, last step {h['last_step']}", file=out)
    rows = agg["steps"][-max(0, n_steps):]
    if rows:
        print(f"  per-step skew (last {len(rows)} shared steps; "
              f"skew = slowest - fastest wall):", file=out)
        for r in rows:
            walls = " ".join(f"{label}={_fmt_s(w)}"
                             for label, w in sorted(r["walls"].items()))
            print(f"    step {r['step']:>6d}  skew {_fmt_s(r['skew_s']):>9s}"
                  f"  slowest {r['slowest']}  [{walls}]", file=out)
    if agg["steps"] or agg["straggler_ranking"]:
        print("  straggler ranking (mean wall excess over the fastest "
              "host per shared step):", file=out)
        for i, r in enumerate(agg["straggler_ranking"], start=1):
            print(f"    {i}. {r['host']:<24s} "
                  f"+{_fmt_s(r['mean_excess_s'])} "
                  f"mean excess, slowest on {r['slowest_steps']}/"
                  f"{r['shared_steps']} steps", file=out)
        top = agg["straggler_ranking"][0] \
            if agg["straggler_ranking"] else None
        if top and top["mean_excess_s"] > 0:
            print(f"  => straggler: {top['host']}", file=out)
    for h in agg.get("serve_hosts") or ():
        if h.get("tier") == "prefill":
            pps = (f"{h['prefills_per_s']:,.1f} prefills/s"
                   if h.get("prefills_per_s") else "-")
            print(f"  replica {h['host']:<22s} [prefill tier] "
                  f"{h['prefill_steps']} prefill step(s), {pps}, "
                  f"last step {h['last_step']}", file=out)
            continue
        tps = (f"{h['tokens_per_s']:,.1f} tokens/s"
               if h["tokens_per_s"] else "-")
        print(f"  replica {h['host']:<22s} {h['decode_steps']} decode "
              f"step(s), {h['tokens']} tokens, {tps}, last step "
              f"{h['last_step']}", file=out)
    if agg.get("fleet"):
        print_fleet_block(agg["fleet"], out=out)
    if agg.get("disagg"):
        print_disagg_block(agg["disagg"], out=out)


# ---------------------------------------------------------------------------
# postmortem: render one flight-recorder dump
# ---------------------------------------------------------------------------

def print_postmortem(dump, n_events=15, out=None):
    meta = dump.get("meta") or {}
    host = meta.get("hostname", "?")
    pidx = meta.get("process_index", "?")
    print(f"flight-recorder postmortem ({dump.get('schema')})", file=out)
    print(f"  reason   {dump.get('reason')}", file=out)
    print(f"  host     {host} process {pidx}/"
          f"{meta.get('process_count', '?')} pid {dump.get('pid')}",
          file=out)
    print(f"  t        {dump.get('t')}", file=out)
    if meta:
        facts = " ".join(f"{k}={v}" for k, v in sorted(meta.items())
                         if k not in ("hostname", "process_index",
                                      "process_count"))
        if facts:
            print(f"  run      {facts}", file=out)
    wd = dump.get("watchdog")
    if wd:
        print(f"  watchdog step {wd.get('step')} stuck in "
              f"'{wd.get('phase')}' for {wd.get('elapsed_s')}s "
              f"(deadline {wd.get('deadline_s')}s = "
              f"{wd.get('deadline_factor')} x median "
              f"{wd.get('median_wall_s')}s)", file=out)
        print(f"  verdict  {wd.get('verdict')}", file=out)
        for s in wd.get("stragglers") or []:
            if s.get("status") == "no-heartbeat":
                print(f"    straggler p{s.get('process_index')}: "
                      f"no-heartbeat ({s.get('reason', 'missing')}) — "
                      f"process died before/while writing its heartbeat",
                      file=out)
                continue
            print(f"    straggler p{s.get('process_index')} "
                  f"({s.get('hostname')}): step {s.get('step')} "
                  f"({s.get('behind_steps')} behind), phase "
                  f"'{s.get('phase')}', beat {s.get('beat_age_s')}s ago",
                  file=out)
    exc = dump.get("exception")
    if exc:
        print(f"  exception {exc.get('type')}: {exc.get('message')}",
              file=out)
    in_flight = dump.get("in_flight_phases") or {}
    if in_flight:
        print("  in-flight phases:", file=out)
        for thread, path in sorted(in_flight.items()):
            print(f"    {thread:<24s} {path}", file=out)
    for t in dump.get("threads") or []:
        flag = " daemon" if t.get("daemon") else ""
        print(f"  thread {t.get('name')}{flag}:", file=out)
        for line in (t.get("stack") or [])[-8:]:
            for part in line.splitlines():
                print(f"    {part}", file=out)
    colls = dump.get("collectives") or []
    if colls:
        print(f"  collectives traced into the step "
              f"({len(colls)} site(s)):", file=out)
        for c in colls[:20]:
            print(f"    {c.get('site'):<28s} axis={c.get('axis')} "
                  f"{c.get('primitive')} chunks={c.get('chunks')} "
                  f"hops={c.get('hops')} chained={c.get('chained')}",
                  file=out)
    events = dump.get("events") or []
    tail = events[-max(0, n_events):]
    if tail:
        print(f"  timeline tail (last {len(tail)} of {len(events)} "
              f"events):", file=out)
        print_tail(tail, False, out=out)
    phases = dump.get("phase_log") or []
    if phases:
        print(f"  last phase transitions:", file=out)
        for p in phases[-10:]:
            dur = f" ({p['duration_s'] * 1e3:.2f}ms)" \
                if p.get("duration_s") is not None else ""
            print(f"    {p.get('t', 0):.3f} {p.get('kind'):<6s}"
                  f"{p.get('path')}{dur}", file=out)


def print_heartbeat_status(directory, expected_count=None, out=None):
    """One line per process in a heartbeat dir — live heartbeats plus
    the expected-but-silent ``no-heartbeat`` processes."""
    from deepspeed_tpu.telemetry.watchdog import scan_heartbeats
    heartbeats, no_heartbeat = scan_heartbeats(
        directory, expected_count=expected_count)
    print(f"  heartbeat dir {directory}: {len(heartbeats)} heartbeat "
          f"file(s), {len(no_heartbeat)} silent", file=out)
    for hb in sorted(heartbeats,
                     key=lambda h: h.get("process_index") or 0):
        state = (f"in step for {hb.get('step_elapsed_s')}s"
                 if hb.get("in_step") else "between steps")
        print(f"    p{hb.get('process_index')} ({hb.get('hostname')}): "
              f"step {hb.get('step')}, phase '{hb.get('phase')}', "
              f"{state}", file=out)
    for gone in no_heartbeat:
        print(f"    p{gone['process_index']}: no-heartbeat "
              f"({gone['reason']})", file=out)


def _load(parser, path):
    try:
        return read_events(path)
    except OSError as exc:
        parser.error(f"cannot read log: {exc}")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_tpu_metrics",
        description="Summarize, tail, and diff deepspeed_tpu telemetry "
                    "JSONL logs (step-time breakdown, MFU estimate, "
                    "regression diffs).")
    sub = parser.add_subparsers(dest="cmd")

    p_sum = sub.add_parser("summary", help="aggregate one run's log")
    p_sum.add_argument("log")
    p_sum.add_argument("--json", action="store_true", dest="as_json")
    p_sum.add_argument("--flops-per-token", type=float, default=None,
                       help="model flops per token for the MFU estimate "
                            "(default: the log's compile/run_start stamp)")
    p_sum.add_argument("--peak-tflops", type=float,
                       default=DEFAULT_PEAK_TFLOPS,
                       help="per-chip peak TFLOPS for MFU (default "
                            f"{DEFAULT_PEAK_TFLOPS:.0f}, v5e bf16)")

    p_tail = sub.add_parser("tail", help="print the last N events")
    p_tail.add_argument("log")
    p_tail.add_argument("-n", type=int, default=10)
    p_tail.add_argument("--json", action="store_true", dest="as_json")
    p_tail.add_argument("--event", default=None,
                        help="only events of this type")

    p_diff = sub.add_parser("diff",
                            help="regression table between two runs")
    p_diff.add_argument("log_a", help="baseline run log")
    p_diff.add_argument("log_b", help="candidate run log")
    p_diff.add_argument("--json", action="store_true", dest="as_json")
    p_diff.add_argument("--flops-per-token", type=float, default=None)
    p_diff.add_argument("--peak-tflops", type=float,
                        default=DEFAULT_PEAK_TFLOPS)
    p_diff.add_argument("--fail-over", type=float, default=None,
                        metavar="PCT",
                        help="exit 1 when mean step time regressed by "
                             "more than PCT percent")

    p_agg = sub.add_parser(
        "aggregate",
        help="merge per-host logs: cross-host skew + straggler ranking")
    p_agg.add_argument("logs", nargs="+",
                       help="one telemetry JSONL log per host/process")
    p_agg.add_argument("-n", type=int, default=10,
                       help="shared steps shown in the skew table")
    p_agg.add_argument("--json", action="store_true", dest="as_json")
    p_agg.add_argument("--heartbeats", default=None, metavar="DIR",
                       help="also scan this heartbeat dir and list "
                            "processes with no usable hb-p*.json as "
                            "no-heartbeat hosts")
    p_agg.add_argument("--expect-hosts", type=int, default=None,
                       help="expected process count: indices in "
                            "range(N) with no heartbeat file at all are "
                            "reported as no-heartbeat")

    p_pm = sub.add_parser(
        "postmortem", help="render a flight-recorder crash dump")
    p_pm.add_argument("dump", help="flight-*.json dump file")
    p_pm.add_argument("-n", type=int, default=15,
                      help="events shown in the timeline tail")
    p_pm.add_argument("--json", action="store_true", dest="as_json")
    p_pm.add_argument("--heartbeats", default=None, metavar="DIR",
                      help="also render the heartbeat dir's per-process "
                           "status (silent hosts show as no-heartbeat)")
    p_pm.add_argument("--expect-hosts", type=int, default=None,
                      help="expected process count for --heartbeats")

    args = parser.parse_args(argv)
    if args.cmd is None:
        parser.error("a subcommand is required: summary, tail, diff, "
                     "aggregate, or postmortem")

    if args.cmd == "summary":
        s = summarize(_load(parser, args.log),
                      flops_per_token=args.flops_per_token,
                      peak_tflops=args.peak_tflops)
        if s is None:
            print("no step events in log", file=sys.stderr)
            return 1
        if args.as_json:
            print(json.dumps(s, indent=2, sort_keys=True))
        else:
            print_summary(s)
        return 0

    if args.cmd == "tail":
        events = _load(parser, args.log)
        if args.event:
            events = [e for e in events if e.get("event") == args.event]
        print_tail(events[-max(0, args.n):], args.as_json)
        return 0

    if args.cmd == "aggregate":
        logs = []
        no_heartbeat = []
        for path in args.logs:
            try:
                events = read_events(path)
            except OSError as exc:
                # A crashed host may never have opened (or half-wrote)
                # its log — report it, don't die on it.
                no_heartbeat.append({
                    "host": os.path.basename(path),
                    "status": "no-heartbeat",
                    "reason": f"unreadable log ({exc})"})
                continue
            logs.append((host_label(events, path), events))
        if args.heartbeats:
            from deepspeed_tpu.telemetry.watchdog import scan_heartbeats
            _, silent = scan_heartbeats(
                args.heartbeats, expected_count=args.expect_hosts)
            no_heartbeat.extend(
                {"host": f"p{g['process_index']}",
                 "status": "no-heartbeat", "reason": g["reason"]}
                for g in silent)
        agg = aggregate(logs, no_heartbeat=no_heartbeat)
        if agg is None:
            print("no step appears in two or more logs — nothing "
                  "cross-host to compare", file=sys.stderr)
            return 1
        if args.as_json:
            print(json.dumps(agg, indent=2, sort_keys=True))
        else:
            print_aggregate(agg, n_steps=args.n)
        return 0

    if args.cmd == "postmortem":
        from deepspeed_tpu.telemetry.flight import read_dump
        try:
            dump = read_dump(args.dump)
        except (OSError, ValueError) as exc:
            # A host killed mid-dump leaves a truncated/absent file —
            # degrade to whatever else we can report instead of a usage
            # error.
            print(f"cannot read dump {args.dump}: {exc} — host produced "
                  f"no usable flight dump (no-heartbeat)",
                  file=sys.stderr)
            if args.heartbeats:
                print_heartbeat_status(args.heartbeats,
                                       expected_count=args.expect_hosts,
                                       out=sys.stderr)
            return 1
        if args.as_json:
            print(json.dumps(dump, indent=2, sort_keys=True, default=str))
        else:
            print_postmortem(dump, n_events=args.n)
            if args.heartbeats:
                print_heartbeat_status(args.heartbeats,
                                       expected_count=args.expect_hosts)
        return 0

    # diff
    sa = summarize(_load(parser, args.log_a),
                   flops_per_token=args.flops_per_token,
                   peak_tflops=args.peak_tflops)
    sb = summarize(_load(parser, args.log_b),
                   flops_per_token=args.flops_per_token,
                   peak_tflops=args.peak_tflops)
    if sa is None or sb is None:
        which = args.log_a if sa is None else args.log_b
        print(f"no step events in log {which}", file=sys.stderr)
        return 1
    rows, step_mean_delta = diff_summaries(sa, sb)
    if args.as_json:
        print(json.dumps({"schema": SCHEMA_VERSION, "rows": rows,
                          "step_mean_delta_pct": step_mean_delta},
                         indent=2, sort_keys=True))
    else:
        print_diff(rows)
    if args.fail_over is not None and step_mean_delta is not None \
            and step_mean_delta > args.fail_over:
        print(f"FAIL: mean step time regressed "
              f"{step_mean_delta:+.1f}% (> {args.fail_over}%)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
