"""`ds_tpu_metrics`: tail / summarize / diff telemetry JSONL logs.

Three subcommands over the schema-versioned event log a run writes when
``telemetry.jsonl_path`` is set (`telemetry/events.py`):

- ``ds_tpu_metrics summary LOG`` — step count, wall time, step-time
  stats (mean/p50/p95), per-phase breakdown with shares, tokens/sec,
  and an MFU estimate (the ANALYSIS_MFU.md accounting: achieved TFLOPS
  = tokens/sec x flops/token; MFU = achieved / peak, default peak 197
  TFLOPS — one v5e chip's bf16 ceiling), plus recompile / health-guard /
  checkpoint event counts.
- ``ds_tpu_metrics tail LOG -n 20`` — the last N events, one line each.
- ``ds_tpu_metrics diff A B`` — per-metric regression table between two
  runs; ``--fail-over PCT`` exits 1 when mean step time regressed more.

Exit codes: 0 ok, 1 no step events (summary) or regression past
``--fail-over`` (diff), 2 usage errors / unreadable files.

flops/token resolution for MFU (first hit wins): ``--flops-per-token``
flag > the run's ``compile`` event > its ``run_start`` event. Without
any, the summary reports throughput but skips MFU.
"""

import argparse
import json
import sys

from deepspeed_tpu.telemetry.events import SCHEMA_VERSION

# One v5e chip's bf16 peak (ANALYSIS_MFU.md) — override per target chip.
DEFAULT_PEAK_TFLOPS = 197.0


def read_events(path):
    """Parse a JSONL log, skipping blank/corrupt lines (a live run may
    be mid-write on the last line)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                evt = json.loads(line)
            except ValueError:
                continue
            if isinstance(evt, dict):
                events.append(evt)
    return events


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _resolve_flops_per_token(events, flops_per_token=None):
    if flops_per_token:
        return float(flops_per_token)
    for kind in ("compile", "run_start"):
        for evt in events:
            if evt.get("event") == kind and evt.get("flops_per_token"):
                return float(evt["flops_per_token"])
    return None


def _wire_bytes_per_step(events):
    """Per-step collective wire accounting from the run's ``compile``
    event (`collective_bytes_by_dtype`): total bytes moved by collectives
    per step, and how many of them travel in 1-byte quantized form
    (u8/s8/f8 element dtypes — the int8/fp8 wire codecs)."""
    for evt in reversed(events):
        bd = evt.get("collective_bytes_by_dtype") \
            if evt.get("event") == "compile" else None
        if not bd:
            continue
        total = wire = 0
        for op, per_dtype in bd.items():
            if not isinstance(per_dtype, dict):   # the "total" rollup
                continue
            for dt, b in per_dtype.items():
                total += int(b)
                if dt in ("u8", "s8") or dt.startswith("f8"):
                    wire += int(b)
        return {"total_bytes": total, "quantized_bytes": wire,
                "quantized_share": (wire / total) if total else 0.0}
    return None


def summarize(events, flops_per_token=None, peak_tflops=DEFAULT_PEAK_TFLOPS):
    """Aggregate a run's events into the summary dict (None when the log
    holds no step events)."""
    steps = [e for e in events if e.get("event") == "step"]
    if not steps:
        return None
    walls = sorted(float(e["wall_s"]) for e in steps
                   if e.get("wall_s") is not None)
    total_s = sum(walls)
    phases = {}
    for evt in steps:
        for name, secs in (evt.get("phases") or {}).items():
            phases.setdefault(name, []).append(float(secs))
    phase_stats = {
        name: {"total_s": sum(vals),
               "mean_s": sum(vals) / len(vals),
               "share": (sum(vals) / total_s) if total_s else 0.0}
        for name, vals in sorted(phases.items())}
    guard_actions = {}
    for evt in events:
        if evt.get("event") == "health_guard":
            action = evt.get("action", "?")
            guard_actions[action] = guard_actions.get(action, 0) + 1
    saves = [e for e in events if e.get("event") == "checkpoint_save"]
    save_secs = [float(e["duration_s"]) for e in saves
                 if e.get("duration_s") is not None]
    tokens = sum(int(e.get("tokens") or 0) for e in steps)
    tokens_per_s = tokens / total_s if total_s and tokens else None
    fpt = _resolve_flops_per_token(events, flops_per_token)
    mfu = None
    if tokens_per_s and fpt:
        achieved_tflops = tokens_per_s * fpt / 1e12
        mfu = {"flops_per_token": fpt,
               "peak_tflops": float(peak_tflops),
               "achieved_tflops": achieved_tflops,
               "mfu": achieved_tflops / float(peak_tflops)}
    losses = [float(e["loss"]) for e in steps
              if e.get("loss") is not None]
    return {
        "schema": SCHEMA_VERSION,
        "steps": len(steps),
        "flavor": steps[-1].get("flavor"),
        "wall_s": total_s,
        "step_s": {
            "mean": (total_s / len(walls)) if walls else None,
            "p50": _percentile(walls, 0.50),
            "p95": _percentile(walls, 0.95),
            "min": walls[0] if walls else None,
            "max": walls[-1] if walls else None,
        },
        "phases": phase_stats,
        "tokens": tokens or None,
        "tokens_per_s": tokens_per_s,
        "mfu": mfu,
        "collective_wire": _wire_bytes_per_step(events),
        "last_loss": losses[-1] if losses else None,
        "events": {
            "recompile": sum(1 for e in events
                             if e.get("event") == "recompile"),
            "health_guard": guard_actions,
            "checkpoint_save": {
                "count": len(saves),
                "mean_s": (sum(save_secs) / len(save_secs))
                if save_secs else None,
            },
            "checkpoint_load": sum(
                1 for e in events if e.get("event") == "checkpoint_load"),
        },
    }


def _fmt_s(v):
    if v is None:
        return "-"
    return f"{v * 1e3:.2f}ms" if v < 1.0 else f"{v:.3f}s"


def print_summary(s, out=sys.stdout):
    print(f"run summary ({s['flavor'] or 'unknown'} flavor, schema "
          f"{s['schema']})", file=out)
    print(f"  steps {s['steps']}, wall {s['wall_s']:.3f}s, "
          f"step time mean {_fmt_s(s['step_s']['mean'])} "
          f"p50 {_fmt_s(s['step_s']['p50'])} "
          f"p95 {_fmt_s(s['step_s']['p95'])}", file=out)
    if s["phases"]:
        print("  phase breakdown (host wall, share of step time):",
              file=out)
        for name, ps in s["phases"].items():
            print(f"    {name:<14s} mean {_fmt_s(ps['mean_s']):>10s}  "
                  f"total {_fmt_s(ps['total_s']):>10s}  "
                  f"{ps['share'] * 100:5.1f}%", file=out)
    if s["tokens_per_s"]:
        print(f"  throughput {s['tokens_per_s']:,.0f} tokens/s", file=out)
    if s["mfu"]:
        m = s["mfu"]
        print(f"  MFU {m['mfu'] * 100:.1f}% "
              f"({m['achieved_tflops']:.1f} / {m['peak_tflops']:.0f} "
              f"TFLOPS at {m['flops_per_token']:,.0f} flops/token)",
              file=out)
    if s.get("collective_wire"):
        w = s["collective_wire"]
        print(f"  collective wire {w['total_bytes'] / 1024:,.1f}KB/step, "
              f"{w['quantized_bytes'] / 1024:,.1f}KB "
              f"({w['quantized_share'] * 100:.1f}%) in 1-byte quantized "
              f"form", file=out)
    ev = s["events"]
    guards = ", ".join(f"{k}={v}" for k, v in
                       sorted(ev["health_guard"].items())) or "none"
    save_mean = ev["checkpoint_save"]["mean_s"]
    print(f"  events: {ev['recompile']} recompile(s), health guards "
          f"[{guards}], {ev['checkpoint_save']['count']} checkpoint "
          f"save(s)"
          + (f" (mean {_fmt_s(save_mean)})" if save_mean else "")
          + f", {ev['checkpoint_load']} load(s)", file=out)
    if s["last_loss"] is not None:
        print(f"  last loss {s['last_loss']:.6g}", file=out)


# Metrics the diff table compares; (label, getter, lower_is_better).
def _diff_rows(a, b):
    def step_stat(s, key):
        return s["step_s"][key]

    rows = [
        ("step_s.mean", step_stat(a, "mean"), step_stat(b, "mean"), True),
        ("step_s.p50", step_stat(a, "p50"), step_stat(b, "p50"), True),
        ("step_s.p95", step_stat(a, "p95"), step_stat(b, "p95"), True),
        ("tokens_per_s", a["tokens_per_s"], b["tokens_per_s"], False),
        ("mfu", a["mfu"]["mfu"] if a["mfu"] else None,
         b["mfu"]["mfu"] if b["mfu"] else None, False),
    ]
    for name in sorted(set(a["phases"]) | set(b["phases"])):
        rows.append((f"phase.{name}.mean_s",
                     a["phases"].get(name, {}).get("mean_s"),
                     b["phases"].get(name, {}).get("mean_s"), True))
    return rows


def diff_summaries(a, b):
    """Regression table between run A (baseline) and run B. Returns
    (rows, step_mean_delta_pct); each row is
    {metric, a, b, delta_pct, regression}."""
    out = []
    step_mean_delta = None
    for metric, va, vb, lower_better in _diff_rows(a, b):
        delta = None
        if va and vb:
            delta = (vb - va) / va * 100.0
        regression = None
        if delta is not None:
            regression = delta > 0 if lower_better else delta < 0
        if metric == "step_s.mean":
            step_mean_delta = delta
        out.append({"metric": metric, "a": va, "b": vb,
                    "delta_pct": delta, "regression": regression})
    return out, step_mean_delta


def print_diff(rows, out=sys.stdout):
    print(f"{'metric':<24s} {'A':>12s} {'B':>12s} {'delta':>9s}",
          file=out)
    for r in rows:
        def fmt(v):
            if v is None:
                return "-"
            return f"{v:.5g}"
        delta = "-" if r["delta_pct"] is None else f"{r['delta_pct']:+.1f}%"
        mark = " <-- regression" if r["regression"] else ""
        print(f"{r['metric']:<24s} {fmt(r['a']):>12s} "
              f"{fmt(r['b']):>12s} {delta:>9s}{mark}", file=out)


def print_tail(events, as_json, out=sys.stdout):
    if as_json:
        print(json.dumps(events, indent=2, default=str), file=out)
        return
    for evt in events:
        extra = " ".join(
            f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in evt.items()
            if k not in ("schema", "event", "t", "phases")
            and isinstance(v, (str, int, float, bool)))
        print(f"{evt.get('t', 0):.3f} {evt.get('event', '?'):<16s} "
              f"{extra}", file=out)


def _load(parser, path):
    try:
        return read_events(path)
    except OSError as exc:
        parser.error(f"cannot read log: {exc}")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_tpu_metrics",
        description="Summarize, tail, and diff deepspeed_tpu telemetry "
                    "JSONL logs (step-time breakdown, MFU estimate, "
                    "regression diffs).")
    sub = parser.add_subparsers(dest="cmd")

    p_sum = sub.add_parser("summary", help="aggregate one run's log")
    p_sum.add_argument("log")
    p_sum.add_argument("--json", action="store_true", dest="as_json")
    p_sum.add_argument("--flops-per-token", type=float, default=None,
                       help="model flops per token for the MFU estimate "
                            "(default: the log's compile/run_start stamp)")
    p_sum.add_argument("--peak-tflops", type=float,
                       default=DEFAULT_PEAK_TFLOPS,
                       help="per-chip peak TFLOPS for MFU (default "
                            f"{DEFAULT_PEAK_TFLOPS:.0f}, v5e bf16)")

    p_tail = sub.add_parser("tail", help="print the last N events")
    p_tail.add_argument("log")
    p_tail.add_argument("-n", type=int, default=10)
    p_tail.add_argument("--json", action="store_true", dest="as_json")
    p_tail.add_argument("--event", default=None,
                        help="only events of this type")

    p_diff = sub.add_parser("diff",
                            help="regression table between two runs")
    p_diff.add_argument("log_a", help="baseline run log")
    p_diff.add_argument("log_b", help="candidate run log")
    p_diff.add_argument("--json", action="store_true", dest="as_json")
    p_diff.add_argument("--flops-per-token", type=float, default=None)
    p_diff.add_argument("--peak-tflops", type=float,
                        default=DEFAULT_PEAK_TFLOPS)
    p_diff.add_argument("--fail-over", type=float, default=None,
                        metavar="PCT",
                        help="exit 1 when mean step time regressed by "
                             "more than PCT percent")

    args = parser.parse_args(argv)
    if args.cmd is None:
        parser.error("a subcommand is required: summary, tail, or diff")

    if args.cmd == "summary":
        s = summarize(_load(parser, args.log),
                      flops_per_token=args.flops_per_token,
                      peak_tflops=args.peak_tflops)
        if s is None:
            print("no step events in log", file=sys.stderr)
            return 1
        if args.as_json:
            print(json.dumps(s, indent=2, sort_keys=True))
        else:
            print_summary(s)
        return 0

    if args.cmd == "tail":
        events = _load(parser, args.log)
        if args.event:
            events = [e for e in events if e.get("event") == args.event]
        print_tail(events[-max(0, args.n):], args.as_json)
        return 0

    # diff
    sa = summarize(_load(parser, args.log_a),
                   flops_per_token=args.flops_per_token,
                   peak_tflops=args.peak_tflops)
    sb = summarize(_load(parser, args.log_b),
                   flops_per_token=args.flops_per_token,
                   peak_tflops=args.peak_tflops)
    if sa is None or sb is None:
        which = args.log_a if sa is None else args.log_b
        print(f"no step events in log {which}", file=sys.stderr)
        return 1
    rows, step_mean_delta = diff_summaries(sa, sb)
    if args.as_json:
        print(json.dumps({"schema": SCHEMA_VERSION, "rows": rows,
                          "step_mean_delta_pct": step_mean_delta},
                         indent=2, sort_keys=True))
    else:
        print_diff(rows)
    if args.fail_over is not None and step_mean_delta is not None \
            and step_mean_delta > args.fail_over:
        print(f"FAIL: mean step time regressed "
              f"{step_mean_delta:+.1f}% (> {args.fail_over}%)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
