"""Unified runtime telemetry (ISSUE 8): metrics registry, step-phase
spans, schema-versioned event log, pluggable exporters.

One import surface for everything the engine, the resilience/elastic
layers, bench.py, and the ``ds_tpu_metrics`` CLI share:

- :class:`MetricsRegistry` — typed counters/gauges/histograms + labels
  (`registry.py`).
- :class:`TelemetrySession` / :func:`get_default_session` — registry +
  event log + span API bundled per run (`session.py`).
- :func:`null_span` — the telemetry-off no-op fast path (`spans.py`).
- :data:`SCHEMA_VERSION` — the event-log version tag, also embedded in
  ``ds_tpu_audit --json`` so audits and telemetry join (`events.py`).
- The synchronized timers and the trace-window profiler that moved here
  from ``utils/`` (`timers.py`, `profiler.py`).
- The runtime-forensics layer (ISSUE 12): :class:`FlightRecorder`
  (black-box ring + atomic crash dumps, `flight.py`),
  :class:`HangWatchdog` / :class:`StepAnomalyDetector` (hang detection
  + anomaly-triggered trace capture, `watchdog.py`).

See docs/observability.md for the config block and event schema.
"""

from deepspeed_tpu.telemetry.events import EventLog, SCHEMA_VERSION  # noqa: F401
from deepspeed_tpu.telemetry.exporters import (  # noqa: F401
    ConsoleExporter, JsonlExporter, PrometheusTextfileExporter)
from deepspeed_tpu.telemetry.flight import (  # noqa: F401
    FlightRecorder, install_crash_hooks, uninstall_crash_hooks)
from deepspeed_tpu.telemetry.profiler import (  # noqa: F401
    TraceProfiler, device_report)
from deepspeed_tpu.telemetry.watchdog import (  # noqa: F401
    HangWatchdog, StepAnomalyDetector)
from deepspeed_tpu.telemetry.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry)
from deepspeed_tpu.telemetry.session import (  # noqa: F401
    TelemetrySession, get_default_session, set_default_session)
from deepspeed_tpu.telemetry.spans import Span, null_span  # noqa: F401
from deepspeed_tpu.telemetry.timers import (  # noqa: F401
    SynchronizedWallClockTimer, ThroughputTimer)

__all__ = [
    "ConsoleExporter",
    "Counter",
    "EventLog",
    "FlightRecorder",
    "Gauge",
    "HangWatchdog",
    "Histogram",
    "JsonlExporter",
    "MetricsRegistry",
    "PrometheusTextfileExporter",
    "SCHEMA_VERSION",
    "Span",
    "StepAnomalyDetector",
    "SynchronizedWallClockTimer",
    "TelemetrySession",
    "ThroughputTimer",
    "TraceProfiler",
    "device_report",
    "get_default_session",
    "install_crash_hooks",
    "null_span",
    "set_default_session",
    "uninstall_crash_hooks",
]
