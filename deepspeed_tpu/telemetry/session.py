"""TelemetrySession: one registry + one event log + the span API.

The engine owns a session when the ``telemetry`` config block enables
it; subsystems that run outside an engine method (elastic reshard,
bench.py) reach the *process-default* session via
:func:`get_default_session` so their events land in the same log.

Span durations accumulate per phase name between ``drain_phases()``
calls — the engine drains once per step and stamps the result into that
step's event, so nested/repeated spans within a step sum correctly.

The session is also the forensics hub: when the config enables them it
owns a :class:`~deepspeed_tpu.telemetry.flight.FlightRecorder` (which
rides the exporter fan-out and additionally receives every span
transition) and a
:class:`~deepspeed_tpu.telemetry.watchdog.HangWatchdog` (which every
span entry/exit feeds as a heartbeat).
"""

from deepspeed_tpu.telemetry.events import EventLog
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.spans import Span

_default_session = None


def get_default_session():
    """The process-default session, or None when telemetry is off."""
    return _default_session


def set_default_session(session, replace=True):
    """Install ``session`` as the process default. ``replace=False``
    keeps an already-installed session (first engine wins)."""
    global _default_session
    if _default_session is not None and not replace:
        return _default_session
    _default_session = session
    return session


class TelemetrySession:
    def __init__(self, registry=None, exporters=(), history=256,
                 flight=None, watchdog=None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.flight = flight
        self.watchdog = watchdog
        if flight is not None:
            exporters = list(exporters) + [flight]
        self.events = EventLog(exporters=exporters, history=history)
        if watchdog is not None and watchdog.session is None:
            watchdog.session = self
        self._phases = {}

    @classmethod
    def from_config(cls, tcfg, meta=None):
        """Build a session from a validated ``TelemetryConfig``.

        ``meta`` (process identity + run facts from the engine) is
        stamped into the flight recorder's dumps and names the
        watchdog's heartbeat file; forensics pieces only exist when
        the config enables them.
        """
        from deepspeed_tpu.telemetry.exporters import (
            ConsoleExporter, JsonlExporter, PrometheusTextfileExporter)
        registry = MetricsRegistry()
        exporters = []
        if tcfg.jsonl_path:
            exporters.append(JsonlExporter(tcfg.jsonl_path))
        if tcfg.console:
            exporters.append(ConsoleExporter())
        if tcfg.prometheus_textfile:
            exporters.append(PrometheusTextfileExporter(
                tcfg.prometheus_textfile, registry,
                write_every=tcfg.prometheus_write_every))
        meta = dict(meta or {})
        flight = watchdog = None
        if tcfg.crash_dump_dir:
            from deepspeed_tpu.telemetry.flight import FlightRecorder
            flight = FlightRecorder(tcfg.crash_dump_dir,
                                    history=tcfg.flight_history, meta=meta)
        if tcfg.watchdog_enabled:
            from deepspeed_tpu.telemetry.watchdog import HangWatchdog
            watchdog = HangWatchdog(
                flight=flight,
                deadline_factor=tcfg.watchdog_deadline_factor,
                min_deadline_s=tcfg.watchdog_min_deadline_s,
                action=tcfg.watchdog_action,
                heartbeat_dir=tcfg.crash_dump_dir,
                process_index=meta.get("process_index", 0),
                process_count=meta.get("process_count", 1),
                hostname=meta.get("hostname"))
        return cls(registry=registry, exporters=exporters,
                   history=tcfg.history, flight=flight, watchdog=watchdog)

    # -- spans ---------------------------------------------------------
    def span(self, name):
        return Span(name, self)

    def _enter_phase(self, name, path):
        wd = self.watchdog
        if wd is not None:
            wd.beat(path)
        if self.flight is not None:
            self.flight.record_phase("enter", path)

    def _record_phase(self, name, path, duration_s):
        self._phases[name] = self._phases.get(name, 0.0) + duration_s
        self.registry.histogram(
            "phase_seconds", labels={"phase": name},
            help="host wall seconds per step phase").observe(duration_s)
        wd = self.watchdog
        if wd is not None:
            wd.beat(path)
        if self.flight is not None:
            self.flight.record_phase("exit", path, duration_s)

    def drain_phases(self):
        """Per-phase seconds accumulated since the last drain (one step's
        phase breakdown); resets the accumulator."""
        phases, self._phases = self._phases, {}
        return phases

    # -- events --------------------------------------------------------
    def emit(self, event, **fields):
        self.registry.counter(
            "events_total", labels={"event": event},
            help="telemetry events emitted by type").inc()
        return self.events.emit(event, **fields)

    def step_event(self, **fields):
        """Emit one per-step event and update the step-level metrics."""
        wall = fields.get("wall_s")
        if wall is not None:
            self.registry.histogram(
                "step_seconds",
                help="end-to-end host wall seconds per optimizer step"
            ).observe(wall)
        if fields.get("loss") is not None:
            self.registry.gauge("loss", help="last step loss").set(
                fields["loss"])
        self.registry.counter("steps_total",
                              help="optimizer steps completed").inc()
        return self.emit("step", **fields)

    def close(self):
        if self.watchdog is not None:
            self.watchdog.stop()
        self.events.close()
