"""TelemetrySession: one registry + one event log + the span API.

The engine owns a session when the ``telemetry`` config block enables
it; subsystems that run outside an engine method (elastic reshard,
bench.py) reach the *process-default* session via
:func:`get_default_session` so their events land in the same log.

Span durations accumulate per phase name between ``drain_phases()``
calls — the engine drains once per step and stamps the result into that
step's event, so nested/repeated spans within a step sum correctly.
"""

from deepspeed_tpu.telemetry.events import EventLog
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.spans import Span

_default_session = None


def get_default_session():
    """The process-default session, or None when telemetry is off."""
    return _default_session


def set_default_session(session, replace=True):
    """Install ``session`` as the process default. ``replace=False``
    keeps an already-installed session (first engine wins)."""
    global _default_session
    if _default_session is not None and not replace:
        return _default_session
    _default_session = session
    return session


class TelemetrySession:
    def __init__(self, registry=None, exporters=(), history=256):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.events = EventLog(exporters=exporters, history=history)
        self._phases = {}

    @classmethod
    def from_config(cls, tcfg):
        """Build a session from a validated ``TelemetryConfig``."""
        from deepspeed_tpu.telemetry.exporters import (
            ConsoleExporter, JsonlExporter, PrometheusTextfileExporter)
        registry = MetricsRegistry()
        exporters = []
        if tcfg.jsonl_path:
            exporters.append(JsonlExporter(tcfg.jsonl_path))
        if tcfg.console:
            exporters.append(ConsoleExporter())
        if tcfg.prometheus_textfile:
            exporters.append(PrometheusTextfileExporter(
                tcfg.prometheus_textfile, registry,
                write_every=tcfg.prometheus_write_every))
        return cls(registry=registry, exporters=exporters,
                   history=tcfg.history)

    # -- spans ---------------------------------------------------------
    def span(self, name):
        return Span(name, self)

    def _record_phase(self, name, path, duration_s):
        self._phases[name] = self._phases.get(name, 0.0) + duration_s
        self.registry.histogram(
            "phase_seconds", labels={"phase": name},
            help="host wall seconds per step phase").observe(duration_s)

    def drain_phases(self):
        """Per-phase seconds accumulated since the last drain (one step's
        phase breakdown); resets the accumulator."""
        phases, self._phases = self._phases, {}
        return phases

    # -- events --------------------------------------------------------
    def emit(self, event, **fields):
        self.registry.counter(
            "events_total", labels={"event": event},
            help="telemetry events emitted by type").inc()
        return self.events.emit(event, **fields)

    def step_event(self, **fields):
        """Emit one per-step event and update the step-level metrics."""
        wall = fields.get("wall_s")
        if wall is not None:
            self.registry.histogram(
                "step_seconds",
                help="end-to-end host wall seconds per optimizer step"
            ).observe(wall)
        if fields.get("loss") is not None:
            self.registry.gauge("loss", help="last step loss").set(
                fields["loss"])
        self.registry.counter("steps_total",
                              help="optimizer steps completed").inc()
        return self.emit("step", **fields)

    def close(self):
        self.events.close()
