"""Process-wide metrics registry: typed counters / gauges / histograms.

The runtime's measurement substrate (ISSUE 8): every host-side number
the engine, the resilience layer, or ``bench.py`` wants to report flows
through one of three metric kinds, each supporting labels the Prometheus
way — a *family* (one name, one kind, one help string) fans out into
per-label-set series, e.g. ``phase_seconds{phase="dispatch"}`` and
``phase_seconds{phase="device_wait"}`` are two series of one family.

All operations are plain-python dict updates on the hot path (no jax,
no I/O); exporters (`telemetry/exporters.py`) snapshot the registry when
they need to materialize it.
"""

import bisect
import threading

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# Log-spaced seconds buckets sized for host phases: sub-ms null-span
# noise up through multi-minute compiles. Prometheus-style upper bounds;
# +Inf is implicit.
DEFAULT_TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


def _label_key(labels):
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


class Counter:
    """Monotonic count (events, steps, retries)."""

    __slots__ = ("labels", "value")

    def __init__(self, labels=None):
        self.labels = dict(labels or {})
        self.value = 0.0

    def inc(self, n=1.0):
        if n < 0:
            raise ValueError(f"counter increments must be >= 0, got {n}")
        self.value += n

    def sample(self):
        return {"value": self.value}


class Gauge:
    """Last-write-wins scalar (loss, lr, queue depth)."""

    __slots__ = ("labels", "value")

    def __init__(self, labels=None):
        self.labels = dict(labels or {})
        self.value = 0.0

    def set(self, v):
        self.value = float(v)

    def inc(self, n=1.0):
        self.value += n

    def dec(self, n=1.0):
        self.value -= n

    def sample(self):
        return {"value": self.value}


class Histogram:
    """Distribution with count/sum/min/max plus fixed cumulative-style
    buckets (upper bounds; +Inf implicit) for the Prometheus exporter."""

    __slots__ = ("labels", "bounds", "bucket_counts", "count", "sum",
                 "min", "max")

    def __init__(self, labels=None, buckets=DEFAULT_TIME_BUCKETS):
        self.labels = dict(labels or {})
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.bucket_counts = [0] * len(self.bounds)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        i = bisect.bisect_left(self.bounds, v)
        if i < len(self.bounds):
            self.bucket_counts[i] += 1
        # past the last bound -> only the implicit +Inf bucket (== count)

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0

    def cumulative_buckets(self):
        """[(upper_bound, cumulative_count), ...] ending with +Inf."""
        out, running = [], 0
        for bound, n in zip(self.bounds, self.bucket_counts):
            running += n
            out.append((bound, running))
        out.append((float("inf"), self.count))
        return out

    def sample(self):
        return {"count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max, "mean": self.mean}


_KINDS = {COUNTER: Counter, GAUGE: Gauge, HISTOGRAM: Histogram}


class _Family:
    """One metric name: one kind, one help string, many label series."""

    __slots__ = ("name", "kind", "help", "buckets", "series")

    def __init__(self, name, kind, help="", buckets=None):
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets = buckets
        self.series = {}   # label_key -> metric instance

    def child(self, labels=None):
        key = _label_key(labels)
        metric = self.series.get(key)
        if metric is None:
            if self.kind == HISTOGRAM:
                metric = Histogram(labels,
                                   buckets=self.buckets or
                                   DEFAULT_TIME_BUCKETS)
            else:
                metric = _KINDS[self.kind](labels)
            self.series[key] = metric
        return metric


class MetricsRegistry:
    """Name -> typed metric family; get-or-create on access, so call
    sites never pre-register. Re-registering a name under a different
    kind is a bug and raises."""

    def __init__(self):
        self._families = {}
        self._lock = threading.Lock()

    def _family(self, name, kind, help="", buckets=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help, buckets=buckets)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}")
            return fam

    def counter(self, name, labels=None, help=""):
        return self._family(name, COUNTER, help).child(labels)

    def gauge(self, name, labels=None, help=""):
        return self._family(name, GAUGE, help).child(labels)

    def histogram(self, name, labels=None, help="", buckets=None):
        return self._family(name, HISTOGRAM, help,
                            buckets=buckets).child(labels)

    def snapshot(self):
        """JSON-friendly view of every series (tests, console export)."""
        out = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            out[fam.name] = {
                "kind": fam.kind,
                "help": fam.help,
                "series": [dict(labels=m.labels, **m.sample())
                           for m in fam.series.values()],
            }
        return out

    def to_prometheus(self, prefix="ds_tpu_"):
        """Prometheus text exposition format (textfile-collector ready)."""
        lines = []
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
        for fam in families:
            name = prefix + fam.name
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for metric in fam.series.values():
                lbl = _fmt_labels(metric.labels)
                if fam.kind == HISTOGRAM:
                    for bound, n in metric.cumulative_buckets():
                        le = "+Inf" if bound == float("inf") else repr(bound)
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels(metric.labels, le=le)} {n}")
                    lines.append(f"{name}_sum{lbl} {metric.sum}")
                    lines.append(f"{name}_count{lbl} {metric.count}")
                else:
                    lines.append(f"{name}{lbl} {metric.value}")
        return "\n".join(lines) + "\n"


def _fmt_labels(labels, le=None):
    items = sorted(labels.items())
    if le is not None:
        items = items + [("le", le)]
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in items)
    return "{" + body + "}"


def _escape(v):
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")
