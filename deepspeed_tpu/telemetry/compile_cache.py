"""Persistent-compilation-cache hit/miss counters.

jax's compiler records ``/jax/compilation_cache/cache_hits`` /
``cache_misses`` monitoring events whenever the persistent cache
(``compilation_cache_dir`` in the engine config) serves or misses a
lookup. This module installs one process-wide listener and exposes the
running counts so the engine's ``compile`` telemetry event (and the
tuner's rerun report) can show that a warmed cache produced near-zero
recompilation.

The listener is a no-op until :func:`install` is called — the engine
calls it exactly when it applies ``compilation_cache_dir`` — and
installing twice is safe.
"""

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_counts = {"hits": 0, "misses": 0}
_installed = False


def _listener(event, **kwargs):
    if event == _HIT_EVENT:
        _counts["hits"] += 1
    elif event == _MISS_EVENT:
        _counts["misses"] += 1


def install():
    """Register the monitoring listener (idempotent). Returns True when
    the listener is active, False when jax.monitoring is unavailable."""
    global _installed
    if _installed:
        return True
    try:
        from jax import monitoring
        monitoring.register_event_listener(_listener)
    except Exception:
        return False
    _installed = True
    return True


def counts():
    """``{"hits": int, "misses": int}`` accumulated since install()."""
    return dict(_counts)


def reset():
    """Zero the counters (test helper)."""
    _counts["hits"] = 0
    _counts["misses"] = 0
