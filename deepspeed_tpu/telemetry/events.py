"""Schema-versioned structured event log.

Every telemetry record is one flat-ish JSON object ("event") with a
fixed envelope::

    {"schema": "ds-tpu-telemetry/1",   # version tag, bump on breaking
     "event":  "step",                 # event type
     "t":      1756000000.123,        # unix seconds (host clock)
     ...payload fields per type...}

Event types the runtime emits (see docs/observability.md for the full
field tables): ``run_start``, ``compile`` (static facts stamped once —
collective bytes/counts, static peak memory), ``step`` (per-step
metrics + phase breakdown), ``recompile``, ``health_guard``,
``checkpoint_save`` / ``checkpoint_load``, ``elastic_resume``,
``preemption``, ``reshard``, and ``bench_step`` (bench.py).

``ds_tpu_audit --json`` embeds the same ``schema`` tag so audit findings
and telemetry events are joinable offline.

The log keeps a bounded in-memory ring (the engine re-exposes the step
slice as ``engine.metrics_history``) and fans each event out to the
configured exporters. Exporter failures are contained: telemetry must
never kill a training run, so a throwing exporter is disabled with one
warning instead of propagating.
"""

import collections
import threading
import time

from deepspeed_tpu.utils.logging import logger

SCHEMA_VERSION = "ds-tpu-telemetry/1"


class EventLog:
    """Bounded ring of events + exporter fan-out.

    ``emit`` serializes under a lock: the hang watchdog emits its
    ``watchdog`` event from its own daemon thread, and interleaved
    exporter writes would corrupt the JSONL line stream.
    """

    def __init__(self, exporters=(), history=256):
        self.exporters = list(exporters)
        self._ring = collections.deque(maxlen=int(history))
        self._dead = set()
        self._lock = threading.Lock()

    def emit(self, event, **fields):
        evt = {"schema": SCHEMA_VERSION, "event": event, "t": time.time()}
        evt.update(fields)
        with self._lock:
            self._ring.append(evt)
            for ex in self.exporters:
                if id(ex) in self._dead:
                    continue
                try:
                    ex.export(evt)
                except Exception as e:
                    self._dead.add(id(ex))
                    logger.warning(
                        f"telemetry: exporter {type(ex).__name__} failed "
                        f"({e}); disabling it for the rest of the run")
        return evt

    def recent(self, n=None, event=None):
        evts = list(self._ring)
        if event is not None:
            evts = [e for e in evts if e.get("event") == event]
        return evts if n is None else evts[-n:]

    def close(self):
        for ex in self.exporters:
            try:
                ex.close()
            except Exception:
                pass
