"""Hang watchdog + step-wall anomaly detection.

A hung collective is invisible from inside the step: every process
blocks in ``device_wait`` forever and nothing raises. The
:class:`HangWatchdog` is a daemon thread fed two ultra-cheap signals
from the training loop — ``step_start``/``step_end`` (the engine's
step boundary) and ``beat`` (every span transition, wired through
``TelemetrySession``) — that

1. learns a deadline from a **rolling median** of completed step walls
   (``deadline = max(min_deadline_s, deadline_factor * median)``),
2. writes a per-process **heartbeat file** each poll tick, so on a
   multi-host run every process can see how far its peers got, and
3. on expiry classifies the hang — ``this_host_stuck`` (we are the
   laggard) vs ``waiting_on_straggler`` (a peer is behind us; ranked)
   — emits a ``watchdog`` telemetry event, and dumps the flight
   record (`telemetry/flight.py`).

``action: "dump"`` (default) fires at most once per hung step and lets
the run continue if the step ever completes; ``action: "abort"`` prints
all thread stacks and terminates the process with SIGABRT so a cluster
supervisor can restart it.

:class:`StepAnomalyDetector` is the third forensics trigger: a
step-wall regression against the same rolling median arms the
``TraceProfiler`` to capture the next K steps (`runtime/engine.py`).
"""

import collections
import json
import math
import os
import signal
import socket
import sys
import threading
import time

from deepspeed_tpu.utils.logging import logger

WATCHDOG_ACTION_DUMP = "dump"
WATCHDOG_ACTION_ABORT = "abort"
WATCHDOG_ACTIONS = (WATCHDOG_ACTION_DUMP, WATCHDOG_ACTION_ABORT)

VERDICT_THIS_HOST = "this_host_stuck"
VERDICT_STRAGGLER = "waiting_on_straggler"

_HB_PREFIX = "hb-p"


def heartbeat_path(directory, process_index):
    return os.path.join(directory, f"{_HB_PREFIX}{int(process_index):05d}.json")


def _hb_index(name):
    """Process index encoded in a heartbeat filename, or None."""
    try:
        return int(name[len(_HB_PREFIX):-len(".json")])
    except ValueError:
        return None


# One bounded re-read before a heartbeat file is classified as
# unparseable. The writer is tmp+os.replace atomic, but a reader racing
# a slow replace (or a file torn by a mid-write kill that a healthy
# watchdog is about to overwrite) can observe truncated JSON once; a
# single retry separates "torn right now" from "torn forever" without
# letting a truly corrupt file stall the scan. ``_retry_sleep`` is a
# module hook so tests can repair/observe the file between the reads.
_TORN_RETRY_SLEEP_S = 0.05
_retry_sleep = time.sleep


def _read_heartbeat_file(path):
    """Parse one heartbeat file with one bounded re-read retry; None
    when both attempts fail."""
    for attempt in (0, 1):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            if attempt == 0:
                _retry_sleep(_TORN_RETRY_SLEEP_S)
    return None


def scan_heartbeats(directory, expected_count=None):
    """``(heartbeats, no_heartbeat)`` for ``directory``.

    ``heartbeats`` is every parseable per-process heartbeat file.
    ``no_heartbeat`` lists the processes that SHOULD have reported but
    did not — a half-written file (killed mid-``json.dump``, though the
    writer's tmp+``os.replace`` makes that rare; each file gets one
    bounded re-read via :func:`_read_heartbeat_file` before the
    ``unparseable`` verdict sticks), or, with ``expected_count``, an
    index in ``range(expected_count)`` with no file at all (the process
    died before its watchdog ever wrote).
    Each entry is ``{"process_index", "status": "no-heartbeat",
    "reason": "missing"|"unparseable"}`` — JSON-safe, so consumers
    (``classify``, ``ds_tpu_metrics``, the supervisor) can report the
    host instead of raising.
    """
    heartbeats = []
    unparseable = set()
    seen = set()
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        names = []
    for name in names:
        if not (name.startswith(_HB_PREFIX) and name.endswith(".json")):
            continue
        idx = _hb_index(name)
        hb = _read_heartbeat_file(os.path.join(directory, name))
        if hb is None:
            if idx is not None:
                unparseable.add(idx)
            continue
        if isinstance(hb, dict):
            heartbeats.append(hb)
            pi = hb.get("process_index")
            seen.add(idx if pi is None else pi)
        elif idx is not None:
            unparseable.add(idx)
    expected = range(int(expected_count)) if expected_count else \
        sorted(unparseable)
    no_heartbeat = [
        {"process_index": idx, "status": "no-heartbeat",
         "reason": "unparseable" if idx in unparseable else "missing"}
        for idx in expected if idx not in seen
    ]
    return heartbeats, no_heartbeat


def read_heartbeats(directory):
    """All parseable per-process heartbeat files in ``directory``."""
    return scan_heartbeats(directory)[0]


class HangWatchdog:
    """Daemon thread that turns "no progress" into a flight dump.

    The training-loop hooks (``step_start``/``step_end``/``beat``) are
    attribute stores only — no locks, no allocation — so the enabled
    steady-state overhead stays within the pinned <=1% budget.
    """

    def __init__(self, flight=None, deadline_factor=3.0, min_deadline_s=60.0,
                 action=WATCHDOG_ACTION_DUMP, heartbeat_dir=None,
                 process_index=0, process_count=1, hostname=None,
                 window=32, warmup_steps=2, poll_interval_s=None,
                 session=None, clock=time.monotonic):
        if action not in WATCHDOG_ACTIONS:
            raise ValueError(f"watchdog action must be one of "
                             f"{WATCHDOG_ACTIONS}, got {action!r}")
        self.flight = flight
        self.session = session
        self.deadline_factor = float(deadline_factor)
        self.min_deadline_s = float(min_deadline_s)
        self.action = action
        self.heartbeat_dir = heartbeat_dir
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.hostname = hostname or socket.gethostname()
        self.warmup_steps = max(1, int(warmup_steps))
        self._clock = clock
        self._walls = collections.deque(maxlen=int(window))
        if poll_interval_s is None:
            poll_interval_s = min(1.0, max(0.02, self.min_deadline_s / 10.0))
        self.poll_interval_s = float(poll_interval_s)
        # hot-path state (single-writer: the training thread)
        self._step = -1
        self._step_t0 = None
        self._beat_t = None
        self._beat_phase = None
        # watchdog-thread state
        self._fired_step = None
        self.fired = []          # record of firings (tests / postmortem)
        self._stop = threading.Event()
        self._thread = None

    # -- training-loop hooks (hot path: keep allocation-free) ----------
    def step_start(self, step):
        self._step = step
        self._beat_phase = "step"
        self._beat_t = self._step_t0 = self._clock()

    def step_end(self, step, wall_s):
        self._step_t0 = None
        self._beat_t = self._clock()
        self._walls.append(wall_s)

    def beat(self, phase):
        self._beat_phase = phase
        self._beat_t = self._clock()

    # -- deadline ------------------------------------------------------
    def median_wall(self):
        if not self._walls:
            return None
        walls = sorted(self._walls)
        n = len(walls)
        mid = n // 2
        if n % 2:
            return walls[mid]
        return 0.5 * (walls[mid - 1] + walls[mid])

    def deadline_s(self):
        """Current deadline, or None while fewer than ``warmup_steps``
        steps have completed (never fire on the compile step)."""
        if len(self._walls) < self.warmup_steps:
            return None
        return max(self.min_deadline_s,
                   self.deadline_factor * self.median_wall())

    # -- lifecycle -----------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="ds-tpu-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        self._write_heartbeat(final=True)

    def _run(self):
        while not self._stop.wait(self.poll_interval_s):
            try:
                self._write_heartbeat()
                self.check()
            except Exception as e:   # pragma: no cover - forensics never kills
                logger.warning("hang watchdog: poll failed (%s)", e)

    # -- heartbeat files -----------------------------------------------
    def _write_heartbeat(self, final=False):
        if not self.heartbeat_dir:
            return
        t0 = self._step_t0
        hb = {
            "t": time.time(),
            "hostname": self.hostname,
            "process_index": self.process_index,
            "pid": os.getpid(),
            "step": self._step,
            "phase": self._beat_phase,
            "in_step": t0 is not None and not final,
            "step_elapsed_s": round(self._clock() - t0, 3)
            if t0 is not None else 0.0,
        }
        try:
            os.makedirs(self.heartbeat_dir, exist_ok=True)
            path = heartbeat_path(self.heartbeat_dir, self.process_index)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(hb, f)
            os.replace(tmp, path)
        except OSError as e:   # pragma: no cover
            logger.warning("hang watchdog: heartbeat write failed (%s)", e)

    # -- firing --------------------------------------------------------
    def check(self, now=None):
        """One expiry check (the poll loop's body; callable from tests).
        Returns the firing record when the watchdog fires, else None."""
        t0 = self._step_t0
        if t0 is None:
            return None
        step = self._step
        if self._fired_step == step:
            return None
        deadline = self.deadline_s()
        if deadline is None:
            return None
        elapsed = (now if now is not None else self._clock()) - t0
        if elapsed <= deadline:
            return None
        self._fired_step = step
        verdict, stragglers = self.classify()
        fired = {
            "step": step,
            "phase": self._beat_phase,
            "elapsed_s": round(elapsed, 3),
            "deadline_s": round(deadline, 3),
            "median_wall_s": round(self.median_wall(), 6),
            "deadline_factor": self.deadline_factor,
            "verdict": verdict,
            "stragglers": stragglers,
            "action": self.action,
            "process_index": self.process_index,
            "hostname": self.hostname,
        }
        self.fired.append(fired)
        logger.warning(
            "hang watchdog: step %d stuck in %s for %.1fs "
            "(deadline %.1fs = max(%.1fs, %.1f x median %.3fs)) -> %s",
            step, self._beat_phase, elapsed, deadline, self.min_deadline_s,
            self.deadline_factor, self.median_wall() or 0.0, verdict)
        if self.session is not None:
            try:
                self.session.emit("watchdog", **fired)
            except Exception:   # pragma: no cover
                pass
        if self.flight is not None:
            self.flight.dump("watchdog", extra={"watchdog": fired})
        if self.action == WATCHDOG_ACTION_ABORT:
            self._abort()
        return fired

    def classify(self):
        """(verdict, stragglers): who to blame, from peer heartbeats.

        A peer is a straggler when it is on an earlier step, or on the
        same step with a beat at least half a deadline staler than ours
        — then we are ``waiting_on_straggler`` at the collective.
        Otherwise (no peers, or every peer at/above our step and fresh)
        the stall is local: ``this_host_stuck``. A peer with no
        parseable heartbeat at all (crashed before its watchdog ever
        wrote, or killed mid-write) is the prime suspect: it is listed
        first with ``status: "no-heartbeat"`` and null step fields.
        """
        if not self.heartbeat_dir or self.process_count <= 1:
            return VERDICT_THIS_HOST, []
        now = time.time()
        grace = 0.5 * (self.deadline_s() or self.min_deadline_s)
        mine = None
        peers = []
        heartbeats, no_heartbeat = scan_heartbeats(
            self.heartbeat_dir, expected_count=self.process_count)
        for hb in heartbeats:
            if hb.get("process_index") == self.process_index:
                mine = hb
            else:
                peers.append(hb)
        my_step = self._step
        my_age = now - mine["t"] if mine else 0.0
        stragglers = [
            {"process_index": gone["process_index"], "hostname": None,
             "step": None, "behind_steps": None, "phase": None,
             "beat_age_s": None, "status": "no-heartbeat",
             "reason": gone["reason"]}
            for gone in no_heartbeat
            if gone["process_index"] != self.process_index
        ]
        for hb in peers:
            step = hb.get("step", -1)
            age = now - hb.get("t", now)
            behind_steps = my_step - step
            if behind_steps > 0 or (behind_steps == 0 and
                                    age > my_age + grace):
                stragglers.append({
                    "process_index": hb.get("process_index"),
                    "hostname": hb.get("hostname"),
                    "step": step,
                    "behind_steps": behind_steps,
                    "phase": hb.get("phase"),
                    "beat_age_s": round(age, 3),
                })
        stragglers.sort(key=lambda s: (s.get("status") == "no-heartbeat",
                                       s.get("behind_steps") or 0,
                                       s.get("beat_age_s") or 0.0),
                        reverse=True)
        if stragglers:
            return VERDICT_STRAGGLER, stragglers
        return VERDICT_THIS_HOST, []

    def _abort(self):   # pragma: no cover - terminates the process
        try:
            import faulthandler
            faulthandler.dump_traceback(file=sys.stderr)
        except Exception:
            pass
        logger.error("hang watchdog: action=abort, raising SIGABRT")
        os.kill(os.getpid(), signal.SIGABRT)


class StepAnomalyDetector:
    """Rolling-baseline step-wall regression detector.

    ``observe(wall_s)`` returns a reason string when this step's wall
    exceeds ``factor`` x the rolling median of the previous ``window``
    steps (after ``min_history`` clean steps — the compile step never
    trips it), else None. The engine maps a trip — plus recompiles and
    guard trips, which arrive through their own emit sites — onto
    ``TraceProfiler.arm()``.
    """

    def __init__(self, factor=2.0, window=32, min_history=5):
        self.factor = float(factor)
        self.min_history = max(2, int(min_history))
        self._walls = collections.deque(maxlen=int(window))

    def observe(self, wall_s):
        walls = sorted(self._walls)
        reason = None
        if len(walls) >= self.min_history:
            mid = len(walls) // 2
            median = walls[mid] if len(walls) % 2 else \
                0.5 * (walls[mid - 1] + walls[mid])
            if median > 0 and wall_s > self.factor * median and \
                    math.isfinite(wall_s):
                reason = (f"step wall {wall_s * 1e3:.1f}ms > "
                          f"{self.factor:g} x median {median * 1e3:.1f}ms")
        # a regressed wall still enters the baseline: a real plateau
        # shift re-baselines instead of tripping forever
        self._walls.append(wall_s)
        return reason
