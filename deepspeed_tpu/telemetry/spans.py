"""Step-phase spans: nestable wall-time scopes correlated with xplane.

``session.span("dispatch")`` is a context manager that (1) records the
scope's wall seconds into the session's per-step phase accumulator and
the ``phase_seconds{phase=...}`` histogram, and (2) opens a
``jax.profiler.TraceAnnotation`` so the same scope shows up as a named
range in an xprof/xplane trace captured by ``TraceProfiler`` — host
phases and device timelines line up in one view.

Spans nest: the engine's offload host-Adam phase runs inside the
``dispatch`` span, and ``Span.path`` carries the full ``a/b`` nesting
path (per-thread). Exit is exception-safe — a phase that raises still
records its duration and closes its annotation before re-raising.

The disabled fast path is :func:`null_span`: a module-level singleton
whose ``__enter__``/``__exit__`` do nothing, so an engine with telemetry
off pays one attribute check + one no-op context manager per phase
(pinned by the overhead micro-benchmark test).

Every thread's span stack is also registered in a process-global map so
the forensics layer (`telemetry/flight.py`, `telemetry/watchdog.py`)
can read *other* threads' in-flight phase paths — thread-locals are
invisible cross-thread, and "which phase is the main thread stuck in"
is exactly what a hang dump must answer. :func:`live_phase_paths`
snapshots that map.
"""

import threading
import time

try:                                     # annotations are optional:
    from jax.profiler import TraceAnnotation   # telemetry must work in
except Exception:                        # jax-less tools (the CLI).
    TraceAnnotation = None

_local = threading.local()
# thread ident -> that thread's live span stack (the same list object
# _local.stack aliases); entries for exited threads are pruned on read
_live_stacks = {}


def _stack():
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
        _live_stacks[threading.get_ident()] = stack
    return stack


def live_phase_paths():
    """``{thread_ident: "a/b" in-flight span path}`` for every thread
    currently inside at least one span. Reads are lock-free snapshots:
    a concurrently-mutating stack at worst yields a one-frame-stale
    path, which is fine for forensics."""
    live = {t.ident for t in threading.enumerate()}
    out = {}
    for ident, stack in list(_live_stacks.items()):
        if ident not in live:
            _live_stacks.pop(ident, None)
            continue
        path = "/".join(stack)
        if path:
            out[ident] = path
    return out


class Span:
    """One timed, annotated scope. Created via ``TelemetrySession.span``."""

    __slots__ = ("name", "path", "duration_s", "_session", "_t0",
                 "_annotation")

    def __init__(self, name, session=None):
        self.name = name
        self.path = name
        self.duration_s = None
        self._session = session
        self._t0 = None
        self._annotation = None

    def __enter__(self):
        stack = _stack()
        stack.append(self.name)
        self.path = "/".join(stack)
        if TraceAnnotation is not None:
            self._annotation = TraceAnnotation(f"ds_tpu/{self.path}")
            self._annotation.__enter__()
        if self._session is not None:
            self._session._enter_phase(self.name, self.path)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration_s = time.perf_counter() - self._t0
        try:
            if self._annotation is not None:
                self._annotation.__exit__(exc_type, exc, tb)
        finally:
            stack = _stack()
            if stack and stack[-1] == self.name:
                stack.pop()
            if self._session is not None:
                self._session._record_phase(self.name, self.path,
                                            self.duration_s)
        return False   # never swallow the phase's exception


class _NullSpan:
    """Singleton no-op context manager — the telemetry-off fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


def null_span(name=None):
    """Drop-in for ``session.span`` when telemetry is disabled."""
    return _NULL_SPAN
