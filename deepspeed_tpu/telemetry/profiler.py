"""Device-time profiling: jax.profiler trace capture + per-step timings.

The reference's tracing story is host timers around engine phases plus
CUDA-event kernel timers (`utils/timer.py:26-104`, `csrc/includes/
StopWatch.h`); SURVEY §5.1 names the TPU equivalents: ``jax.profiler``
traces for xprof/tensorboard, synchronized host timers, and per-step
device-time deltas. This module supplies the trace capture and the
per-step record; `telemetry/timers.py` supplies the synchronized timers.

Moved here from ``deepspeed_tpu/utils/profiler.py`` (now a deprecation
shim): the trace window is the device half of the telemetry story — the
step-phase spans (`telemetry/spans.py`) emit ``TraceAnnotation``s, so a
trace captured by this window shows the host phases as named ranges on
the xplane timeline.

Config surface (engine ``wall_clock_breakdown`` drives the timers; this is
the trace window)::

    "profiling": {
        "trace_dir": "/tmp/tpu_trace",   # where xprof events go
        "trace_start_step": 10,           # first traced optimizer step
        "trace_num_steps": 3              # how many steps to capture
    }

The trace is viewable with tensorboard's profile plugin or xprof.
"""

import collections

from deepspeed_tpu.utils.logging import log_dist


_KNOWN_KEYS = ("trace_dir", "trace_start_step", "trace_num_steps",
               "history")


class TraceProfiler:
    """Captures a ``jax.profiler`` trace for a configured step window and
    keeps a rolling record of synchronized per-step durations."""

    def __init__(self, trace_dir=None, trace_start_step=0,
                 trace_num_steps=0, history=100, **unknown):
        if unknown:
            raise ValueError(
                f"unknown 'profiling' config keys {sorted(unknown)}; "
                f"supported: {list(_KNOWN_KEYS)}")
        self.trace_dir = trace_dir
        self.start_step = int(trace_start_step)
        self.num_steps = int(trace_num_steps)
        self._active = False
        self.armed_reason = None
        self.step_times = collections.deque(maxlen=history)

    @property
    def enabled(self):
        return self.trace_dir is not None and self.num_steps > 0

    def arm(self, start_step, num_steps, trace_dir=None, reason=None):
        """(Re-)point the capture window at a future step — the
        anomaly-triggered capture path (step-wall regression, recompile,
        guard trip arm the *next* ``num_steps`` steps). Re-arming after
        a window closed is supported; an in-flight window is never
        disturbed. Returns True when armed."""
        if self._active:
            return False
        if trace_dir is not None:
            self.trace_dir = str(trace_dir)
        if self.trace_dir is None or int(num_steps) <= 0:
            return False
        self.start_step = int(start_step)
        self.num_steps = int(num_steps)
        self.armed_reason = reason
        log_dist(f"profiler: armed {self.num_steps}-step trace window at "
                 f"step {self.start_step}"
                 f"{f' ({reason})' if reason else ''} -> {self.trace_dir}",
                 ranks=[0])
        return True

    def in_window(self, global_step):
        """True only for steps inside the trace window — the engine syncs
        per-step timing for these (plus wall_clock_breakdown runs), NOT
        for the whole run."""
        return self.enabled and (
            self.start_step <= global_step <
            self.start_step + self.num_steps)

    def before_step(self, global_step):
        if not self.enabled or self._active:
            return
        if self.in_window(global_step):
            import atexit
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self._active = True
            # a window past the end of the run still flushes xprof files
            atexit.register(self.close)
            log_dist(f"profiler: trace started at step {global_step} "
                     f"-> {self.trace_dir}", ranks=[0])

    def after_step(self, global_step, duration=None):
        if duration is not None:
            self.step_times.append(duration)
        if self._active and \
                global_step >= self.start_step + self.num_steps - 1:
            self.close(global_step)

    def close(self, global_step=None):
        """Stop an in-flight trace (idempotent) — also called at interpreter
        exit so a run ending inside the window still flushes xprof files."""
        if not self._active:
            return
        import jax

        jax.profiler.stop_trace()
        self._active = False
        log_dist(f"profiler: trace stopped"
                 f"{f' after step {global_step}' if global_step is not None else ''}",
                 ranks=[0])

    def summary(self):
        """(mean, min, max) of recorded synchronized step seconds."""
        if not self.step_times:
            return None
        ts = list(self.step_times)
        return sum(ts) / len(ts), min(ts), max(ts)


def device_report(out=None):
    """Print the device/mesh/ICI picture (`ds_tpu_report`): platform,
    chip kind, per-device coords — the topology a mesh maps onto."""
    import sys

    out = out or sys.stdout
    try:
        import jax

        devices = jax.devices()
    except Exception as e:  # backend unavailable — report, don't crash
        print(f"devices: unavailable ({e})", file=out)
        return
    print("-" * 64, file=out)
    print("device / interconnect topology", file=out)
    print("-" * 64, file=out)
    print(f"{'platform':.<30} {devices[0].platform}", file=out)
    print(f"{'device kind':.<30} {devices[0].device_kind}", file=out)
    print(f"{'local devices':.<30} {len(jax.local_devices())}", file=out)
    print(f"{'global devices':.<30} {len(devices)}", file=out)
    print(f"{'processes':.<30} {jax.process_count()}", file=out)
    for d in devices[:16]:
        coords = getattr(d, "coords", None)
        core = getattr(d, "core_on_chip", None)
        extra = f" coords={coords}" if coords is not None else ""
        extra += f" core={core}" if core is not None else ""
        print(f"  device {d.id}: process={d.process_index}{extra}",
              file=out)
    if len(devices) > 16:
        print(f"  ... {len(devices) - 16} more", file=out)
