"""Wall-clock and throughput timers.

TPU-native analog of the reference's ``deepspeed/utils/timer.py``
(`utils/timer.py:26,106`): named synchronized timers and a throughput timer.
Where the reference calls ``torch.cuda.synchronize()`` before reading the
clock, we block on outstanding async XLA dispatch with
``jax.effects_barrier()`` (device work in JAX is async-dispatched; the barrier
is the TPU-correct way to make host wall-clock measurements meaningful).

Moved here from ``deepspeed_tpu/utils/timer.py`` (now a deprecation
shim) as part of the unified telemetry package — these are the
*synchronized* timers behind ``wall_clock_breakdown``; the un-synchronized
per-phase spans live in `telemetry/spans.py`.
"""

import time

from deepspeed_tpu.utils.logging import logger, log_dist


def _synchronize():
    try:
        import jax
        # Drains the async dispatch queue on all local devices.
        jax.effects_barrier()
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Group of named timers, synchronized against async device execution."""

    class Timer:
        def __init__(self, name):
            self.name_ = name
            self.elapsed_ = 0.0
            self.started_ = False
            self.start_time = time.time()

        def start(self):
            assert not self.started_, f"timer {self.name_} has already been started"
            _synchronize()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False):
            assert self.started_, f"timer {self.name_} is not started"
            _synchronize()
            if reset:
                self.elapsed_ = time.time() - self.start_time
            else:
                self.elapsed_ += time.time() - self.start_time
            self.started_ = False

        def reset(self):
            self.elapsed_ = 0.0
            self.started_ = False

        def elapsed(self, reset=True):
            started_ = self.started_
            if self.started_:
                self.stop()
            elapsed_ = self.elapsed_
            if reset:
                self.reset()
            if started_:
                self.start()
            return elapsed_

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        """Per-device memory report (HBM analog of the CUDA alloc stats)."""
        try:
            import jax
            parts = []
            for d in jax.local_devices():
                stats = d.memory_stats() or {}
                in_use = stats.get("bytes_in_use", 0)
                limit = stats.get("bytes_limit", 0)
                parts.append(f"{d}: in_use {in_use / 2**30:.2f}GB "
                             f"limit {limit / 2**30:.2f}GB")
            return " | ".join(parts)
        except Exception:
            return "memory stats unavailable"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        if memory_breakdown:
            string += " | " + self.memory_usage()
        log_dist(string, ranks=ranks or [0])


class ThroughputTimer:
    """Samples/sec tracker printed every ``steps_per_output`` steps."""

    def __init__(self,
                 batch_size,
                 num_workers,
                 start_step=2,
                 steps_per_output=50,
                 monitor_memory=False,
                 logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = batch_size if batch_size else 1
        self.num_workers = num_workers
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            _synchronize()
            self.start_time = time.time()

    def stop(self, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        self.global_step_count += 1
        if self.start_time > 0:
            _synchronize()
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            if report_speed and self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"{self.global_step_count}/{self.micro_step_count}, "
                    f"SamplesPerSec={self.avg_samples_per_sec():.4f}")
                if self.monitor_memory:
                    self.logging(SynchronizedWallClockTimer.memory_usage())

    def avg_samples_per_sec(self):
        if self.global_step_count > 0 and self.total_elapsed_time > 0:
            samples_per_step = self.batch_size * self.num_workers
            total_step_offset = self.global_step_count - self.start_step
            avg_time_per_step = self.total_elapsed_time / max(total_step_offset, 1)
            return samples_per_step / avg_time_per_step
        return float("-inf")
