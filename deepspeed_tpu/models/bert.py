"""BERT model family, TPU-first, built on DeepSpeedTransformerLayer.

The reference's flagship workload is BERT pretraining with the fused CUDA
transformer kernel (`docs/_tutorials/bert-pretraining.md`; in-repo fixtures
`tests/unit/modeling.py:1578` / `modelingpreln.py:1673` are the post-LN and
pre-LN HF-style variants). This module is the equivalent in-framework
model: embeddings + N fused blocks + MLM head, post-LN (classic BERT) or
pre-LN, bf16-ready, with tensor-parallel PartitionSpecs over the ``model``
mesh axis.
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn
from flax.traverse_util import flatten_dict, unflatten_dict
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.0
    attention_probs_dropout_prob: float = 0.0
    initializer_range: float = 0.02
    pre_layer_norm: bool = False      # classic BERT is post-LN
    dtype: Any = jnp.float32
    use_flash_attention: bool = False
    # SparsityConfig instance → every layer's attention goes block-sparse
    # (the SparseAttentionUtils adoption path; heads must match).
    sparse_attention: Optional[Any] = None
    loss_chunk: int = 0           # >0: chunked MLM cross-entropy (the
    #                               [B, T, 30522] logits never materialize)


def bert_base(**kw):
    return BertConfig(**kw)


def bert_large(**kw):
    kw.setdefault("hidden_size", 1024)
    kw.setdefault("num_hidden_layers", 24)
    kw.setdefault("num_attention_heads", 16)
    kw.setdefault("intermediate_size", 4096)
    return BertConfig(**kw)


def bert_tiny(**kw):
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    return BertConfig(**kw)


def _ds_layer_config(cfg: BertConfig) -> DeepSpeedTransformerConfig:
    return DeepSpeedTransformerConfig(
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        heads=cfg.num_attention_heads,
        attn_dropout_ratio=cfg.attention_probs_dropout_prob,
        hidden_dropout_ratio=cfg.hidden_dropout_prob,
        num_hidden_layers=cfg.num_hidden_layers,
        initializer_range=cfg.initializer_range,
        pre_layer_norm=cfg.pre_layer_norm,
        fp16=cfg.dtype == jnp.float16)


class BertEmbeddings(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, deterministic=True):
        cfg = self.config
        B, T = input_ids.shape
        word = self.param("word_embeddings",
                          nn.initializers.normal(cfg.initializer_range),
                          (cfg.vocab_size, cfg.hidden_size))
        pos = self.param("position_embeddings",
                         nn.initializers.normal(cfg.initializer_range),
                         (cfg.max_position_embeddings, cfg.hidden_size))
        tok = self.param("token_type_embeddings",
                         nn.initializers.normal(cfg.initializer_range),
                         (cfg.type_vocab_size, cfg.hidden_size))
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = word[input_ids] + pos[None, :T] + tok[token_type_ids]
        x = nn.LayerNorm(epsilon=1e-12, name="LayerNorm")(x)
        x = nn.Dropout(cfg.hidden_dropout_prob)(x, deterministic)
        return x.astype(cfg.dtype)


class BertModel(nn.Module):
    """Embeddings + encoder stack of DeepSpeedTransformerLayers."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        x = BertEmbeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, deterministic)
        additive_mask = None
        if attention_mask is not None:
            additive_mask = jnp.where(
                attention_mask.astype(bool), 0.0, -10000.0
            )[:, None, None, :].astype(jnp.float32)
        ds_cfg = _ds_layer_config(cfg)
        for i in range(cfg.num_hidden_layers):
            x = DeepSpeedTransformerLayer(
                ds_cfg, use_flash_attention=cfg.use_flash_attention,
                sparsity_config=cfg.sparse_attention,
                name=f"layer_{i}")(x, additive_mask, deterministic)
        return x


class BertForMaskedLM(nn.Module):
    """MLM head over the encoder (BERT-pretraining objective — the
    reference's bert-pretraining workload)."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True, return_hidden=False):
        cfg = self.config
        x = BertModel(cfg, name="bert")(
            input_ids, attention_mask, token_type_ids, deterministic)
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="transform")(x)
        x = jax.nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype,
                         name="transform_ln")(x)
        if return_hidden:
            return x    # chunked-loss path applies the decoder itself
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype, name="decoder")(x)
        return logits


def make_bert_mlm_loss_fn(model: BertForMaskedLM):
    """loss_fn(params, batch, rng): batch has input_ids [B,T], labels [B,T]
    with -100 at unmasked positions, optional attention_mask [B,T].

    With ``config.loss_chunk > 0`` the [B, T, vocab] logits never
    materialize (chunked CE over the decoder head — see
    models/gpt2.py:chunked_cross_entropy_with_head)."""
    from deepspeed_tpu.models.gpt2 import (
        chunked_cross_entropy_with_head, cross_entropy_loss)

    def loss_fn(params, batch, rng=None):
        rngs = {"dropout": rng} if rng is not None else {}
        chunk = model.config.loss_chunk
        if chunk:
            hidden = model.apply(
                {"params": params}, batch["input_ids"],
                batch.get("attention_mask"), batch.get("token_type_ids"),
                deterministic=rng is None, rngs=rngs, return_hidden=True)
            total, count = chunked_cross_entropy_with_head(
                hidden, params["decoder"]["kernel"],
                params["decoder"]["bias"], batch["labels"], chunk)
            return total / jnp.maximum(count, 1)
        logits = model.apply(
            {"params": params}, batch["input_ids"],
            batch.get("attention_mask"), batch.get("token_type_ids"),
            deterministic=rng is None, rngs=rngs)
        return cross_entropy_loss(logits, batch["labels"])

    return loss_fn


class BertForQuestionAnswering(nn.Module):
    """Span-prediction head over the encoder — the reference's
    BingBertSquad fine-tuning workload (its e2e accuracy gate,
    `tests/model/BingBertSquad/test_e2e_squad.py`). Outputs
    (start_logits, end_logits), each [B, T]."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        x = BertModel(cfg, name="bert")(
            input_ids, attention_mask, token_type_ids, deterministic)
        logits = nn.Dense(2, dtype=cfg.dtype, name="qa_outputs")(x)
        start, end = jnp.split(logits, 2, axis=-1)
        return start.squeeze(-1), end.squeeze(-1)


def make_bert_qa_loss_fn(model: BertForQuestionAnswering):
    """loss_fn(params, batch, rng): batch has input_ids [B,T],
    start_positions/end_positions [B] token indices, optional
    attention_mask — mean of start/end cross-entropies (SQuAD training
    objective)."""
    from deepspeed_tpu.models.gpt2 import cross_entropy_loss

    def loss_fn(params, batch, rng=None):
        rngs = {"dropout": rng} if rng is not None else {}
        start_logits, end_logits = model.apply(
            {"params": params}, batch["input_ids"],
            batch.get("attention_mask"), batch.get("token_type_ids"),
            deterministic=rng is None, rngs=rngs)
        start_loss = cross_entropy_loss(start_logits,
                                        batch["start_positions"])
        end_loss = cross_entropy_loss(end_logits, batch["end_positions"])
        return 0.5 * (start_loss + end_loss)

    return loss_fn


def init_bert_params(model, rng, batch_size=2, seq_len=16):
    dummy = jnp.zeros((batch_size, seq_len), jnp.int32)
    return model.init({"params": rng, "dropout": rng}, dummy)["params"]


def bert_partition_specs(params, model_axis="model"):
    """Megatron-style TP specs over the ``model`` axis: QKV/intermediate
    column-parallel, output projections row-parallel, embeddings
    vocab-sharded."""
    flat = flatten_dict(params)
    specs = {}
    for path, leaf in flat.items():
        name = "/".join(str(p) for p in path)
        ndim = getattr(leaf, "ndim", 0)
        if ndim <= 1:
            specs[path] = P()
        elif name.endswith("word_embeddings"):
            specs[path] = P(model_axis, None)
        elif "attn_qkvw" in name or "inter_w" in name:
            specs[path] = P(None, model_axis)
        elif "attn_ow" in name or "output_w" in name:
            specs[path] = P(model_axis, None)
        elif "decoder" in name and name.endswith("kernel"):
            specs[path] = P(None, model_axis)
        else:
            specs[path] = P()
    return unflatten_dict(specs)
