"""GPT-2 as a PipelineModule: the pipelined flagship.

The reference's pipeline examples wrap Megatron GPT-2 layers in
``LayerSpec``s (SURVEY §2.1 PP row); this is the in-tree equivalent:
embedding prologue (tied with the LM head, the reference's
``TiedLayerSpec`` pattern at `pipe/module.py:71`), a homogeneous stack of
transformer blocks that the engine shards over the ``pipe`` axis, and a
final-norm + tied-head epilogue.
"""

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.models.gpt2 import (
    Block,
    GPT2Config,
    cross_entropy_sum_and_count,
)
from deepspeed_tpu.runtime.pipe.module import (
    LayerSpec,
    PipelineModule,
    TiedLayerSpec,
)


class GPT2Embed:
    """Prologue layer: token ids → hidden states. Owns the tied wte/wpe."""

    def __init__(self, config: GPT2Config):
        self.config = config

    def init(self, rng, micro):
        cfg = self.config
        k1, k2 = jax.random.split(rng)
        return {
            "wte": nn.initializers.normal(0.02)(
                k1, (cfg.vocab_size, cfg.n_embd), cfg.param_dtype),
            "wpe": nn.initializers.normal(0.01)(
                k2, (cfg.n_positions, cfg.n_embd), cfg.param_dtype),
        }

    def apply(self, params, micro, rng=None):
        cfg = self.config
        ids = micro["input_ids"]
        T = ids.shape[1]
        x = params["wte"][ids].astype(cfg.dtype) + \
            params["wpe"][None, :T].astype(cfg.dtype)
        if cfg.dropout > 0 and rng is not None:
            keep = 1.0 - cfg.dropout
            mask = jax.random.bernoulli(rng, keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0).astype(cfg.dtype)
        return x


def tied_lm_head(params, x):
    """Epilogue forward for the tied embedding: logits = x @ wte^T
    (``TiedLayerSpec.forward_fn``)."""
    return x @ params["wte"].T.astype(x.dtype)


class GPT2BlockLayer:
    """One transformer block in the homogeneous pipelined body."""

    def __init__(self, config: GPT2Config):
        self.config = config
        self.module = Block(config)

    def init(self, rng, x):
        return self.module.init({"params": rng}, x)["params"]

    def apply(self, params, x, rng=None):
        rngs = {"dropout": rng} if rng is not None else {}
        return self.module.apply({"params": params}, x,
                                 deterministic=rng is None, rngs=rngs)


class GPT2FinalNorm:
    """Epilogue ln_f."""

    def __init__(self, config: GPT2Config):
        self.config = config
        self.module = nn.LayerNorm(dtype=config.dtype)

    def init(self, rng, x):
        return self.module.init({"params": rng}, x)["params"]

    def apply(self, params, x, rng=None):
        return self.module.apply({"params": params}, x)


def gpt2_pipe_loss(logits, micro):
    """Per-microbatch LM loss as (sum, token count): the weighted form makes
    the pipeline's global average exact under uneven ignore-index masks."""
    input_ids = micro["input_ids"]
    labels = micro.get("labels")
    if labels is None:
        labels = jnp.concatenate(
            [input_ids[:, 1:],
             jnp.full((input_ids.shape[0], 1), -100, input_ids.dtype)],
            axis=1)
    return cross_entropy_sum_and_count(logits, labels)


def gpt2_pipeline_module(config: GPT2Config,
                         num_stages=None,
                         seq_len=None,
                         activation_checkpoint_interval=0,
                         seed_layers=False) -> PipelineModule:
    """Spec list: [tied embed] + n_layer × [block] + [ln_f, tied head]."""
    T = seq_len or min(config.n_positions, 64)
    specs = [TiedLayerSpec("embed", GPT2Embed, config)]
    specs += [LayerSpec(GPT2BlockLayer, config)
              for _ in range(config.n_layer)]
    specs += [LayerSpec(GPT2FinalNorm, config),
              TiedLayerSpec("embed", GPT2Embed, config,
                            forward_fn=tied_lm_head)]
    example = {"input_ids": np.zeros((2, T), np.int32)}
    return PipelineModule(layers=specs,
                          num_stages=num_stages,
                          loss_fn=gpt2_pipe_loss,
                          seed_layers=seed_layers,
                          partition_method="uniform",
                          activation_checkpoint_interval=(
                              activation_checkpoint_interval),
                          example_input=example)
