"""GPT-2 MoE variant: expert-parallel FFNs on alternating blocks.

The mixture-of-experts flagship for the ``expert`` mesh axis (beyond the
v0.3.2 reference, which has no MoE). Dense blocks reuse
`models/gpt2.py`; MoE blocks replace the MLP with
:class:`deepspeed_tpu.moe.MoE` and the loss carries the load-balancing
auxiliary term.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import flax.linen as nn
from flax.traverse_util import flatten_dict, unflatten_dict
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.gpt2 import (
    Block, CausalSelfAttention, GPT2Config, cross_entropy_loss)
from deepspeed_tpu.moe.layer import MoE, MoEConfig, moe_param_spec


@dataclasses.dataclass(frozen=True)
class GPT2MoEConfig(GPT2Config):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2            # every Nth block is MoE (GShard style)
    aux_loss_weight: float = 0.01


def gpt2_moe_tiny(**kw):
    kw.setdefault("vocab_size", 256)
    kw.setdefault("n_positions", 64)
    kw.setdefault("n_embd", 64)
    kw.setdefault("n_layer", 2)
    kw.setdefault("n_head", 4)
    kw.setdefault("num_experts", 4)
    return GPT2MoEConfig(**kw)


class MoEBlock(nn.Module):
    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        moe_cfg = MoEConfig(num_experts=cfg.num_experts, top_k=cfg.top_k,
                            capacity_factor=cfg.capacity_factor,
                            aux_loss_weight=cfg.aux_loss_weight,
                            dtype=cfg.dtype)
        x = x + CausalSelfAttention(cfg, name="attn")(
            nn.LayerNorm(dtype=cfg.dtype, name="ln_1")(x), deterministic)
        y, aux = MoE(moe_cfg, hidden_dim=4 * cfg.n_embd, name="moe")(
            nn.LayerNorm(dtype=cfg.dtype, name="ln_2")(x), deterministic)
        return x + y, aux


class GPT2MoELMHead(nn.Module):
    """Decoder LM with MoE FFNs every ``moe_every`` blocks. Returns
    (logits, total_aux_loss)."""
    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, input_ids, deterministic=True):
        cfg = self.config
        B, T = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.n_positions, cfg.n_embd), cfg.param_dtype)
        x = wte[input_ids].astype(cfg.dtype) + wpe[None, :T].astype(cfg.dtype)
        aux_total = jnp.asarray(0.0, jnp.float32)
        for i in range(cfg.n_layer):
            if cfg.moe_every > 0 and i % cfg.moe_every == cfg.moe_every - 1:
                x, aux = MoEBlock(cfg, name=f"h_{i}")(x, deterministic)
                aux_total = aux_total + aux
            else:
                x = Block(cfg, name=f"h_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        logits = x @ wte.T.astype(cfg.dtype)
        return logits, aux_total


def make_gpt2_moe_loss_fn(model: GPT2MoELMHead):
    """Cross-entropy + load-balancing aux loss."""

    def loss_fn(params, batch, rng=None):
        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [input_ids[:, 1:],
                 jnp.full((input_ids.shape[0], 1), -100, input_ids.dtype)],
                axis=1)
        rngs = {"dropout": rng} if rng is not None else {}
        logits, aux = model.apply({"params": params}, input_ids,
                                  deterministic=rng is None, rngs=rngs)
        return cross_entropy_loss(logits, labels) + aux

    return loss_fn


def init_gpt2_moe_params(model, rng, batch_size=2, seq_len=None):
    cfg = model.config
    T = seq_len or min(cfg.n_positions, 64)
    dummy = jnp.zeros((batch_size, T), jnp.int32)
    return model.init({"params": rng}, dummy)["params"]


def gpt2_moe_partition_specs(params, expert_axis="expert",
                             model_axis="model"):
    """TP specs for dense weights (as `gpt2_partition_specs`) + expert-axis
    sharding for the MoE banks."""
    from deepspeed_tpu.models.gpt2 import gpt2_partition_specs
    base = flatten_dict(gpt2_partition_specs(params, model_axis=model_axis))
    flat = flatten_dict(params)
    specs = {}
    for path, leaf in flat.items():
        name = path[-1]
        if "moe" in path:
            specs[path] = moe_param_spec(name, leaf,
                                         expert_axis=expert_axis)
        else:
            specs[path] = base[path]
    return unflatten_dict(specs)
