"""GPT-2 model family, TPU-first.

The reference ships no in-tree GPT-2 (its perf harness drives Megatron-GPT2
externally, `tests/model/Megatron_GPT2/run_perf_baseline.py:18-60`); this
module provides the equivalent flagship decoder for the framework's
benchmarks: sizes matching the reference perf configs (125M … 1.5B),
bf16 compute over fp32 masters, optional rematerialization, and
Megatron-style tensor-parallel PartitionSpecs over the ``model`` mesh axis.
"""

import dataclasses
import functools
import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn
from flax.traverse_util import flatten_dict, unflatten_dict
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.fp8 import fp8_dot_general


def _fp8_dot(site):
    """Per-site ``dot_general`` hook: plain ``lax.dot_general`` unless an
    ``fp8_scope`` is active at trace time (the head matmul and attention
    einsums stay full precision — the standard fp8 recipe)."""
    return functools.partial(fp8_dot_general, site=site)


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16        # compute dtype (MXU-native)
    param_dtype: Any = jnp.float32   # master param dtype
    remat: bool = False              # activation checkpointing per block
    remat_policy: str = "full"       # "full" | "dots" | "nothing":
    #                                  full = save only block inputs;
    #                                  dots = save matmul outputs
    #                                  (jax.checkpoint_policies.
    #                                  checkpoint_dots) — recompute just
    #                                  the elementwise/softmax tails, the
    #                                  usual best trade on TPU where bwd
    #                                  is HBM-bound; nothing = save all
    #                                  (policy-form of remat=False)
    use_flash_attention: bool = False  # Pallas flash-attention kernel
    loss_chunk: int = 0              # >0: chunked cross-entropy over the
    #                                  vocab head (never materializes the
    #                                  [B, T, vocab] logits in HBM)
    scan_layers: bool = False        # stack the Blocks into one lax.scan
    #                                  over layer-stacked params: the HLO
    #                                  carries ONE block body instead of
    #                                  n_layer copies, collapsing trace +
    #                                  compile wall and HLO size (the
    #                                  autotuner's inner loop is a
    #                                  compile, so this pays per
    #                                  candidate). Params live under "h"
    #                                  with a leading layer axis; see
    #                                  stack_gpt2_layer_params /
    #                                  unstack_gpt2_layer_params for
    #                                  checkpoint conversion.


# Sizes follow the reference perf-harness configs
# (`tests/model/Megatron_GPT2/run_perf_baseline.py:18-60`).
def gpt2_125m(**kw):
    return GPT2Config(n_embd=768, n_layer=12, n_head=12, **kw)


def gpt2_350m(**kw):
    return GPT2Config(n_embd=1024, n_layer=24, n_head=16, **kw)


def gpt2_760m(**kw):
    return GPT2Config(n_embd=1536, n_layer=24, n_head=16, **kw)


def gpt2_1_5b(**kw):
    return GPT2Config(n_embd=1600, n_layer=48, n_head=25, **kw)


# Capacity-ladder sizes past the reference perf configs (GPT-3 paper
# shapes): used by BENCH_MODEL=capacity to answer "max trainable on one
# 16 GB v5e via ZeRO-Offload" — the proportional analog of the
# reference's 13B-on-one-32GB-V100 claim
# (`docs/_tutorials/zero-offload.md:9`).
def gpt2_2_7b(**kw):
    return GPT2Config(n_embd=2560, n_layer=32, n_head=32, **kw)


def gpt2_4b(**kw):
    return GPT2Config(n_embd=3072, n_layer=36, n_head=24, **kw)


def gpt2_tiny(**kw):
    """Test-size model (the `SimpleModel` analog for LM tests)."""
    kw.setdefault("vocab_size", 256)
    kw.setdefault("n_positions", 64)
    kw.setdefault("n_embd", 64)
    kw.setdefault("n_layer", 2)
    kw.setdefault("n_head", 4)
    return GPT2Config(**kw)


class CausalSelfAttention(nn.Module):
    """Causal attention; also the incremental-decode write/attend site.

    ``kv_cache`` (a layer's ``{"k", "v"(, scales)}`` buffers from
    `inference/cache.py`) switches to the cached path: this call's k/v
    are written at explicit ``positions`` and attention runs over the
    whole cache row under a position mask — the call then returns
    ``(y, updated_cache)``. With ``kv_cache=None`` the training path is
    untouched (same modules, same trace), so train and serve share
    every parameter."""
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True, positions=None,
                 kv_cache=None, attn_impl="dense", attn_block_k=128,
                 attn_mesh=None, attn_mask=None, kv_page_table=None):
        cfg = self.config
        B, T, C = x.shape
        H = cfg.n_head
        qkv = nn.Dense(3 * C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       dot_general=_fp8_dot("c_attn"), name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, C // H)
        k = k.reshape(B, T, H, C // H)
        v = v.reshape(B, T, H, C // H)

        new_cache = None
        if kv_cache is not None:
            from deepspeed_tpu.inference.cache import cached_attention
            y, new_cache = cached_attention(q, k, v, kv_cache, positions,
                                            compute_dtype=cfg.dtype,
                                            impl=attn_impl,
                                            block_k=attn_block_k,
                                            mesh=attn_mesh,
                                            mask=attn_mask,
                                            page_table=kv_page_table)
        elif cfg.use_flash_attention:
            from deepspeed_tpu.ops.pallas import flash_attention
            # Attention-prob dropout runs inside the kernels (counter-based
            # mask regenerated in the backward), so the flash path stays on
            # in training configs — the round-3 gate that forced dense
            # attention whenever dropout was active is gone.
            rate, seed = 0.0, None
            if not deterministic and cfg.dropout > 0.0:
                from deepspeed_tpu.ops.pallas.flash_attention import (
                    dropout_seed_from_rng)
                rate = cfg.dropout
                seed = dropout_seed_from_rng(self.make_rng("dropout"))
            y = flash_attention(q, k, v, causal=True,
                                dropout_rate=rate, dropout_seed=seed)
        else:
            scale = 1.0 / jnp.sqrt(jnp.asarray(C // H, cfg.dtype))
            att = jnp.einsum("bthd,bshd->bhts", q, k) * scale
            mask = jnp.tril(jnp.ones((T, T), bool))
            att = jnp.where(mask[None, None], att, jnp.finfo(att.dtype).min)
            att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(cfg.dtype)
            att = nn.Dropout(cfg.dropout)(att, deterministic=deterministic)
            y = jnp.einsum("bhts,bshd->bthd", att, v)
        y = y.reshape(B, T, C)
        y = nn.Dense(C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     dot_general=_fp8_dot("c_proj"), name="c_proj")(y)
        y = nn.Dropout(cfg.dropout)(y, deterministic=deterministic)
        if kv_cache is not None:
            return y, new_cache
        return y


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        C = x.shape[-1]
        h = nn.Dense(4 * C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     dot_general=_fp8_dot("c_fc"), name="c_fc")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(C, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     dot_general=_fp8_dot("c_proj"), name="c_proj")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    """Transformer block, optionally with progressive layer drop.

    PLD (reference `runtime/progressive_layer_drop.py:5` + the engine's
    per-forward theta kwarg injection, reference engine.py:791-792): when
    ``pld_theta`` is given and training, each sublayer executes with
    probability ``1 - (l/L)(1 - theta)`` (deeper layers dropped more, the
    paper's depth schedule). The skip is a ``lax.cond``, so a dropped
    sublayer costs nothing at runtime on TPU — the paper's compute saving,
    not just its regularization."""
    config: GPT2Config
    layer_idx: int = 0
    n_layers: int = 1

    @nn.compact
    def __call__(self, x, deterministic=True, pld_theta=None,
                 layer_idx=None, positions=None, kv_cache=None,
                 attn_impl="dense", attn_block_k=128, attn_mesh=None,
                 attn_mask=None, kv_page_table=None):
        cfg = self.config
        attn = CausalSelfAttention(cfg, name="attn")
        mlp = MLP(cfg, name="mlp")
        ln1 = nn.LayerNorm(dtype=cfg.dtype, name="ln_1")
        ln2 = nn.LayerNorm(dtype=cfg.dtype, name="ln_2")

        if kv_cache is not None:
            # incremental decode: PLD never applies (serving is
            # deterministic), and the attention call also returns the
            # layer's updated cache.
            a, new_cache = attn(ln1(x), deterministic,
                                positions=positions, kv_cache=kv_cache,
                                attn_impl=attn_impl,
                                attn_block_k=attn_block_k,
                                attn_mesh=attn_mesh, attn_mask=attn_mask,
                                kv_page_table=kv_page_table)
            x = x + a
            x = x + mlp(ln2(x), deterministic)
            return x, new_cache

        if pld_theta is None or deterministic:
            x = x + attn(ln1(x), deterministic)
            x = x + mlp(ln2(x), deterministic)
            return x

        # ``layer_idx`` as a call arg overrides the attribute so the
        # scan_layers path can feed the (traced) loop counter into the
        # PLD depth schedule.
        idx = self.layer_idx if layer_idx is None else layer_idx
        keep_p = 1.0 - (idx + 1) / self.n_layers * \
            (1.0 - pld_theta)
        coin_a = jax.random.bernoulli(self.make_rng("pld"), keep_p)
        coin_m = jax.random.bernoulli(self.make_rng("pld"), keep_p)
        if cfg.scan_layers:
            # flax can't build submodules inside lax.cond branches under
            # the lifted scan trace, so the skip degrades to a
            # multiplicative gate: same dropped-layer values, but the
            # sublayer compute always runs (PLD's FLOP saving is the one
            # thing scan_layers gives up).
            x = x + jnp.where(coin_a, 1, 0).astype(x.dtype) * \
                attn(ln1(x), deterministic)
            x = x + jnp.where(coin_m, 1, 0).astype(x.dtype) * \
                mlp(ln2(x), deterministic)
            return x
        x = jax.lax.cond(coin_a,
                         lambda h: h + attn(ln1(h), deterministic),
                         lambda h: h, x)
        x = jax.lax.cond(coin_m,
                         lambda h: h + mlp(ln2(h), deterministic),
                         lambda h: h, x)
        return x


class GPT2LMHead(nn.Module):
    """Decoder-only LM with tied embedding / output head."""
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, deterministic=True, pld_theta=None,
                 return_hidden=False, positions=None, kv_cache=None,
                 attn_impl="dense", attn_block_k=128, attn_mesh=None,
                 kv_page_table=None, truncate_layers=None):
        cfg = self.config
        B, T = input_ids.shape
        # Early-exit truncation (speculative draft): run only the first
        # ``truncate_layers`` blocks, then the usual ln_f + tied head.
        # Decode-only — the caller must slice the stacked params/cache
        # leaves to [:truncate_layers] under scan_layers (nn.scan splits
        # params along axis 0, so the leading axis must equal the scan
        # length); unrolled trees pass whole and only h_0..h_{L-1} run.
        n_run = cfg.n_layer if truncate_layers is None \
            else int(truncate_layers)
        if not 0 < n_run <= cfg.n_layer:
            raise ValueError(
                f"truncate_layers {truncate_layers} outside "
                f"1..{cfg.n_layer}")
        if truncate_layers is not None and kv_cache is None:
            raise ValueError("truncate_layers is a decode-path knob "
                             "(requires kv_cache)")
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.n_positions, cfg.n_embd), cfg.param_dtype)
        if positions is None:
            # training/full-context: positions ARE the sequence index.
            pos_emb = wpe[None, :T]
        else:
            # incremental decode: a [B, T] chunk sits at explicit
            # absolute positions (past the prefill), so the position
            # embedding is a gather, not a prefix slice.
            pos_emb = wpe[positions]
        x = wte[input_ids].astype(cfg.dtype) + pos_emb.astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        block_cls = Block
        if cfg.remat:
            policies = {
                "full": None,
                "dots": jax.checkpoint_policies.checkpoint_dots,
                "nothing": jax.checkpoint_policies.everything_saveable,
            }
            if cfg.remat_policy not in policies:
                raise ValueError(
                    f"remat_policy {cfg.remat_policy!r} not in "
                    f"{sorted(policies)}")
            policy = policies[cfg.remat_policy]
            block_cls = nn.remat(Block, prevent_cse=False, policy=policy)
        new_kv = None
        attn_mask = None
        if kv_cache is not None and attn_impl == "dense":
            # Hoist the dense cached-attention position mask: computed
            # once here and broadcast to every layer, instead of each
            # layer rebuilding the same [B, T, max_seq] iota-compare
            # inside the compiled decode program (the flash path masks
            # in-kernel from the positions scalar and needs none; the
            # paged pool takes S off the page table — the pool buffer
            # no longer carries the sequence length).
            from deepspeed_tpu.inference.cache import attention_mask
            layer0 = kv_cache["h" if cfg.scan_layers else "h_0"]
            attn_mask = attention_mask(layer0, positions,
                                       page_table=kv_page_table)
        if cfg.scan_layers and kv_cache is not None:
            # decode over the scanned stack: the per-layer cache slices
            # ride the same lax.scan as the stacked params (in_axes=0
            # over the (iota, cache) pair), and the updated slices come
            # back as the scan's stacked ys. The page table (one per
            # ROW, not per layer) broadcasts like the positions.
            def body(block, h, xs, det, pos, mask, page_table):
                idx, layer_cache = xs
                h, new_c = block(h, det, None, layer_idx=idx,
                                 positions=pos, kv_cache=layer_cache,
                                 attn_impl=attn_impl,
                                 attn_block_k=attn_block_k,
                                 attn_mesh=attn_mesh, attn_mask=mask,
                                 kv_page_table=page_table)
                return h, new_c

            scan = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True, "pld": True},
                in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast,
                         nn.broadcast),
                length=n_run)
            x, new_h = scan(block_cls(cfg, n_layers=cfg.n_layer, name="h"),
                            x, (jnp.arange(n_run), kv_cache["h"]),
                            deterministic, positions, attn_mask,
                            kv_page_table)
            new_kv = {"h": new_h}
        elif cfg.scan_layers:
            # One lax.scan over layer-stacked params instead of n_layer
            # unrolled Block copies: the lowered HLO carries a single
            # block body (trip-count-weighted by the audit), so trace and
            # compile wall stop scaling with depth. Params live under
            # "h" with a leading layer axis (variable_axes={"params": 0});
            # per-layer rngs come from split_rngs, and the PLD depth
            # schedule rides the scanned iota as the layer index.
            def body(block, h, idx, det, theta):
                return block(h, det, theta, layer_idx=idx), None

            scan = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True, "pld": True},
                in_axes=(0, nn.broadcast, nn.broadcast),
                length=cfg.n_layer)
            x, _ = scan(block_cls(cfg, n_layers=cfg.n_layer, name="h"),
                        x, jnp.arange(cfg.n_layer), deterministic,
                        pld_theta)
        elif kv_cache is not None:
            new_kv = {}
            for i in range(n_run):
                x, new_kv[f"h_{i}"] = block_cls(
                    cfg, layer_idx=i, n_layers=cfg.n_layer,
                    name=f"h_{i}")(x, deterministic, None,
                                   positions=positions,
                                   kv_cache=kv_cache[f"h_{i}"],
                                   attn_impl=attn_impl,
                                   attn_block_k=attn_block_k,
                                   attn_mesh=attn_mesh,
                                   attn_mask=attn_mask,
                                   kv_page_table=kv_page_table)
        else:
            for i in range(cfg.n_layer):
                x = block_cls(cfg, layer_idx=i, n_layers=cfg.n_layer,
                              name=f"h_{i}")(x, deterministic, pld_theta)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        if return_hidden:
            return x        # chunked-loss path applies the head itself
        logits = x @ wte.T.astype(cfg.dtype)
        if kv_cache is not None:
            return logits, new_kv
        return logits


def cross_entropy_sum_and_count(logits, labels, ignore_index=-100):
    """(summed token cross-entropy in fp32, valid-token count) — the
    weighted-loss form exact under sharded/microbatched averaging."""
    logits = logits.astype(jnp.float32)
    mask = (labels != ignore_index)
    safe_labels = jnp.where(mask, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_loss = -jnp.take_along_axis(logp, safe_labels[..., None],
                                      axis=-1).squeeze(-1)
    token_loss = jnp.where(mask, token_loss, 0.0)
    return token_loss.sum(), mask.sum()


def cross_entropy_loss(logits, labels, ignore_index=-100):
    """Mean token cross-entropy in fp32, masking ``ignore_index`` labels."""
    total, count = cross_entropy_sum_and_count(logits, labels, ignore_index)
    return total / jnp.maximum(count, 1)


@jax.custom_vjp
def _head_matmul(xc, head):
    """[B, c, M] x [M, V] head matmul with an fp32-accumulated head
    cotangent.

    ``head`` arrives fp32 (widened outside the scan); the forward computes
    at ``xc``'s dtype so the MXU runs the usual bf16 pass. The point is
    the backward: the per-chunk head cotangent is produced DIRECTLY in
    fp32 (``preferred_element_type`` — the MXU's native fp32 accumulator,
    no bf16 rounding of the partial), and because the head PRIMAL is fp32,
    ``lax.scan``'s constant-transpose then sums the per-chunk partials in
    fp32 too. One downcast happens at the end, in the caller's
    ``astype`` VJP — the same round-once-from-fp32 the dense head gets
    from a single big matmul (VERDICT r4 weak #5 / next-round #6)."""
    return jnp.dot(xc, head.astype(xc.dtype))


def _head_matmul_fwd(xc, head):
    return _head_matmul(xc, head), (xc, head)


def _head_matmul_bwd(res, g):
    xc, head = res
    dx = jnp.dot(g, head.astype(g.dtype).T)
    dhead = jax.lax.dot_general(
        xc, g, dimension_numbers=(((0, 1), (0, 1)), ((), ())),
        preferred_element_type=jnp.float32)
    return dx, dhead


_head_matmul.defvjp(_head_matmul_fwd, _head_matmul_bwd)


def chunked_cross_entropy_with_head(x, head, bias, labels, chunk,
                                    ignore_index=-100):
    """CE against a vocab head without materializing [B, T, V] logits.

    At GPT-2 scale the fp32 logits are the single largest activation
    (bs8 x 1024 x 50257 x 4 B ≈ 1.6 GB — the reason 760M OOMs with fp32
    masters, BENCHNOTES r2). ``lax.scan`` over sequence chunks computes
    each [B, chunk, V] logit tile, reduces it to (loss sum, count), and
    drops it; ``jax.checkpoint`` on the body recomputes the tile in the
    backward, so peak HBM is O(B * chunk * V) in both directions. The
    head matmuls stay full-width [B*chunk, M] x [M, V] — MXU-shaped.

    The head (and bias) stay fp32 across the scan so their cotangents
    accumulate in fp32 — under bf16 compute this makes chunked grads
    match the dense head's single fp32-accumulated matmul to fp32
    summation-order noise instead of the bf16 noise floor (see
    :func:`_head_matmul`).

    x: [B, T, M] final hidden states; head: [M, V]; bias: [V] or None;
    labels: [B, T].
    """
    B, T, M = x.shape
    chunk = min(chunk, T)
    n = -(-T // chunk)
    pad = n * chunk - T
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)),
                         constant_values=ignore_index)
    xc = jnp.moveaxis(x.reshape(B, n, chunk, M), 1, 0)       # [n,B,c,M]
    lc = jnp.moveaxis(labels.reshape(B, n, chunk), 1, 0)     # [n,B,c]
    head = head.astype(jnp.float32)
    if bias is not None:
        bias = bias.astype(jnp.float32)

    @jax.checkpoint
    def body(carry, inp):
        s, cnt = carry
        xcb, lcb = inp
        logits = _head_matmul(xcb, head)
        if bias is not None:
            # astype inside the body: the add's transpose reduces at the
            # logit dtype per chunk (same as dense), while the cast's VJP
            # widens so the CROSS-chunk bias accumulation stays fp32.
            logits = logits + bias.astype(logits.dtype)
        ls, c = cross_entropy_sum_and_count(logits, lcb, ignore_index)
        return (s + ls, cnt + c), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), (xc, lc))
    return total, count


def chunked_cross_entropy_sum_and_count(x, wte, labels, chunk,
                                        ignore_index=-100):
    """Tied-head form: CE against ``wte.T`` (see
    :func:`chunked_cross_entropy_with_head`)."""
    return chunked_cross_entropy_with_head(x, wte.T, None, labels, chunk,
                                           ignore_index)


def make_gpt2_loss_fn(model: GPT2LMHead):
    """loss_fn(params, batch, rng) for the engine.

    ``batch`` is a dict with ``input_ids`` [B, T] (labels default to the
    next-token shift) or explicit ``labels``.
    """

    def loss_fn(params, batch, rng=None, pld_theta=None):
        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [input_ids[:, 1:],
                 jnp.full((input_ids.shape[0], 1), -100, input_ids.dtype)],
                axis=1)
        rngs = {}
        if rng is not None:
            d_rng, p_rng = jax.random.split(rng)
            rngs = {"dropout": d_rng, "pld": p_rng}
        chunk = model.config.loss_chunk
        if chunk:
            hidden = model.apply(
                {"params": params}, input_ids,
                deterministic=rng is None, rngs=rngs,
                pld_theta=pld_theta if rng is not None else None,
                return_hidden=True)
            total, count = chunked_cross_entropy_sum_and_count(
                hidden, params["wte"], labels, chunk)
            return total / jnp.maximum(count, 1)
        logits = model.apply({"params": params}, input_ids,
                             deterministic=rng is None, rngs=rngs,
                             pld_theta=pld_theta if rng is not None else None)
        return cross_entropy_loss(logits, labels)

    return loss_fn


def init_gpt2_params(model: GPT2LMHead, rng, batch_size=2, seq_len=None):
    cfg = model.config
    T = seq_len or min(cfg.n_positions, 64)
    dummy = jnp.zeros((batch_size, T), jnp.int32)
    return model.init({"params": rng}, dummy)["params"]


def gpt2_partition_specs(params, model_axis="model"):
    """Megatron-style tensor-parallel PartitionSpecs over the ``model`` axis.

    The reference delegates TP to an external Megatron mpu (SURVEY §2.1); here
    TP is first-class: column-parallel QKV/FC kernels shard their output dim,
    row-parallel projections shard their input dim, embeddings shard the
    vocab dim, and GSPMD inserts the psums that Megatron hand-codes.

    ``scan_layers`` trees (stacked ``h`` subtree) get the same per-weight
    specs with a replicated leading layer axis prepended.
    """
    flat = flatten_dict(params)
    specs = {}
    for path, leaf in flat.items():
        name = "/".join(str(p) for p in path)
        ndim = getattr(leaf, "ndim", 0)
        stacked = bool(path) and str(path[0]) == "h"
        if stacked:
            ndim -= 1           # leading layer axis from scan_layers
        if ndim <= 1:
            spec = P()
        elif name.endswith("wte"):
            spec = P(model_axis, None)
        elif name.endswith("wpe"):
            spec = P()
        elif "attn/c_attn" in name and name.endswith("kernel"):
            spec = P(None, model_axis)            # column parallel
        elif "attn/c_proj" in name and name.endswith("kernel"):
            spec = P(model_axis, None)            # row parallel
        elif "mlp/c_fc" in name and name.endswith("kernel"):
            spec = P(None, model_axis)
        elif "mlp/c_proj" in name and name.endswith("kernel"):
            spec = P(model_axis, None)
        else:
            spec = P()
        if stacked:
            spec = P(None, *spec)   # layer axis is never model-sharded
        specs[path] = spec
    return unflatten_dict(specs)


# ---------------------------------------------------------------------------
# scan_layers checkpoint interop: stacked <-> per-layer param layouts
# ---------------------------------------------------------------------------

_LAYER_KEY_RE = re.compile(r"^h_(\d+)$")


def stack_gpt2_layer_params(params):
    """Unrolled tree (``h_0`` … ``h_{L-1}``) -> ``scan_layers`` layout.

    The per-layer subtrees collapse into one ``h`` subtree whose leaves
    gain a leading layer axis; everything else (wte/wpe/ln_f) passes
    through untouched. Inverse of :func:`unstack_gpt2_layer_params`;
    the round trip is bit-exact, so existing checkpoints load into
    ``scan_layers=True`` models (and back) without loss.
    """
    idxs = sorted(int(m.group(1)) for k in params
                  if (m := _LAYER_KEY_RE.match(str(k))))
    if not idxs:
        raise ValueError("no per-layer 'h_<i>' entries to stack")
    if idxs != list(range(len(idxs))):
        raise ValueError(f"non-contiguous layer indices: {idxs}")
    out = {k: v for k, v in params.items()
           if not _LAYER_KEY_RE.match(str(k))}
    out["h"] = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0),
        *[params[f"h_{i}"] for i in idxs])
    return out


def unstack_gpt2_layer_params(params):
    """``scan_layers`` layout -> unrolled ``h_0`` … ``h_{L-1}`` tree (the
    inverse of :func:`stack_gpt2_layer_params`)."""
    if "h" not in params:
        raise ValueError("no stacked 'h' entry to unstack")
    out = {k: v for k, v in params.items() if str(k) != "h"}
    stacked = params["h"]
    n_layer = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    for i in range(n_layer):
        out[f"h_{i}"] = jax.tree_util.tree_map(
            lambda leaf: leaf[i], stacked)
    return out
