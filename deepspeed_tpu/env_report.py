"""Environment/op diagnostic — the ``ds_report`` analog
(reference `deepspeed/env_report.py:23-109`): native-op build/compat
matrix, framework versions, device inventory."""

import importlib
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{RED}[NO]{END}"


def op_report(out=sys.stdout):
    from deepspeed_tpu.ops.op_builder import ALL_OPS
    print("-" * 64, file=out)
    print("deepspeed_tpu native op report", file=out)
    print("-" * 64, file=out)
    print(f"{'op name':<20} {'compatible':<14} {'built':<10}", file=out)
    print("-" * 64, file=out)
    rows = []
    for name, builder_cls in sorted(ALL_OPS.items()):
        b = builder_cls()
        compatible = b.is_compatible()
        built = b.lib_path().exists() if compatible else False
        print(f"{name:<20} {(OKAY if compatible else NO):<23} "
              f"{(OKAY if built else NO):<10}", file=out)
        rows.append((name, compatible, built))
    return rows


def debug_report(out=sys.stdout):
    import os
    import jax
    import jaxlib
    import deepspeed_tpu
    # Some environments register extra PJRT plugins at interpreter startup
    # in a way that ignores the JAX_PLATFORMS env var; re-assert it through
    # the config so `ds_tpu_report` can be pointed at a platform (e.g.
    # JAX_PLATFORMS=cpu) without initializing unreachable backends.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    print("-" * 64, file=out)
    print("environment", file=out)
    print("-" * 64, file=out)
    rows = [
        ("deepspeed_tpu version", deepspeed_tpu.__version__),
        ("jax version", jax.__version__),
        ("jaxlib version", getattr(jaxlib, "__version__", "?")),
        ("python version", sys.version.split()[0]),
    ]
    for mod in ("flax", "optax", "orbax.checkpoint"):
        try:
            m = importlib.import_module(mod)
            rows.append((f"{mod} version", getattr(m, "__version__", "?")))
        except ImportError:
            rows.append((f"{mod} version", "not installed"))
    try:
        devs = jax.devices()
        rows.append(("default backend", jax.default_backend()))
        rows.append(("device count", str(len(devs))))
        rows.append(("devices", ", ".join(str(d) for d in devs[:8])))
    except Exception as e:  # device init can fail off-TPU
        rows.append(("devices", f"unavailable ({e})"))
    for name, val in rows:
        print(f"{name:.<30} {val}", file=out)
    return rows


def main(out=sys.stdout, argv=None):
    import argparse
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu environment report (reference bin/"
                    "ds_report)")
    parser.add_argument(
        "--perf", action="store_true",
        help="also run the CPU Adam micro-benchmark at reference scale "
             "(~1e8 elements; reference tests/perf/adam_test.py)")
    args = parser.parse_args(argv)
    op_report(out=out)
    debug_report(out=out)
    from deepspeed_tpu.telemetry.profiler import device_report
    device_report(out=out)
    if args.perf:
        import json
        from deepspeed_tpu.ops.adam.perf import benchmark_cpu_adam
        print("cpu_adam micro-bench (1e8 elems, best of 5):", file=out)
        print(json.dumps(benchmark_cpu_adam()), file=out)


if __name__ == "__main__":
    main()
