"""SparseAttentionUtils: adopt sparse attention in an existing model.

Analog of the reference's ``SparseAttentionUtils``
(`deepspeed/ops/sparse_attention/sparse_attention_utils.py:13-225`), whose
capabilities are: extend position embeddings to a longer max length, bump
the tokenizer's max length, swap a model's self-attention for sparse
self-attention, and pad/unpad inputs to the sparsity block size.

Functional-JAX differences: models are immutable (config + params pytree),
so "surgery" returns *new* objects — ``replace_model_self_attention...``
maps a model to an equivalent one whose config enables sparse attention
(param shapes are unchanged, so the original params remain valid), and
``extend_position_embedding`` returns a new params pytree.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.sparsity_config import SparsityConfig
from deepspeed_tpu.utils.logging import logger

POSITION_EMBEDDING_NAMES = ("position_embeddings", "wpe")


class SparseAttentionUtils:
    """Utilities for integrating sparse attention into transformer models
    (reference class docstring: `sparse_attention_utils.py:14-17`)."""

    @staticmethod
    def extend_position_embedding(params, max_position):
        """Return a new params pytree whose position-embedding leaves are
        extended to ``max_position`` rows by replicating the learned
        weights (the reference's duplication scheme, which it reports works
        better than random init, `sparse_attention_utils.py:19-66`).

        Leaves are matched by path name (``position_embeddings`` / ``wpe``)
        — covers this package's BERT/GPT-2 and HF flax checkpoints.
        """
        import jax

        extended = []

        def extend(path, leaf):
            names = [str(getattr(p, "key", getattr(p, "idx", p)))
                     for p in path]
            if leaf.ndim == 2 and any(
                    n in POSITION_EMBEDDING_NAMES for n in names):
                orig = leaf.shape[0]
                if max_position < orig:
                    raise ValueError(
                        f"max_position {max_position} < current {orig}")
                reps = -(-max_position // orig)  # ceil
                new = jnp.tile(leaf, (reps, 1))[:max_position]
                extended.append(("/".join(names), orig, max_position))
                return new
            return leaf

        new_params = jax.tree_util.tree_map_with_path(extend, params)
        if not extended:
            raise ValueError(
                "no position-embedding leaves found; supported names: "
                f"{POSITION_EMBEDDING_NAMES}")
        for name, orig, new in extended:
            logger.info(f"extended {name}: {orig} -> {new} positions")
        return new_params

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position):
        """Bump a (HF-style) tokenizer's max length to ``max_position``
        (reference `sparse_attention_utils.py:68-83`)."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            model, max_position, sparsity_config=None, params=None):
        """Return a model equivalent to ``model`` but with sparse
        self-attention enabled and ``max_position`` positions (reference
        `sparse_attention_utils.py:85-121`, which mutates HF BERT/RoBERTa
        layers in place; here config replacement does it for every layer
        at once — attention param shapes are unchanged).

        Pass ``params`` to also get a matching params pytree back —
        ``(model, params)`` — with the position embeddings extended via
        :meth:`extend_position_embedding`. Without ``params``, the caller
        must extend any existing params themselves before applying the
        returned model beyond their original position count.

        Supported: this package's ``BertModel`` / ``BertForMaskedLM``.
        """
        from deepspeed_tpu.models.bert import BertForMaskedLM, BertModel

        if isinstance(model, (BertModel, BertForMaskedLM)):
            if max_position < model.config.max_position_embeddings:
                raise ValueError(
                    f"max_position {max_position} is smaller than the "
                    f"model's current "
                    f"{model.config.max_position_embeddings}; position "
                    "tables are never shrunk")
            if sparsity_config is None:
                from deepspeed_tpu.ops.sparse_attention.sparsity_config \
                    import FixedSparsityConfig
                sparsity_config = FixedSparsityConfig(
                    num_heads=model.config.num_attention_heads)
            assert isinstance(sparsity_config, SparsityConfig)
            new_cfg = dataclasses.replace(
                model.config, sparse_attention=sparsity_config,
                max_position_embeddings=max_position)
            new_model = type(model)(new_cfg)
            if params is not None:
                if max_position > model.config.max_position_embeddings:
                    params = SparseAttentionUtils.extend_position_embedding(
                        params, max_position)
                return new_model, params
            return new_model
        raise ValueError(
            f"{type(model).__name__} is not supported: only the in-package "
            "BERT family can be sparsified (the reference supports HF "
            "BERT/RoBERTa the same way)")

    @staticmethod
    def replace_self_attention_layer_with_sparse_self_attention_layer(
            hidden_size, num_attention_heads, sparsity_config,
            dtype=jnp.float32):
        """Build a :class:`BertSparseSelfAttention` layer with the given
        geometry (reference `sparse_attention_utils.py:123-149`, which
        rewires each HF layer's ``attention.self``)."""
        from deepspeed_tpu.ops.sparse_attention.bert_sparse_self_attention \
            import BertSparseSelfAttention

        return BertSparseSelfAttention(
            hidden_size=hidden_size,
            num_attention_heads=num_attention_heads,
            sparsity_config=sparsity_config,
            dtype=dtype)

    @staticmethod
    def pad_to_block_size(block_size, input_ids, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id=0):
        """Pad the sequence dim up to a multiple of ``block_size``
        (reference `sparse_attention_utils.py:151-208`). Returns
        ``(pad_len, input_ids, attention_mask, token_type_ids,
        position_ids, inputs_embeds)`` with None passed through. Padded
        key positions get ``attention_mask`` 0, so they are masked out.
        """
        seq_len = (input_ids if input_ids is not None
                   else inputs_embeds).shape[1]
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len == 0:
            return (0, input_ids, attention_mask, token_type_ids,
                    position_ids, inputs_embeds)

        def pad2(x, value):
            if x is None:
                return None
            return jnp.pad(x, ((0, 0), (0, pad_len)), constant_values=value)

        input_ids = pad2(input_ids, pad_token_id)
        attention_mask = pad2(attention_mask, 0)
        token_type_ids = pad2(token_type_ids, 0)
        position_ids = pad2(position_ids, 0)
        if inputs_embeds is not None:
            inputs_embeds = jnp.pad(
                inputs_embeds, ((0, 0), (0, pad_len), (0, 0)))
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        """Strip the padding added by :meth:`pad_to_block_size`
        (reference `sparse_attention_utils.py:210-224`)."""
        if pad_len:
            sequence_output = sequence_output[:, :-pad_len]
        return sequence_output
