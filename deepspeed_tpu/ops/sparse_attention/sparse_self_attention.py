"""SparseSelfAttention: layout-driven sparse attention module.

Analog of the reference module (`deepspeed/ops/sparse_attention/
sparse_self_attention.py:13`), which chains SDD matmul → sparse softmax →
DSD matmul; here the chain is one fused block-sparse flash-attention call
(`block_sparse_attention.py`). Tensors follow the reference convention:
[batch, heads, seq, head_dim].
"""

import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.block_sparse_attention import (
    block_sparse_attention,
)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig,
    SparsityConfig,
)


def collapse_additive_mask(attention_mask, B, T):
    """BERT-style additive mask broadcastable to [B, 1, 1, T] → the [B, T]
    key-padding mask the sparse core consumes (mode "add"). Shared by the
    sparse entry points so mask semantics can't diverge."""
    return jnp.reshape(
        jnp.broadcast_to(attention_mask.astype(jnp.float32),
                         (B, 1, 1, T)), (B, T))


class SparseSelfAttention:
    """Efficient sparse self attention (Generative Modeling with Sparse
    Transformers, arXiv:1904.10509).

    ``sparsity_config``: a :class:`SparsityConfig` subclass instance.
    ``key_padding_mask_mode`` / ``attn_mask_mode``: "add" (mask added to
    scores) or "mul" (zeros become -inf) — reference semantics.
    """

    def __init__(self, sparsity_config=None, key_padding_mask_mode="add",
                 attn_mask_mode="mul", implementation="auto"):
        if sparsity_config is None:
            sparsity_config = FixedSparsityConfig(num_heads=4)
        assert isinstance(sparsity_config, SparsityConfig)
        self.sparsity_config = sparsity_config
        assert key_padding_mask_mode in ("add", "mul")
        assert attn_mask_mode in ("add", "mul")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.implementation = implementation
        # per-instance layout cache keyed by seq len — the analog of the
        # reference's per-seq-len ops cache (`sparse_self_attention.py:41-66`)
        self._layouts = {}

    def get_layout(self, seq_len):
        if seq_len not in self._layouts:
            self._layouts[seq_len] = \
                self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None, dropout_rate=0.0, dropout_seed=None):
        """query/key/value: [B, H, T, D] → attention context [B, H, T, D].
        ``dropout_rate``/``dropout_seed``: in-kernel attention-prob
        dropout (shared counter-based mask — see
        ops/pallas/flash_attention.py)."""
        bsz, num_heads, tgt_len, head_dim = query.shape
        if query.shape != key.shape or key.shape != value.shape:
            raise NotImplementedError(
                "only self-attention is supported for now")
        assert num_heads == self.sparsity_config.num_heads, (
            f"tensor has {num_heads} heads, sparsity config expects "
            f"{self.sparsity_config.num_heads}")

        layout = self.get_layout(tgt_len)
        causal = getattr(self.sparsity_config, "attention",
                         "bidirectional") == "unidirectional"
        # [B, H, T, D] → [B, T, H, D]
        q = jnp.swapaxes(query, 1, 2)
        k = jnp.swapaxes(key, 1, 2)
        v = jnp.swapaxes(value, 1, 2)
        out = block_sparse_attention(
            q, k, v, layout, self.sparsity_config.block,
            causal=causal,
            sm_scale=float(head_dim) ** -0.5,
            rpe=rpe,
            key_padding_mask=key_padding_mask,
            attn_mask=attn_mask,
            key_padding_mask_mode=self.key_padding_mask_mode,
            attn_mask_mode=self.attn_mask_mode,
            implementation=self.implementation,
            dropout_rate=dropout_rate,
            dropout_seed=dropout_seed)
        return jnp.swapaxes(out, 1, 2)
