"""Block-sparse attention for TPU.

The reference implements block-sparse attention as three Triton kernels —
SDD/DSD block matmuls (`deepspeed/ops/sparse_attention/matmul.py:16-614`,
`trsrc/matmul.tr`) and a fused scale+rpe+mask softmax over nonzero blocks
(`softmax.py:17-217`, `trsrc/softmax_fwd.tr`) — stitched together by
``SparseSelfAttention`` with the [T, T] block-sparse score matrix
materialized in HBM.

TPU-first redesign: one *fused* block-sparse flash-attention — for each
(head, q-block) the kernel walks only that row's nonzero k-blocks (a LUT
built from the ``SparsityConfig`` layout) with online-softmax accumulation,
so the sparse score matrix never exists in memory at all. Two
implementations share the LUT:

- ``pallas``: TPU kernels, forward AND backward; the LUT rides in SMEM
  via scalar prefetch and drives the k/v block index maps, accumulators
  live in VMEM scratch. The backward is the FlashAttention-2 split — dQ
  walks the forward LUT, dK/dV walks a transposed LUT (each k-block's
  nonzero q-blocks) — wired through ``jax.custom_vjp``.
- ``xla``: per-head gather of the LUT's k/v blocks + masked softmax —
  runs everywhere (CPU test meshes), natively differentiable, and carries
  the rpe / key-padding-mask / attention-mask features of the reference
  softmax kernel.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.flash_attention import (
    _from_bh, _to_bh, DEFAULT_MASK_VALUE)


# ---------------------------------------------------------------------------
# LUT construction (the analog of the reference's make_lut machinery,
# `matmul.py:53-114` / `softmax.py:42-77`, minus the Triton segmenting)
# ---------------------------------------------------------------------------

def _build_lut_numpy(layout):
    H, nq, nk = layout.shape
    nnz = layout.sum(axis=-1).astype(np.int32)
    max_nnz = max(int(nnz.max()), 1)
    lut = np.zeros((H, nq, max_nnz), dtype=np.int32)
    for h in range(H):
        for qi in range(nq):
            cols = np.nonzero(layout[h, qi])[0]
            lut[h, qi, :len(cols)] = cols
    return lut, nnz


def _build_lut_native(layout):
    """OpenMP C++ LUT builder (`csrc/sparse_attention/lut_builder.cpp` —
    the analog of the reference's only sparse-attn C++, the sdd_segment
    LUT helper). Returns None if the native op can't build here."""
    try:
        from deepspeed_tpu.ops.op_builder import SparseAttnBuilder

        lib = SparseAttnBuilder().load(verbose=False)
    except Exception:
        return None
    import ctypes

    H, nq, nk = layout.shape
    flat = np.ascontiguousarray(layout.reshape(-1), dtype=np.int64)
    p64 = flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    max_nnz = max(int(lib.ds_lut_max_nnz(p64, H, nq, nk)), 1)
    lut = np.zeros((H, nq, max_nnz), dtype=np.int32)
    nnz = np.zeros((H, nq), dtype=np.int32)
    lib.ds_build_lut(
        p64, H, nq, nk, max_nnz,
        lut.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        nnz.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return lut, nnz


def build_lut(layout):
    """Per-(head, q-block) list of nonzero k-block indices.

    layout: [H, nq, nk] 0/1 array →
      lut:  [H, nq, max_nnz] int32 (k-block index; padded entries are 0)
      nnz:  [H, nq] int32 (valid entries per row)

    Uses the native C++/OpenMP builder when it can compile, the NumPy
    loop otherwise.
    """
    layout = np.asarray(layout)
    native = _build_lut_native(layout)
    if native is not None:
        return native
    return _build_lut_numpy(layout)


@functools.lru_cache(maxsize=64)
def _build_lut_cached(layout_bytes, layout_shape):
    layout = np.frombuffer(layout_bytes, dtype=np.int64).reshape(layout_shape)
    return build_lut(layout)


# ---------------------------------------------------------------------------
# XLA gather implementation (differentiable; carries rpe/masks)
# ---------------------------------------------------------------------------

def _xla_impl(q, k, v, lut, nnz, block, causal, sm_scale,
              rpe=None, key_padding_mask=None, attn_mask=None,
              key_padding_mask_mode="add", attn_mask_mode="mul",
              dropout_rate=0.0, dropout_seed=None):
    """q,k,v: [B, T, H, D]; lut/nnz per build_lut. Returns [B, T, H, D]."""
    from deepspeed_tpu.ops.pallas.flash_attention import dropout_multiplier

    B, T, H, D = q.shape
    nq = T // block
    max_nnz = lut.shape[-1]
    lut = jnp.asarray(lut)
    nnz = jnp.asarray(nnz)

    def to_heads(x):
        # [B, T, H, D] → [H, B, nq, block, D]
        return x.transpose(2, 0, 1, 3).reshape(H, B, nq, block, D)

    qh = to_heads(q).astype(jnp.float32) * sm_scale
    kh = to_heads(k).astype(jnp.float32)
    vh = to_heads(v).astype(jnp.float32)

    in_block = jnp.arange(block)
    q_pos = jnp.arange(nq)[:, None] * block + in_block[None, :]   # [nq, blk]

    def mask_to_additive(m, mode):
        m = m.astype(jnp.float32)
        if mode == "mul":
            # reference softmax_fwd.tr:103 — zero entries become -inf
            return jnp.where(m == 0, DEFAULT_MASK_VALUE, 0.0)
        return m

    kp_add = None
    if key_padding_mask is not None:
        kp_add = mask_to_additive(jnp.asarray(key_padding_mask),
                                  key_padding_mask_mode)    # [B, T]
        kp_blocks = kp_add.reshape(B, nq, block)
    attn_add = None
    if attn_mask is not None:
        attn_add = mask_to_additive(jnp.asarray(attn_mask),
                                    attn_mask_mode)         # [T, T]

    def per_head(h, q_h, k_h, v_h):
        lut_h = lut[h]                      # [nq, max_nnz]
        nnz_h = nnz[h]                      # [nq]
        kg = k_h[:, lut_h]                  # [B, nq, nnz, blk, D]
        vg = v_h[:, lut_h]
        s = jnp.einsum("bqrd,bqjcd->bqrjc", q_h, kg)   # [B,nq,blk,nnz,blk]

        k_pos = lut_h[:, :, None] * block + in_block[None, None, :]
        valid = jnp.arange(max_nnz)[None, :] < nnz_h[:, None]   # [nq, nnz]
        mask = valid[:, None, :, None]
        if causal:
            cmask = k_pos[:, None, :, :] <= q_pos[:, :, None, None]
            mask = mask & cmask
        if rpe is not None:
            # rpe: [B, H, T, T] added to scaled scores (softmax_fwd.tr:117)
            s = s + _gather_rows(rpe[:, h].astype(jnp.float32), lut_h,
                                 block, nq)
        if kp_add is not None:
            # [B, nq, nnz, blk] → broadcast over the q-row dim
            s = s + kp_blocks[:, lut_h][:, :, None, :, :]
        if attn_add is not None:
            s = s + _gather_attn(attn_add, lut_h, block, nq)

        s = jnp.where(mask[None], s, DEFAULT_MASK_VALUE)
        s = s.reshape(B, nq, block, max_nnz * block)
        p = jax.nn.softmax(s, axis=-1)
        p = p.reshape(B, nq, block, max_nnz, block)
        if dropout_rate > 0.0:
            bh = jnp.arange(B) * H + h                       # [B]
            p = p * dropout_multiplier(
                dropout_seed, bh[:, None, None, None, None],
                q_pos[None, :, :, None, None],
                k_pos[None, :, None, :, :], dropout_rate)
        return jnp.einsum("bqrjc,bqjcd->bqrd", p, vg)

    out = jax.vmap(per_head, in_axes=(0, 0, 0, 0))(
        jnp.arange(H), qh, kh, vh)          # [H, B, nq, blk, D]
    return out.transpose(1, 2, 3, 0, 4).reshape(B, T, H, D).astype(q.dtype)


def _gather_rows(rpe_h, lut_h, block, nq):
    """rpe_h: [B, T, T]; gather k-blocks per q-block row →
    [B, nq, blk, max_nnz, blk]."""
    B = rpe_h.shape[0]
    r = rpe_h.reshape(B, nq, block, nq, block)
    # vmap over q-block rows: r[:, qi][:, :, lut_h[qi]] per row
    return jax.vmap(lambda rq, idx: rq[:, :, idx],
                    in_axes=(1, 0), out_axes=1)(r, lut_h)


def _gather_attn(attn_add, lut_h, block, nq):
    """attn_add: [T, T] → gathered [nq, blk, max_nnz, blk] broadcast over B."""
    a = attn_add.reshape(nq, block, nq, block)
    gathered = jax.vmap(lambda aq, idx: aq[:, idx],
                        in_axes=(0, 0))(a, lut_h)  # [nq, blk, nnz, blk]
    return gathered[None]


# ---------------------------------------------------------------------------
# Pallas TPU kernels (no-mask fast path), forward + backward
# ---------------------------------------------------------------------------

def _block_positions(block, qblk, kblk):
    q_pos = qblk * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 0)
    k_pos = kblk * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 1)
    return q_pos, k_pos


def _pallas_impl(q, k, v, lut, nnz, block, causal, sm_scale,
                 interpret=False, dropout_rate=0.0, dropout_seed=None):
    """Returns (out [B,T,H,D], lse [B*H,T,1]) — the logsumexp residual
    feeds the backward kernels (compact, not lane-broadcast — see the
    layout note in ops/pallas/flash_attention.py). Dropout uses the
    flash kernels' counter-based hash at the same global (bh, q, k)
    coordinates (the seed rides as a third scalar-prefetch input)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from deepspeed_tpu.ops.pallas.flash_attention import dropout_multiplier

    B, T, H, D = q.shape
    nq = T // block
    max_nnz = lut.shape[-1]
    dropping = dropout_rate > 0.0

    q, k, v = _to_bh(q), _to_bh(k), _to_bh(v)
    lut_flat = jnp.asarray(lut.reshape(H * nq * max_nnz), jnp.int32)
    nnz_flat = jnp.asarray(nnz.reshape(H * nq), jnp.int32)
    scalars = [lut_flat, nnz_flat]
    if dropping:
        scalars.append(jnp.asarray(dropout_seed, jnp.int32).reshape(1))

    def kernel(lut_ref, nnz_ref, *args):
        if dropping:
            seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, \
                acc_ref, m_ref, l_ref = args
        else:
            q_ref, k_ref, v_ref, o_ref, lse_ref, \
                acc_ref, m_ref, l_ref = args
            seed_ref = None
        bh = pl.program_id(0)
        qi = pl.program_id(1)
        j = pl.program_id(2)
        h = jax.lax.rem(bh, H)

        @pl.when(j == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[:] = jnp.zeros_like(l_ref)

        @pl.when(j < nnz_ref[h * nq + qi])
        def _compute():
            kblk = lut_ref[(h * nq + qi) * max_nnz + j]
            qb = q_ref[0].astype(jnp.float32) * sm_scale     # [blk, D]
            kb = k_ref[0].astype(jnp.float32)                # [blk, D]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)          # [blk, blk]
            if causal:
                q_pos, k_pos = _block_positions(block, qi, kblk)
                s = jnp.where(k_pos <= q_pos, s, DEFAULT_MASK_VALUE)
            m_prev = m_ref[:, 0]
            m_new = jnp.maximum(m_prev, s.max(axis=-1))
            p = jnp.exp(s - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_ref[:, 0] = l_ref[:, 0] * corr + p.sum(axis=-1)
            m_ref[:, 0] = m_new
            pd = p
            if dropping:
                q_pos, k_pos = _block_positions(block, qi, kblk)
                pd = p * dropout_multiplier(seed_ref[0], bh, q_pos, k_pos,
                                            dropout_rate)
            vb = v_ref[0].astype(jnp.float32)
            acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
                pd, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(j == max_nnz - 1)
        def _finish():
            l = jnp.maximum(l_ref[:, 0], 1e-30)
            o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)
            # empty rows keep lse = -inf + log(1e-30): harmless, the bwd
            # kernels never visit them (no LUT entries)
            lse_ref[0] = (m_ref[:, 0] + jnp.log(l))[:, None]

    def k_index(bh, qi, j, lut_ref, nnz_ref, *_):
        h = jax.lax.rem(bh, H)
        return (bh, lut_ref[(h * nq + qi) * max_nnz + j], 0)

    def q_row(bh, qi, j, *_):
        return (bh, qi, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(B * H, nq, max_nnz),
        in_specs=[
            pl.BlockSpec((1, block, D), q_row),
            pl.BlockSpec((1, block, D), k_index),
            pl.BlockSpec((1, block, D), k_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block, D), q_row),
            pl.BlockSpec((1, block, 1), q_row),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, D), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*scalars, q, k, v)
    return _from_bh(out, B, H), lse


def _pallas_bwd_impl(q, k, v, out, lse, g, lut, nnz, lut_t, nnz_t, block,
                     causal, sm_scale, interpret=False,
                     dropout_rate=0.0, dropout_seed=None):
    """Block-sparse FlashAttention-2 backward: the dQ kernel walks each
    q-block's nonzero k-blocks (forward LUT); the dK/dV kernel walks each
    k-block's nonzero q-blocks (transposed LUT). The sparse [T, T] score
    matrix never materializes in either direction. Dropout masks are
    regenerated in-kernel from the shared counter-based hash (see
    ops/pallas/flash_attention.py)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from deepspeed_tpu.ops.pallas.flash_attention import dropout_multiplier

    B, T, H, D = q.shape
    nq = T // block
    nk = nq
    max_nnz = lut.shape[-1]
    max_nnz_t = lut_t.shape[-1]
    in_dtype = q.dtype
    dropping = dropout_rate > 0.0

    qh, kh, vh = _to_bh(q), _to_bh(k), _to_bh(v)
    oh, gh = _to_bh(out), _to_bh(g)
    delta = jnp.sum(gh.astype(jnp.float32) * oh.astype(jnp.float32),
                    axis=-1, keepdims=True)                # [BH, T, 1]

    lut_flat = jnp.asarray(lut.reshape(H * nq * max_nnz), jnp.int32)
    nnz_flat = jnp.asarray(nnz.reshape(H * nq), jnp.int32)
    lut_t_flat = jnp.asarray(lut_t.reshape(H * nk * max_nnz_t), jnp.int32)
    nnz_t_flat = jnp.asarray(nnz_t.reshape(H * nk), jnp.int32)
    seed_arr = (jnp.asarray(dropout_seed, jnp.int32).reshape(1)
                if dropping else None)

    def scores_block(q_blk, k_blk, qi, kblk):
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos, k_pos = _block_positions(block, qi, kblk)
            s = jnp.where(k_pos <= q_pos, s, DEFAULT_MASK_VALUE)
        return s

    def drop_tile(seed_ref, bh, qblk, kblk):
        q_pos, k_pos = _block_positions(block, qblk, kblk)
        return dropout_multiplier(seed_ref[0], bh, q_pos, k_pos,
                                  dropout_rate)

    # ---- dQ: grid (BH, nq, max_nnz) over the forward LUT ---------------
    def dq_kernel(lut_ref, nnz_ref, *args):
        if dropping:
            seed_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, \
                delta_ref, dq_ref, dq_acc = args
        else:
            q_ref, k_ref, v_ref, g_ref, lse_ref, \
                delta_ref, dq_ref, dq_acc = args
            seed_ref = None
        bh = pl.program_id(0)
        qi = pl.program_id(1)
        j = pl.program_id(2)
        h = jax.lax.rem(bh, H)

        @pl.when(j == 0)
        def _init():
            dq_acc[:] = jnp.zeros_like(dq_acc)

        @pl.when(j < nnz_ref[h * nq + qi])
        def _compute():
            kblk = lut_ref[(h * nq + qi) * max_nnz + j]
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            s = scores_block(qb, kb, qi, kblk)
            p = jnp.exp(s - lse_ref[0][:, :1])
            gb = g_ref[0].astype(jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            dp = jax.lax.dot_general(
                gb, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if dropping:
                dp = dp * drop_tile(seed_ref, bh, qi, kblk)
            ds = p * (dp - delta_ref[0][:, :1]) * sm_scale
            dq_acc[:] += jax.lax.dot_general(
                ds, kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(j == max_nnz - 1)
        def _finish():
            dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)

    def k_index(bh, qi, j, lut_ref, nnz_ref, *_):
        h = jax.lax.rem(bh, H)
        return (bh, lut_ref[(h * nq + qi) * max_nnz + j], 0)

    def q_row(bh, qi, j, *_):
        return (bh, qi, 0)

    dq_scalars = [lut_flat, nnz_flat] + ([seed_arr] if dropping else [])
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(dq_scalars),
            grid=(B * H, nq, max_nnz),
            in_specs=[
                pl.BlockSpec((1, block, D), q_row),
                pl.BlockSpec((1, block, D), k_index),
                pl.BlockSpec((1, block, D), k_index),
                pl.BlockSpec((1, block, D), q_row),
                pl.BlockSpec((1, block, 1), q_row),
                pl.BlockSpec((1, block, 1), q_row),
            ],
            out_specs=pl.BlockSpec((1, block, D), q_row),
            scratch_shapes=[pltpu.VMEM((block, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(qh.shape, in_dtype),
        interpret=interpret,
    )(*dq_scalars, qh, kh, vh, gh, lse, delta)

    # ---- dK/dV: grid (BH, nk, max_nnz_t) over the transposed LUT -------
    def dkv_kernel(lut_t_ref, nnz_t_ref, *args):
        if dropping:
            seed_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, \
                delta_ref, dk_ref, dv_ref, dk_acc, dv_acc = args
        else:
            q_ref, k_ref, v_ref, g_ref, lse_ref, \
                delta_ref, dk_ref, dv_ref, dk_acc, dv_acc = args
            seed_ref = None
        bh = pl.program_id(0)
        ki = pl.program_id(1)
        j = pl.program_id(2)
        h = jax.lax.rem(bh, H)

        @pl.when(j == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        @pl.when(j < nnz_t_ref[h * nk + ki])
        def _compute():
            qblk = lut_t_ref[(h * nk + ki) * max_nnz_t + j]
            qb = q_ref[0].astype(jnp.float32)
            kb = k_ref[0].astype(jnp.float32)
            s = scores_block(qb, kb, qblk, ki)
            p = jnp.exp(s - lse_ref[0][:, :1])
            gb = g_ref[0].astype(jnp.float32)
            if dropping:
                mult = drop_tile(seed_ref, bh, qblk, ki)
                pd = p * mult
            else:
                pd = p
            dv_acc[:] += jax.lax.dot_general(
                pd, gb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            vb = v_ref[0].astype(jnp.float32)
            dp = jax.lax.dot_general(
                gb, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if dropping:
                dp = dp * mult
            ds = p * (dp - delta_ref[0][:, :1]) * sm_scale
            dk_acc[:] += jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(j == max_nnz_t - 1)
        def _finish():
            dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    def q_via_lut_t(bh, ki, j, lut_t_ref, nnz_t_ref, *_):
        h = jax.lax.rem(bh, H)
        return (bh, lut_t_ref[(h * nk + ki) * max_nnz_t + j], 0)

    def k_row(bh, ki, j, *_):
        return (bh, ki, 0)

    dkv_scalars = [lut_t_flat, nnz_t_flat] + ([seed_arr] if dropping else [])
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(dkv_scalars),
            grid=(B * H, nk, max_nnz_t),
            in_specs=[
                pl.BlockSpec((1, block, D), q_via_lut_t),
                pl.BlockSpec((1, block, D), k_row),
                pl.BlockSpec((1, block, D), k_row),
                pl.BlockSpec((1, block, D), q_via_lut_t),
                pl.BlockSpec((1, block, 1), q_via_lut_t),
                pl.BlockSpec((1, block, 1), q_via_lut_t),
            ],
            out_specs=[
                pl.BlockSpec((1, block, D), k_row),
                pl.BlockSpec((1, block, D), k_row),
            ],
            scratch_shapes=[
                pltpu.VMEM((block, D), jnp.float32),
                pltpu.VMEM((block, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(kh.shape, in_dtype),
            jax.ShapeDtypeStruct(vh.shape, in_dtype),
        ],
        interpret=interpret,
    )(*dkv_scalars, qh, kh, vh, gh, lse, delta)

    return _from_bh(dq, B, H), _from_bh(dk, B, H), _from_bh(dv, B, H)


@functools.lru_cache(maxsize=64)
def _make_sparse_fn(layout_bytes, layout_shape, block, causal, sm_scale,
                    interpret, dropout_rate=0.0):
    """Build (and cache) a differentiable block-sparse attention closure for
    one static layout. Both directions run the Pallas kernels: the
    backward walks the forward LUT for dQ and a transposed LUT for
    dK/dV. The closure takes a ``seed`` arg (None when dropout_rate is 0);
    masks regenerate in-kernel in both directions."""
    lut, nnz = _build_lut_cached(layout_bytes, layout_shape)
    layout = np.frombuffer(layout_bytes,
                           dtype=np.int64).reshape(layout_shape)
    lut_t, nnz_t = build_lut(layout.transpose(0, 2, 1))

    @jax.custom_vjp
    def f(q, k, v, seed):
        out, _ = _pallas_impl(q, k, v, lut, nnz, block, causal, sm_scale,
                              interpret=interpret,
                              dropout_rate=dropout_rate, dropout_seed=seed)
        return out

    def f_fwd(q, k, v, seed):
        out, lse = _pallas_impl(q, k, v, lut, nnz, block, causal, sm_scale,
                                interpret=interpret,
                                dropout_rate=dropout_rate,
                                dropout_seed=seed)
        return out, (q, k, v, seed, out, lse)

    def f_bwd(res, g):
        q, k, v, seed, out, lse = res
        dq, dk, dv = _pallas_bwd_impl(q, k, v, out, lse, g, lut, nnz,
                                      lut_t, nnz_t, block, causal,
                                      sm_scale, interpret=interpret,
                                      dropout_rate=dropout_rate,
                                      dropout_seed=seed)
        dseed = (None if seed is None
                 else np.zeros(jnp.shape(seed), jax.dtypes.float0))
        return dq, dk, dv, dseed

    f.defvjp(f_fwd, f_bwd)
    return f, lut, nnz


def block_sparse_attention(q, k, v, layout, block, causal=False,
                           sm_scale=None, rpe=None, key_padding_mask=None,
                           attn_mask=None, key_padding_mask_mode="add",
                           attn_mask_mode="mul", implementation="auto",
                           interpret=False,
                           dropout_rate=0.0, dropout_seed=None):
    """Fused block-sparse attention.

    q,k,v: [B, T, H, D]; layout: [H, T//block, T//block] 0/1 (numpy,
    static — from ``SparsityConfig.make_layout``). rpe: [B, H, T, T];
    key_padding_mask: [B, T]; attn_mask: [T, T] (mask semantics per the
    reference softmax op, `softmax.py:219`).

    ``dropout_rate`` (static) / ``dropout_seed`` (int32 scalar, traced
    ok): in-kernel attention-prob dropout with the same counter-based
    mask as the flash kernels (ops/pallas/flash_attention.py) — identical
    bits on every implementation at the same (head, q, k) coordinates.
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    T = q.shape[1]
    layout = np.asarray(layout).astype(np.int64)
    assert layout.shape[0] == q.shape[2], (
        f"layout heads {layout.shape[0]} != tensor heads {q.shape[2]}")
    assert layout.shape[1] * block == T, (
        f"layout covers {layout.shape[1] * block} positions, seq len is {T}")
    if dropout_rate:
        if not 0.0 <= dropout_rate < 1.0:
            raise ValueError(f"dropout_rate {dropout_rate} not in [0, 1)")
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        dropout_seed = jnp.asarray(dropout_seed, jnp.int32)

    has_extras = (rpe is not None or key_padding_mask is not None or
                  attn_mask is not None)
    if implementation == "auto":
        platform = jax.devices()[0].platform
        implementation = "pallas" if (platform == "tpu" and
                                      not has_extras) else "xla"
    if implementation == "pallas":
        assert not has_extras, (
            "rpe/masks route through implementation='xla'")
        fn, _, _ = _make_sparse_fn(layout.tobytes(), layout.shape, block,
                                   causal, float(sm_scale), interpret,
                                   float(dropout_rate))
        return fn(q, k, v, dropout_seed)
    if implementation == "xla":
        lut, nnz = _build_lut_cached(layout.tobytes(), layout.shape)
        return _xla_impl(q, k, v, lut, nnz, block, causal, sm_scale,
                         rpe=rpe, key_padding_mask=key_padding_mask,
                         attn_mask=attn_mask,
                         key_padding_mask_mode=key_padding_mask_mode,
                         attn_mask_mode=attn_mask_mode,
                         dropout_rate=dropout_rate,
                         dropout_seed=dropout_seed)
    raise ValueError(f"unknown implementation {implementation!r}")


def masked_dense_attention(q, k, v, layout, block, causal=False,
                           sm_scale=None, rpe=None, key_padding_mask=None,
                           attn_mask=None, key_padding_mask_mode="add",
                           attn_mask_mode="mul",
                           dropout_rate=0.0, dropout_seed=None):
    """Dense attention with the layout applied as an elementwise mask — the
    parity oracle for the sparse kernels (plays the role the dense-BERT
    fixture plays for the reference's `test_sparse_attention.py`)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    B, T, H, D = q.shape
    layout = np.asarray(layout)
    elem = np.kron(layout, np.ones((block, block)))  # [H, T, T]
    allowed = jnp.asarray(elem, bool)
    scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * sm_scale
    if rpe is not None:
        scores = scores + rpe.astype(jnp.float32)
    if key_padding_mask is not None:
        m = key_padding_mask.astype(jnp.float32)
        if key_padding_mask_mode == "mul":
            m = jnp.where(m == 0, DEFAULT_MASK_VALUE, 0.0)
        scores = scores + m[:, None, None, :]
    if attn_mask is not None:
        m = attn_mask.astype(jnp.float32)
        if attn_mask_mode == "mul":
            m = jnp.where(m == 0, DEFAULT_MASK_VALUE, 0.0)
        scores = scores + m[None, None]
    mask = allowed[None]
    if causal:
        tri = jnp.tril(jnp.ones((T, T), bool))
        mask = mask & tri[None, None]
    scores = jnp.where(mask, scores, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0:
        from deepspeed_tpu.ops.pallas.flash_attention import (
            _dropout_multiplier_full)
        probs = probs * _dropout_multiplier_full(B, H, T, T, dropout_rate,
                                                 dropout_seed)
    return jnp.einsum("bhts,bshd->bthd", probs, v.astype(jnp.float32)) \
        .astype(q.dtype)
