"""BertSparseSelfAttention: drop-in sparse replacement for a BERT
self-attention sub-module.

Analog of the reference's ``BertSparseSelfAttention``
(`deepspeed/ops/sparse_attention/bert_sparse_self_attention.py:9-78`):
BERT-named query/key/value projections feeding the layout-driven
:class:`SparseSelfAttention` core, taking the standard BERT additive
attention mask.
"""

from typing import Optional

import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention,
    collapse_additive_mask,
)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig,
    SparsityConfig,
)


class BertSparseSelfAttention(nn.Module):
    """``__call__(hidden_states, attention_mask)`` → context [B, T, H].

    ``num_attention_heads`` must divide ``hidden_size``;
    ``attention_mask`` is the BERT additive key-padding mask broadcastable
    to [B, 1, 1, T] (0 keep / large-negative pad), or None.
    """

    hidden_size: int
    num_attention_heads: int
    sparsity_config: Optional[SparsityConfig] = None
    dtype: jnp.dtype = jnp.float32
    # attention-prob dropout, applied in-kernel by the sparse core when
    # training (deterministic=False) — needs a "dropout" rng
    attn_dropout_ratio: float = 0.0

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None,
                 deterministic=True):
        H = self.hidden_size
        heads = self.num_attention_heads
        assert H % heads == 0, (
            f"hidden_size {H} not a multiple of heads {heads}")
        hd = H // heads
        B, T, _ = hidden_states.shape
        cfg = self.sparsity_config or FixedSparsityConfig(num_heads=heads)

        x = hidden_states.astype(self.dtype)
        q = nn.Dense(H, dtype=self.dtype, name="query")(x)
        k = nn.Dense(H, dtype=self.dtype, name="key")(x)
        v = nn.Dense(H, dtype=self.dtype, name="value")(x)

        def heads_first(t):  # [B, T, H] → [B, heads, T, hd]
            return t.reshape(B, T, heads, hd).transpose(0, 2, 1, 3)

        key_padding_mask = None
        if attention_mask is not None:
            key_padding_mask = collapse_additive_mask(attention_mask, B, T)

        rate, seed = 0.0, None
        if not deterministic and self.attn_dropout_ratio > 0.0:
            from deepspeed_tpu.ops.pallas.flash_attention import (
                dropout_seed_from_rng)
            rate = self.attn_dropout_ratio
            seed = dropout_seed_from_rng(self.make_rng("dropout"))

        core = SparseSelfAttention(cfg, key_padding_mask_mode="add")
        ctx = core(heads_first(q), heads_first(k), heads_first(v),
                   key_padding_mask=key_padding_mask,
                   dropout_rate=rate, dropout_seed=seed)
        return ctx.transpose(0, 2, 1, 3).reshape(B, T, H)
