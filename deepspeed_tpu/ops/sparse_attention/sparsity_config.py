"""Block-sparsity layout configurations.

Capability parity with the reference's `deepspeed/ops/sparse_attention/
sparsity_config.py:9-663` (SparsityConfig + Dense/Fixed/Variable/BigBird/
BSLongformer), re-designed for TPU use:

- layouts are NumPy ``int64`` arrays ``[num_heads, nb, nb]`` (nb = seq_len //
  block) built with vectorized index math instead of per-cell loops;
- random patterns (Variable/BigBird) draw from a seeded ``np.random.Generator``
  so layouts are reproducible across hosts — the reference uses the global
  ``random`` module, which breaks multi-process determinism;
- the same layout tensor drives both the Pallas block-sparse kernel and the
  masked-dense fallback (`block_sparse_attention.py`).
"""

import numpy as np


class SparsityConfig:
    """Base class: shared properties of block-sparse attention patterns
    (reference `sparsity_config.py:9`)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        """Zero layout ``[num_heads, nb, nb]``; seq_len must divide block."""
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence Length, {seq_len}, needs to be dividable by "
                f"Block size {self.block}!")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        """Share head 0's layout with all heads unless per-head layouts were
        requested (reference `sparsity_config.py:48`)."""
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks present — the dense pattern kept for comparison
    (reference `sparsity_config.py:63`)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


def _check_attention(attention, horizontal_global_attention):
    if attention not in ("unidirectional", "bidirectional"):
        raise NotImplementedError(
            'only "uni/bi-directional" attentions are supported for now!')
    if attention != "bidirectional" and horizontal_global_attention:
        raise ValueError(
            'only "bi-directional" attentions can support horizontal global '
            'attention!')


def _local_window(layout, h, start, end, attention):
    """Mark the dense window [start, end); unidirectional keeps the lower
    triangle only."""
    rows = np.arange(start, end)
    if attention == "unidirectional":
        r, c = np.meshgrid(rows, rows, indexing="ij")
        layout[h][np.ix_(rows, rows)] |= (c <= r).astype(np.int64)
    else:
        layout[h][np.ix_(rows, rows)] = 1


class FixedSparsityConfig(SparsityConfig):
    """Fixed pattern from "Generative Modeling with Sparse Transformers"
    (arXiv:1904.10509), as customized by the reference
    (`sparsity_config.py:94`): dense local windows of ``num_local_blocks``
    plus vertical (and optionally horizontal) global stripes anchored at
    each window's representative block(s)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_local_blocks=4,
                 num_global_blocks=1,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"Number of blocks in a local window, {num_local_blocks}, "
                f"must be dividable by number of global blocks, "
                f"{num_global_blocks}!")
        _check_attention(attention, horizontal_global_attention)
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "Number of different layouts cannot be more than one when "
                "you have set a single layout for all heads! Set "
                "different_layout_per_head to True.")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"Number of layout versions (num_different_global_patterns), "
                f"{num_different_global_patterns}, cannot be larger than "
                f"number of local window blocks divided by number of global "
                f"blocks, {num_local_blocks // num_global_blocks}!")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        nb = layout.shape[1]
        for i in range(0, nb, self.num_local_blocks):
            _local_window(layout, h, i, min(i + self.num_local_blocks, nb),
                          self.attention)
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        g = self.num_global_blocks
        # Representative blocks count back from the end of each window; with
        # per-head patterns head h uses the (h mod P)-th from the back.
        first = self.num_local_blocks - \
            (1 + h % self.num_different_global_patterns) * g
        end = nb - (nb % self.num_local_blocks)
        for i in range(first, end, self.num_local_blocks):
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + g] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + g, :] = 1
        if end < nb:  # short trailing window
            start = min(end + first, nb - g)
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:start + g] = 1
            if self.horizontal_global_attention:
                layout[h, start:start + g, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Fixed-style pattern with user-controlled knobs (reference
    `sparsity_config.py:243`): per-row random blocks, a list of local window
    sizes (last one repeats), and explicit global block indices/ranges."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=0,
                 local_window_blocks=None,
                 global_block_indices=None,
                 global_block_end_indices=None,
                 attention="bidirectional",
                 horizontal_global_attention=False,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        _check_attention(attention, horizontal_global_attention)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks or [4])
        self.global_block_indices = list(global_block_indices or [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    f"Global block start indices length, "
                    f"{len(self.global_block_indices)}, must be same as "
                    f"global block end indices length, "
                    f"{len(global_block_end_indices)}!")
            for start_idx, end_idx in zip(self.global_block_indices,
                                          global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(
                        f"Global block start index, {start_idx}, must be "
                        f"smaller than global block end index, {end_idx}!")
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def set_random_layout(self, h, layout):
        _random_layout(layout, h, self.num_random_blocks, self.seed)
        return layout

    def set_local_layout(self, h, layout):
        nb = layout.shape[1]
        start = 0
        for size in self.local_window_blocks:
            end = min(start + size, nb)
            _local_window(layout, h, start, end, self.attention)
            start += size
        # remaining rows reuse the last window size
        size = self.local_window_blocks[-1]
        for i in range(start, nb, size):
            _local_window(layout, h, i, min(i + size, nb), self.attention)
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start_idx, end_idx in spans:
            if start_idx >= nb:
                continue
            end_idx = min(end_idx, nb)
            if self.horizontal_global_attention:
                layout[h, start_idx:end_idx, :] = 1
            first_row = 0 if self.attention == "bidirectional" else start_idx
            layout[h, first_row:, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


def _random_layout(layout, h, num_random_blocks, seed):
    """Per-row random blocks from a (seed, head)-keyed Generator — same for
    all hosts, unlike the reference's global ``random`` module."""
    nb = layout.shape[1]
    if nb < num_random_blocks:
        raise ValueError(
            f"Number of random blocks, {num_random_blocks}, must be "
            f"smaller than overal number of blocks in a row, {nb}!")
    rng = np.random.default_rng((seed, h))
    for row in range(nb):
        cols = rng.choice(nb, size=num_random_blocks, replace=False)
        layout[h, row, cols] = 1


def _sliding_window(layout, h, num_window_blocks):
    """Symmetric sliding window of ``num_window_blocks`` around the diagonal."""
    nb = layout.shape[1]
    if nb < num_window_blocks:
        raise ValueError(
            f"Number of sliding window blocks, {num_window_blocks}, must be "
            f"smaller than overal number of blocks in a row, {nb}!")
    w = num_window_blocks // 2
    r = np.arange(nb)
    dist = np.abs(r[:, None] - r[None, :])
    layout[h][dist <= w] = 1


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (arXiv:2007.14062) ITC pattern: random + sliding window +
    leading global blocks (reference `sparsity_config.py:421`)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_random_blocks=1,
                 num_sliding_window_blocks=3,
                 num_global_blocks=1,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.seed = seed

    def set_random_layout(self, h, layout):
        _random_layout(layout, h, self.num_random_blocks, self.seed)
        return layout

    def set_sliding_window_layout(self, h, layout):
        _sliding_window(layout, h, self.num_sliding_window_blocks)
        return layout

    def set_global_layout_itc(self, h, layout):
        nb = layout.shape[1]
        if nb < self.num_global_blocks:
            raise ValueError(
                f"Number of global blocks, {self.num_global_blocks}, must be "
                f"smaller than overal number of blocks in a row, {nb}!")
        layout[h, :self.num_global_blocks, :] = 1
        layout[h, :, :self.num_global_blocks] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer (arXiv:2004.05150): sliding window + global
    rows/columns at chosen block indices (reference `sparsity_config.py:544`)."""

    def __init__(self,
                 num_heads,
                 block=16,
                 different_layout_per_head=False,
                 num_sliding_window_blocks=3,
                 global_block_indices=None,
                 global_block_end_indices=None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices or [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    f"Global block start indices length, "
                    f"{len(self.global_block_indices)}, must be same as "
                    f"global block end indices length, "
                    f"{len(global_block_end_indices)}!")
            for start_idx, end_idx in zip(self.global_block_indices,
                                          global_block_end_indices):
                if start_idx >= end_idx:
                    raise ValueError(
                        f"Global block start index, {start_idx}, must be "
                        f"smaller than global block end index, {end_idx}!")
        self.global_block_end_indices = global_block_end_indices

    def set_sliding_window_layout(self, h, layout):
        _sliding_window(layout, h, self.num_sliding_window_blocks)
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start_idx, end_idx in spans:
            if start_idx >= nb:
                continue
            end_idx = min(end_idx, nb)
            layout[h, start_idx:end_idx, :] = 1
            layout[h, :, start_idx:end_idx] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)
