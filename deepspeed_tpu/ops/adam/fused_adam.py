"""Fused Adam / AdamW optimizer.

TPU-native analog of the reference's ``FusedAdam``
(`deepspeed/ops/adam/fused_adam.py:15`, kernel `csrc/adam/multi_tensor_adam.cu`).
The CUDA version exists to batch many small elementwise kernels into one
launch; under ``jax.jit`` XLA already fuses the whole pytree update into a
handful of kernels, so the idiomatic TPU form is a pure functional update over
the param pytree with fp32 master state.
"""

from typing import NamedTuple, Any

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    m: Any          # first moment, fp32, same tree as params
    v: Any          # second moment, fp32
    step: jnp.ndarray  # i32 scalar — number of applied (non-skipped) steps


def init_adam_state(params) -> AdamState:
    zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
    return AdamState(
        m=jax.tree_util.tree_map(zeros, params),
        v=jax.tree_util.tree_map(zeros, params),
        step=jnp.asarray(0, jnp.int32),
    )


def adam_update(params,
                grads,
                state: AdamState,
                lr,
                beta1=0.9,
                beta2=0.999,
                eps=1e-8,
                weight_decay=0.0,
                adam_w_mode=True,
                bias_correction=True):
    """One fused Adam(W) step. Returns (new_params, new_state).

    Matches the reference kernel's math (`csrc/adam/multi_tensor_adam.cu`):
    ADAM_MODE_0 (adam_w_mode=True) decouples weight decay from the moments;
    ADAM_MODE_1 folds ``weight_decay * p`` into the gradient.
    """
    step = state.step + 1
    if bias_correction:
        bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
        bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
    else:
        bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

    def leaf_update(p, g, m, v):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if not adam_w_mode and weight_decay != 0.0:
            g32 = g32 + weight_decay * p32
        m_new = beta1 * m + (1.0 - beta1) * g32
        v_new = beta2 * v + (1.0 - beta2) * jnp.square(g32)
        update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if adam_w_mode and weight_decay != 0.0:
            update = update + weight_decay * p32
        p_new = (p32 - lr * update).astype(p.dtype)
        return p_new, m_new, v_new

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state.m)
    flat_v = treedef.flatten_up_to(state.v)
    out = [leaf_update(p, g, m, v)
           for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
    new_params = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_m = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    new_v = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
    return new_params, AdamState(m=new_m, v=new_v, step=step)


class FusedAdam:
    """API-parity wrapper around the functional update.

    Mirrors the reference constructor surface (lr, betas, eps, weight_decay,
    adam_w_mode, bias_correction); ``amsgrad`` is rejected the same way.
    """

    def __init__(self,
                 params=None,
                 lr=1e-3,
                 bias_correction=True,
                 betas=(0.9, 0.999),
                 eps=1e-8,
                 adam_w_mode=True,
                 weight_decay=0.0,
                 amsgrad=False,
                 set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = tuple(betas)
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.state = init_adam_state(params) if params is not None else None
        self.params = params

    def init(self, params):
        return init_adam_state(params)

    def update(self, params, grads, state, lr=None, beta1=None):
        return adam_update(
            params, grads, state,
            lr=self.lr if lr is None else lr,
            beta1=self.betas[0] if beta1 is None else beta1,
            beta2=self.betas[1],
            eps=self.eps,
            weight_decay=self.weight_decay,
            adam_w_mode=self.adam_w_mode,
            bias_correction=self.bias_correction)

    def step(self, grads):
        """Imperative convenience: updates held params/state in place."""
        assert self.params is not None, "construct with params to use .step()"
        self.params, self.state = self.update(self.params, grads, self.state)
        return self.params
