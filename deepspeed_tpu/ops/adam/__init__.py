"""Adam ops (reference `deepspeed/ops/adam/__init__.py` export surface)."""

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.adam.fused_adam import (
    AdamState, FusedAdam, adam_update, init_adam_state)

__all__ = ["DeepSpeedCPUAdam", "FusedAdam", "AdamState", "adam_update",
           "init_adam_state"]
