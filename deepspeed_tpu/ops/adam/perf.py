"""CPU Adam micro-benchmark (reference `tests/perf/adam_test.py:1-40` and
`adam_test1.py` — the measurements behind the reference's "5-7x faster"
DeepSpeedCPUAdam claim, `deepspeed/ops/adam/cpu_adam.py:18`).

Times one fused C++ step (SIMD+OpenMP over a flat fp32 buffer) against the
same math in (a) vectorized numpy and (b) torch.optim.Adam, at ~1e8
elements by default. Exposed as ``ds_tpu_report --perf`` and asserted
loosely (C++ >= numpy) by ``tests/perf/test_adam_perf.py``.
"""

import time

import numpy as np


def _numpy_adam_step(p, g, m, v, step, lr=1e-3, beta1=0.9, beta2=0.999,
                     eps=1e-8):
    """Unfused vectorized numpy AdamW-style update (bias-corrected)."""
    m *= beta1
    m += (1 - beta1) * g
    v *= beta2
    v += (1 - beta2) * (g * g)
    bc1 = 1 - beta1 ** step
    bc2 = 1 - beta2 ** step
    p -= lr * (m / bc1) / (np.sqrt(v / bc2) + eps)


def benchmark_cpu_adam(n=100_000_000, steps=5, include_torch=True, seed=0):
    """Returns {"n", "cpp_ms", "numpy_ms", "torch_ms", "vs_numpy",
    "vs_torch", "simd_width"} — per-step wall milliseconds (best of
    ``steps`` after one warmup each)."""
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam

    rng = np.random.default_rng(seed)
    params = {"w": rng.standard_normal(n).astype(np.float32)}
    grads = {"w": rng.standard_normal(n).astype(np.float32)}

    opt = DeepSpeedCPUAdam(params, lr=1e-3)
    times = []
    for _ in range(steps + 1):
        t0 = time.perf_counter()
        opt.step(grads)
        times.append(time.perf_counter() - t0)
    cpp_ms = min(times[1:]) * 1e3
    simd = int(opt.lib.ds_simd_width())

    p = params["w"].copy()
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    g = grads["w"]
    times = []
    for i in range(steps + 1):
        t0 = time.perf_counter()
        _numpy_adam_step(p, g, m, v, i + 1)
        times.append(time.perf_counter() - t0)
    numpy_ms = min(times[1:]) * 1e3

    torch_ms = None
    if include_torch:
        try:
            import torch
            tp = torch.from_numpy(params["w"].copy()).requires_grad_(True)
            tg = torch.from_numpy(g)
            topt = torch.optim.Adam([tp], lr=1e-3)
            tp.grad = tg
            times = []
            for _ in range(steps + 1):
                t0 = time.perf_counter()
                topt.step()
                times.append(time.perf_counter() - t0)
            torch_ms = min(times[1:]) * 1e3
        except ImportError:
            pass

    return {
        "n": n,
        "cpp_ms": round(cpp_ms, 2),
        "numpy_ms": round(numpy_ms, 2),
        "torch_ms": round(torch_ms, 2) if torch_ms is not None else None,
        "vs_numpy": round(numpy_ms / cpp_ms, 2),
        "vs_torch": round(torch_ms / cpp_ms, 2) if torch_ms else None,
        "simd_width": simd,
    }
