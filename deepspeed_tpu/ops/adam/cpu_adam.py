"""DeepSpeedCPUAdam: the host-RAM optimizer behind ZeRO-Offload.

Analog of the reference's ``DeepSpeedCPUAdam``
(`deepspeed/ops/adam/cpu_adam.py:12`, kernel `csrc/adam/cpu_adam.cpp`):
fp32 master weights and Adam moments live in host memory; each step runs
the AVX/OpenMP C++ kernel over one flat buffer, then hands back a bf16 (or
fp32) copy for the device upload — the analog of the reference's fused
fp16 param copy-back on a side stream.
"""

import ctypes
import itertools

import numpy as np
import jax

from deepspeed_tpu.ops.op_builder.builder import CPUAdamBuilder

_ids = itertools.count()


def _fptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    """Flat-buffer host AdamW over a params pytree.

    ``params`` (pytree of arrays) seeds the fp32 masters. ``step(grads)``
    takes the matching gradient pytree (device or host), updates masters in
    C++, and returns the updated params pytree as numpy fp32 views (zero
    copy) — callers device_put them at whatever dtype they need.
    """

    optimizer_id = None

    def __init__(self, params, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, bias_correction=True, adamw_mode=True,
                 amsgrad=False):
        if amsgrad:
            raise RuntimeError("CPUAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.betas = tuple(betas)
        self.eps = eps
        self.weight_decay = weight_decay
        self.lib = CPUAdamBuilder().load()
        self.opt_id = next(_ids)
        self.lib.ds_create_adam(
            self.opt_id, ctypes.c_float(lr), ctypes.c_float(betas[0]),
            ctypes.c_float(betas[1]), ctypes.c_float(eps),
            ctypes.c_float(weight_decay), int(adamw_mode),
            int(bias_correction))

        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = [np.shape(l) for l in leaves]
        self.sizes = [int(np.size(l)) for l in leaves]
        self.total = sum(self.sizes)
        self.offsets = np.cumsum([0] + self.sizes).tolist()
        # One contiguous fp32 master buffer + moment buffers.
        self.master = np.empty(self.total, np.float32)
        for leaf, off, size in zip(leaves, self.offsets, self.sizes):
            self.master[off:off + size] = np.asarray(
                leaf, np.float32).reshape(-1)
        self.exp_avg = np.zeros(self.total, np.float32)
        self.exp_avg_sq = np.zeros(self.total, np.float32)
        self._step = 0
        self._grad_buf = np.empty(self.total, np.float32)
        self._pool = None        # lazy 1-thread worker for step_overlapped
        self._chunks = None
        self._chunk_bytes = None
        self._bf16_buf = None
        # Resilience: bounded resubmission of failed range updates (the
        # engine sets these from its `resilience` config block). Retries
        # apply only to failures raised BEFORE the C++ kernel touches the
        # buffers (`host_state_clean` errors) — a mid-kernel failure may
        # have half-applied the moment update, so it surfaces as a typed
        # HostAdamError instead of being silently re-run.
        self.host_adam_retries = 0
        self.host_adam_timeout_s = None

    def __del__(self):
        try:
            if self._pool is not None:
                # Wait for in-flight _update_range work: the worker thread
                # calls ds_adam_step on this opt_id, so destroying the C++
                # optimizer under it is a use-after-free.
                self._pool.shutdown(wait=True)
            self.lib.ds_destroy_adam(self.opt_id)
        except Exception:
            pass

    # -- core --------------------------------------------------------------
    def step(self, grads, lr=None, beta1=None):
        """One Adam step; returns the updated params pytree (numpy fp32
        views into the master buffer). ``lr``/``beta1`` override the
        constructor values (schedule support)."""
        g_leaves = self.treedef.flatten_up_to(grads)
        for leaf, off, size in zip(g_leaves, self.offsets, self.sizes):
            self._grad_buf[off:off + size] = np.asarray(
                leaf, np.float32).reshape(-1)
        self._step += 1
        rc = self.lib.ds_adam_step(
            self.opt_id, ctypes.c_int64(self._step),
            ctypes.c_float(-1.0 if lr is None else lr),
            ctypes.c_float(-1.0 if beta1 is None else beta1),
            _fptr(self.master), _fptr(self._grad_buf), _fptr(self.exp_avg),
            _fptr(self.exp_avg_sq), ctypes.c_int64(self.total))
        assert rc == 0, f"ds_adam_step failed with {rc}"
        return self.params()

    # -- overlapped step ---------------------------------------------------
    def _chunk_plan(self, chunk_bytes):
        """Group whole leaves into contiguous flat ranges of ~chunk_bytes.

        Chunks are leaf-aligned because the D2H copy granularity is the
        leaf (``np.asarray`` materializes a whole array); a leaf larger
        than the target gets its own chunk — its Adam still overlaps the
        copies of the leaves that follow it."""
        target = max(1, chunk_bytes // 4)      # fp32 elements
        chunks = []                            # (leaf_lo, leaf_hi, off, n)
        i = 0
        while i < len(self.sizes):
            j, n = i, 0
            while j < len(self.sizes) and (n == 0 or
                                           n + self.sizes[j] <= target):
                n += self.sizes[j]
                j += 1
            chunks.append((i, j, self.offsets[i], n))
            i = j
        return chunks

    def _update_range(self, step, lr, beta1, off, n, to_bf16):
        """Adam (+ optional bf16 convert) on flat range [off, off+n) —
        the worker half of the overlapped step. The C kernel is stateless
        per call (config lookup only) and elementwise, so range calls are
        bitwise-identical to one full-buffer call."""
        rc = self.lib.ds_adam_step(
            self.opt_id, ctypes.c_int64(step), ctypes.c_float(lr),
            ctypes.c_float(beta1), _fptr(self.master[off:]),
            _fptr(self._grad_buf[off:]), _fptr(self.exp_avg[off:]),
            _fptr(self.exp_avg_sq[off:]), ctypes.c_int64(n))
        assert rc == 0, f"ds_adam_step failed with {rc}"
        if to_bf16:
            self.lib.ds_fp32_to_bf16(
                _fptr(self.master[off:]),
                self._bf16_buf[off:].ctypes.data_as(
                    ctypes.POINTER(ctypes.c_uint16)),
                ctypes.c_int64(n))

    def _guarded_update_range(self, step, lr, beta1, off, n, to_bf16):
        """Worker entry for submitted range updates: the fault-injection
        probe fires before the kernel, so an injected failure is always
        pre-mutation (exactly resubmittable)."""
        from deepspeed_tpu.runtime.resilience import fault_injection
        fault_injection.maybe_fail_host_adam()
        return self._update_range(step, lr, beta1, off, n, to_bf16)

    def submit_update_range(self, step, lr, beta1, off, n, to_bf16):
        """Submit one guarded range update to the worker; pair each future
        with :meth:`drain_update` (same args) to collect it."""
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(max_workers=1)
        return self._pool.submit(self._guarded_update_range, step, lr,
                                 beta1, off, n, to_bf16)

    def drain_update(self, fut, step, lr, beta1, off, n, to_bf16):
        """Wait for a submitted range update, resubmitting pre-mutation
        failures up to ``host_adam_retries`` times with backoff.

        Resubmitted ranges queue behind already-submitted chunks on the
        1-thread worker — safe, since ranges are disjoint. Exhausted
        retries and mid-kernel failures raise a typed ``HostAdamError``.
        """
        from deepspeed_tpu.runtime.resilience.retry import (
            HostAdamError, future_result_with_retry)
        what = f"host-Adam range [{off}, {off + n})"
        try:
            return fut.result(timeout=self.host_adam_timeout_s)
        except Exception as e:
            if not getattr(e, "host_state_clean", False):
                raise HostAdamError(
                    f"{what} failed mid-update ({type(e).__name__}: {e}); "
                    "host master/moment buffers may be partially updated — "
                    "restore from the last checkpoint") from e
            if self.host_adam_retries <= 0:
                raise HostAdamError(
                    f"{what} failed before touching host state "
                    f"({type(e).__name__}: {e}) and retries are disabled "
                    "(host_adam_retries=0)") from e
            return future_result_with_retry(
                lambda: self.submit_update_range(step, lr, beta1, off, n,
                                                 to_bf16),
                what=what, attempts=self.host_adam_retries,
                timeout_s=self.host_adam_timeout_s)

    def step_overlapped(self, grads, lr=None, beta1=None, bf16_out=False,
                        chunk_bytes=1 << 26, on_chunk=None):
        """One Adam step with the host phase software-pipelined.

        The reference's ZeRO-Offload is an overlap design (stage2.py:793
        async grad D2H during backward; cpu_adam.cpp fused async fp16
        copy-back). The TPU analog: start async D2H for EVERY grad leaf
        up front (``copy_to_host_async``), then walk leaf-aligned chunks —
        the main thread lands chunk k+1's bytes into the flat grad buffer
        (blocking only until that leaf's transfer arrives) while a worker
        thread runs the C++ Adam (and, with ``bf16_out``, the fused
        fp32→bf16 convert) on chunk k. ctypes releases the GIL, so copy
        and compute genuinely overlap. Chunk ranges are disjoint across
        master/grad/moment/bf16 buffers — no locking needed.

        ``on_chunk(leaf_lo, leaf_hi)`` (optional) runs on the CALLING
        thread as each chunk's update (and convert) completes, in chunk
        order, while the worker continues later chunks — the engine uses
        it to start each chunk's param H2D upload during the remaining
        Adam compute (the copy-back overlap of the reference's
        cpu_adam.cpp side stream).

        Returns the params pytree (fp32 views), or with ``bf16_out`` the
        flat bf16 master copy ready for one device upload.
        """
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(max_workers=1)
        if self._chunks is None or chunk_bytes != self._chunk_bytes:
            self._chunks = self._chunk_plan(chunk_bytes)
            self._chunk_bytes = chunk_bytes
        if bf16_out and self._bf16_buf is None:
            self._bf16_buf = np.empty(self.total, np.uint16)
        g_leaves = self.treedef.flatten_up_to(grads)
        for g in g_leaves:
            start = getattr(g, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:
                    pass           # non-addressable/committed: asarray blocks
        self._step += 1
        step = self._step
        eff_lr = -1.0 if lr is None else lr
        eff_b1 = -1.0 if beta1 is None else beta1
        futs = []
        for (li, lj, off, n) in self._chunks:
            for k in range(li, lj):
                o, s = self.offsets[k], self.sizes[k]
                self._grad_buf[o:o + s] = np.asarray(
                    g_leaves[k], np.float32).reshape(-1)
            futs.append(self.submit_update_range(
                step, eff_lr, eff_b1, off, n, bf16_out))
        for (li, lj, off, n), f in zip(self._chunks, futs):
            # propagate worker failures (in order), retrying clean ones
            self.drain_update(f, step, eff_lr, eff_b1, off, n, bf16_out)
            if on_chunk is not None:
                on_chunk(li, lj)
        if bf16_out:
            import ml_dtypes
            return self._bf16_buf.view(ml_dtypes.bfloat16)
        return self.params()

    def params(self):
        """Current masters as a pytree of fp32 numpy views (no copy)."""
        leaves = [self.master[off:off + size].reshape(shape)
                  for off, size, shape in zip(self.offsets, self.sizes,
                                              self.shapes)]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def params_bf16_flat(self):
        """Masters converted to bf16 by the fused C++ kernel, as one flat
        uint16 buffer (bit pattern of bf16) ready for device upload."""
        import ml_dtypes
        out = np.empty(self.total, np.uint16)
        self.lib.ds_fp32_to_bf16(
            _fptr(self.master),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            ctypes.c_int64(self.total))
        return out.view(ml_dtypes.bfloat16)

    # -- checkpoint --------------------------------------------------------
    def state_dict(self):
        return {"master": self.master.copy(), "exp_avg": self.exp_avg.copy(),
                "exp_avg_sq": self.exp_avg_sq.copy(), "step": self._step}

    def load_state_dict(self, state):
        self.master[:] = np.asarray(state["master"], np.float32).reshape(-1)
        self.exp_avg[:] = np.asarray(state["exp_avg"],
                                     np.float32).reshape(-1)
        self.exp_avg_sq[:] = np.asarray(state["exp_avg_sq"],
                                        np.float32).reshape(-1)
        self._step = int(state["step"])
