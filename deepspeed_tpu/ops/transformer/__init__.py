from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer,
    init_transformer_layer)

__all__ = ["DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer",
           "init_transformer_layer"]
