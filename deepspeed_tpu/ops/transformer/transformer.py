"""DeepSpeedTransformerLayer: the fused BERT-style transformer block.

Capability parity with the reference's CUDA transformer kernel
(`deepspeed/ops/transformer/transformer.py:41-111` config,
`csrc/transformer/ds_transformer_cuda.cpp:44-121` layer composition:
QKV GEMM → strided-batch attention GEMMs → masked softmax → dropouts →
layernorms → bias-GeLU FFN), re-designed for TPU:

- the hand-fused CUDA kernels (normalize/softmax/dropout/gelu/transform
  kernels, ~5.9k LoC) become one traced function XLA fuses itself; the
  attention core optionally runs the Pallas flash kernel;
- the memory knobs keep their *semantics* as rematerialization policies:
  ``normalize_invertible`` / ``gelu_checkpoint`` / ``attn_dropout_
  checkpoint`` (reference drops those buffers and recomputes in backward)
  → ``jax.checkpoint`` over the corresponding sub-blocks;
- Philox dropout state (`csrc/includes/context.h:177`) → explicit PRNG
  keys; ``stochastic_mode`` is accepted for config parity (XLA kernels are
  deterministic anyway);
- the per-layer C++ object registry (`s_transformer_layers`,
  ds_transformer_cuda.cpp:15) is unnecessary — layers are pure functions
  of their params.

Weight names mirror the reference layer (attn_qkvw/attn_qkvb/attn_ow/
attn_ob/attn_nw/attn_nb/inter_w/inter_b/output_w/output_b/norm_w/norm_b)
so state dicts translate 1:1.
"""

import dataclasses
import json
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn


class DeepSpeedTransformerConfig:
    """Mirror of the reference config surface
    (`ops/transformer/transformer.py:41-111`)."""

    def __init__(self,
                 batch_size=-1,
                 max_seq_length=-1,
                 hidden_size=-1,
                 intermediate_size=-1,
                 heads=-1,
                 attn_dropout_ratio=-1,
                 hidden_dropout_ratio=-1,
                 num_hidden_layers=-1,
                 initializer_range=-1,
                 local_rank=-1,
                 seed=-1,
                 fp16=False,
                 pre_layer_norm=True,
                 normalize_invertible=False,
                 gelu_checkpoint=False,
                 adjust_init_range=True,
                 attn_dropout_checkpoint=False,
                 stochastic_mode=False,
                 huggingface=False):
        self.batch_size = batch_size
        self.max_seq_length = max_seq_length
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size if intermediate_size > 0 \
            else 4 * hidden_size
        self.heads = heads
        self.attn_dropout_ratio = max(attn_dropout_ratio, 0.0)
        self.hidden_dropout_ratio = max(hidden_dropout_ratio, 0.0)
        self.num_hidden_layers = num_hidden_layers
        self.initializer_range = initializer_range if initializer_range > 0 \
            else 0.02
        self.local_rank = local_rank
        self.seed = seed
        self.fp16 = fp16
        self.pre_layer_norm = pre_layer_norm
        self.normalize_invertible = normalize_invertible
        self.gelu_checkpoint = gelu_checkpoint
        self.adjust_init_range = adjust_init_range
        self.attn_dropout_checkpoint = attn_dropout_checkpoint
        self.stochastic_mode = stochastic_mode
        self.huggingface = huggingface

    @property
    def dtype(self):
        return jnp.float16 if self.fp16 else jnp.float32

    @classmethod
    def from_dict(cls, json_object):
        config = cls()
        for key, value in json_object.items():
            setattr(config, key, value)
        return config

    @classmethod
    def from_json_file(cls, json_file):
        with open(json_file, "r", encoding="utf-8") as reader:
            return cls.from_dict(json.loads(reader.read()))


def _is_key_padding_shape(shape, B, T):
    """True when an additive mask is broadcastable to [B, 1, 1, T] — i.e.
    constant over query positions, so it collapses to a key-padding row."""
    if len(shape) > 4:
        return False
    padded = (1,) * (4 - len(shape)) + tuple(shape)
    ok_b = padded[0] in (1, B)
    ok_heads = padded[1] == 1
    ok_q = padded[2] == 1
    ok_k = padded[3] in (1, T)
    return ok_b and ok_heads and ok_q and ok_k


class DeepSpeedTransformerLayer(nn.Module):
    """One transformer encoder block (reference ``DeepSpeedTransformerLayer``,
    `ops/transformer/transformer.py` + the C++ composition cited above).

    ``__call__(hidden_states, attention_mask, deterministic)``:
    ``hidden_states`` [B, T, H]; ``attention_mask`` is the BERT-style
    additive mask broadcastable to [B, heads, T, T] (e.g. [B, 1, 1, T] with
    0 for keep / -10000 for pad), or None.
    """

    config: DeepSpeedTransformerConfig
    use_flash_attention: bool = False
    # SparsityConfig instance → block-sparse attention core (the
    # SparseAttentionUtils adoption path; layout heads must match).
    sparsity_config: Optional[Any] = None

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None,
                 deterministic=True):
        cfg = self.config
        H = cfg.hidden_size
        I = cfg.intermediate_size
        heads = cfg.heads
        dtype = cfg.dtype
        B, T, _ = hidden_states.shape
        std = cfg.initializer_range
        # The reference shrinks the output-projection init by 1/sqrt(2L)
        # when adjust_init_range is on (transformer.py "output std dev").
        out_std = std / (2.0 * max(cfg.num_hidden_layers, 1)) ** 0.5 \
            if cfg.adjust_init_range else std

        init = nn.initializers.normal
        attn_qkvw = self.param("attn_qkvw", init(std), (H, 3 * H))
        attn_qkvb = self.param("attn_qkvb", nn.initializers.zeros, (3 * H,))
        attn_ow = self.param("attn_ow", init(out_std), (H, H))
        attn_ob = self.param("attn_ob", nn.initializers.zeros, (H,))
        attn_nw = self.param("attn_nw", nn.initializers.ones, (H,))
        attn_nb = self.param("attn_nb", nn.initializers.zeros, (H,))
        inter_w = self.param("inter_w", init(std), (H, I))
        inter_b = self.param("inter_b", nn.initializers.zeros, (I,))
        output_w = self.param("output_w", init(out_std), (I, H))
        output_b = self.param("output_b", nn.initializers.zeros, (H,))
        norm_w = self.param("norm_w", nn.initializers.ones, (H,))
        norm_b = self.param("norm_b", nn.initializers.zeros, (H,))

        def layer_norm(x, w, b):
            x32 = x.astype(jnp.float32)
            mu = x32.mean(-1, keepdims=True)
            var = x32.var(-1, keepdims=True)
            y = (x32 - mu) * jax.lax.rsqrt(var + 1e-12)
            return (y * w + b).astype(dtype)

        def dropout(x, rate, name):
            if deterministic or rate == 0.0:
                return x
            keep = 1.0 - rate
            mask = jax.random.bernoulli(
                self.make_rng("dropout"), keep, x.shape)
            return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

        x = hidden_states.astype(dtype)

        def attn_drop_args():
            """(rate, seed) for the in-kernel attention-prob dropout of
            the fused cores — one derivation for every branch so the
            sparse and flash paths consume the identical rng stream."""
            if deterministic or cfg.attn_dropout_ratio == 0.0:
                return 0.0, None
            from deepspeed_tpu.ops.pallas.flash_attention import (
                dropout_seed_from_rng)
            return (cfg.attn_dropout_ratio,
                    dropout_seed_from_rng(self.make_rng("dropout")))

        # ---- attention sub-block ------------------------------------
        def attention(xin):
            qkv = xin @ attn_qkvw.astype(dtype) + attn_qkvb.astype(dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            hd = H // heads
            q = q.reshape(B, T, heads, hd)
            k = k.reshape(B, T, heads, hd)
            v = v.reshape(B, T, heads, hd)
            if self.sparsity_config is not None:
                from deepspeed_tpu.ops.sparse_attention import (
                    SparseSelfAttention)
                from deepspeed_tpu.ops.sparse_attention.\
                    sparse_self_attention import collapse_additive_mask
                core = SparseSelfAttention(self.sparsity_config,
                                           key_padding_mask_mode="add")
                kpm = None
                if attention_mask is not None:
                    kpm = collapse_additive_mask(attention_mask, B, T)
                # in-kernel attn-prob dropout (round 4; the sparse core
                # previously skipped it silently)
                rate, seed = attn_drop_args()
                ctx = core(q.transpose(0, 2, 1, 3),
                           k.transpose(0, 2, 1, 3),
                           v.transpose(0, 2, 1, 3),
                           key_padding_mask=kpm,
                           dropout_rate=rate,
                           dropout_seed=seed).transpose(0, 2, 1, 3)
            elif self.use_flash_attention and (
                    attention_mask is None or
                    _is_key_padding_shape(attention_mask.shape, B, T)):
                # BERT-style [B,1,1,T] additive masks collapse to a key
                # bias the flash kernels add natively (round 3) — soft
                # penalties honored exactly. Attention-prob dropout runs
                # inside the kernels (round 4, counter-based mask —
                # the `dropout_kernels.cu` capability), so training
                # configs stay on the flash path; only per-query masks
                # (e.g. [B,1,T,T]) still fall through to dense.
                from deepspeed_tpu.ops.pallas.flash_attention import (
                    flash_attention)
                from deepspeed_tpu.ops.sparse_attention.\
                    sparse_self_attention import collapse_additive_mask
                kbias = None
                if attention_mask is not None:
                    kbias = collapse_additive_mask(attention_mask, B, T)
                rate, seed = attn_drop_args()
                ctx = flash_attention(q, k, v, causal=False,
                                      key_bias=kbias,
                                      dropout_rate=rate,
                                      dropout_seed=seed)
            else:
                scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
                att = jnp.einsum("bthd,bshd->bhts", q, k).astype(
                    jnp.float32) * scale
                if attention_mask is not None:
                    att = att + attention_mask.astype(jnp.float32)
                att = jax.nn.softmax(att, axis=-1).astype(dtype)
                att = dropout(att, cfg.attn_dropout_ratio, "attn_drop")
                ctx = jnp.einsum("bhts,bshd->bthd", att, v)
            ctx = ctx.reshape(B, T, H)
            out = ctx @ attn_ow.astype(dtype) + attn_ob.astype(dtype)
            return dropout(out, cfg.hidden_dropout_ratio, "attn_out_drop")

        # attn_dropout_checkpoint: the reference frees the attention
        # dropout/score buffers and recomputes them in backward
        # (ds_transformer_cuda.cpp attn_dropout_checkpoint) — here the
        # whole attention sub-block rematerializes.
        if cfg.attn_dropout_checkpoint:
            attention = jax.checkpoint(attention, prevent_cse=False)

        # ---- FFN sub-block ------------------------------------------
        def ffn(xin):
            h = xin @ inter_w.astype(dtype) + inter_b.astype(dtype)
            h = jax.nn.gelu(h, approximate=False)
            h = h @ output_w.astype(dtype) + output_b.astype(dtype)
            return dropout(h, cfg.hidden_dropout_ratio, "ffn_drop")

        # gelu_checkpoint: reference recomputes the [B,T,I] GeLU buffer in
        # backward; same effect via remat of the FFN.
        if cfg.gelu_checkpoint:
            ffn = jax.checkpoint(ffn, prevent_cse=False)

        def ln_attn(xin):
            return layer_norm(xin, attn_nw, attn_nb)

        def ln_out(xin):
            return layer_norm(xin, norm_w, norm_b)

        # normalize_invertible: reference drops the LN inputs and inverts
        # in backward; remat of the norms keeps the same memory shape.
        if cfg.normalize_invertible:
            ln_attn = jax.checkpoint(ln_attn, prevent_cse=False)
            ln_out = jax.checkpoint(ln_out, prevent_cse=False)

        if cfg.pre_layer_norm:
            x = x + attention(ln_attn(x))
            x = x + ffn(ln_out(x))
        else:
            x = ln_attn(x + attention(x))
            x = ln_out(x + ffn(x))
        return x


def init_transformer_layer(layer, rng, batch_size=2, seq_len=None):
    cfg = layer.config
    T = seq_len or (cfg.max_seq_length if cfg.max_seq_length > 0 else 32)
    dummy = jnp.zeros((batch_size, T, cfg.hidden_size), cfg.dtype)
    return layer.init({"params": rng, "dropout": rng}, dummy)["params"]
