"""Lamb ops (reference `deepspeed/ops/lamb/__init__.py` export surface)."""

from deepspeed_tpu.ops.lamb.fused_lamb import (
    FusedLamb, LambState, init_lamb_state, lamb_update)

__all__ = ["FusedLamb", "LambState", "init_lamb_state", "lamb_update"]
