"""Fused LAMB optimizer.

TPU-native analog of the reference's ``FusedLamb``
(`deepspeed/ops/lamb/fused_lamb.py`, kernel `csrc/lamb/fused_lamb_cuda_kernel.cu`).
The CUDA kernel's two-stage block reductions for the update/param norms are
plain ``jnp`` reductions here — XLA maps them onto the VPU and fuses them with
the elementwise update. Trust-ratio clamping (``max_coeff``/``min_coeff``)
matches the reference kernel's lamb-coefficient clamp.
"""

from typing import NamedTuple, Any

import jax
import jax.numpy as jnp


class LambState(NamedTuple):
    m: Any
    v: Any
    step: jnp.ndarray


def init_lamb_state(params) -> LambState:
    zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
    return LambState(
        m=jax.tree_util.tree_map(zeros, params),
        v=jax.tree_util.tree_map(zeros, params),
        step=jnp.asarray(0, jnp.int32),
    )


def lamb_update(params,
                grads,
                state: LambState,
                lr,
                beta1=0.9,
                beta2=0.999,
                eps=1e-8,
                weight_decay=0.0,
                bias_correction=True,
                max_coeff=10.0,
                min_coeff=0.01):
    """One LAMB step: adam-style moments, per-tensor trust ratio."""
    step = state.step + 1
    if bias_correction:
        bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
        bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
    else:
        bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

    def leaf_update(p, g, m, v):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m_new = beta1 * m + (1.0 - beta1) * g32
        v_new = beta2 * v + (1.0 - beta2) * jnp.square(g32)
        update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if weight_decay != 0.0:
            update = update + weight_decay * p32
        # Per-tensor trust ratio with the reference kernel's clamp.
        p_norm = jnp.linalg.norm(p32)
        u_norm = jnp.linalg.norm(update)
        ratio = jnp.where(u_norm > 0, p_norm / (u_norm + eps), 1.0)
        ratio = jnp.clip(ratio, min_coeff, max_coeff)
        ratio = jnp.where(p_norm > 0, ratio, 1.0)
        p_new = (p32 - lr * ratio * update).astype(p.dtype)
        return p_new, m_new, v_new

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state.m)
    flat_v = treedef.flatten_up_to(state.v)
    out = [leaf_update(p, g, m, v)
           for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
    new_params = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_m = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    new_v = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
    return new_params, LambState(m=new_m, v=new_v, step=step)


class FusedLamb:
    """API-parity wrapper (constructor surface of the reference FusedLamb)."""

    def __init__(self,
                 params=None,
                 lr=1e-3,
                 bias_correction=True,
                 betas=(0.9, 0.999),
                 eps=1e-8,
                 weight_decay=0.0,
                 max_grad_norm=0.0,
                 max_coeff=10.0,
                 min_coeff=0.01,
                 amsgrad=False):
        if amsgrad:
            raise RuntimeError("FusedLamb does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = tuple(betas)
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.params = params
        self.state = init_lamb_state(params) if params is not None else None

    def init(self, params):
        return init_lamb_state(params)

    def update(self, params, grads, state, lr=None, beta1=None):
        return lamb_update(
            params, grads, state,
            lr=self.lr if lr is None else lr,
            beta1=self.betas[0] if beta1 is None else beta1,
            beta2=self.betas[1],
            eps=self.eps,
            weight_decay=self.weight_decay,
            bias_correction=self.bias_correction,
            max_coeff=self.max_coeff,
            min_coeff=self.min_coeff)

    def step(self, grads):
        assert self.params is not None
        self.params, self.state = self.update(self.params, grads, self.state)
        return self.params
