"""Native-op build system: JIT-compile C++ sources with g++, load via ctypes.

Analog of the reference's ``op_builder/builder.py`` (``OpBuilder.load/
jit_load/builder``, :54,:146,:158): each op declares its sources and flags;
``load()`` compiles on first use into a content-addressed shared library
under ``.op_cache/`` and returns the loaded module. Differences forced by
this environment: no pybind11/torch extension machinery — ops expose a
plain C ABI consumed through ``ctypes`` (the CPython-native route), and
there is no CUDA-version matching to assert (XLA owns the device; native
ops here are *host* ops).
"""

import ctypes
import hashlib
import os
import subprocess
import sysconfig
from pathlib import Path

from deepspeed_tpu.utils.logging import logger

REPO_ROOT = Path(__file__).resolve().parents[3]
PKG_ROOT = Path(__file__).resolve().parents[2]

# Source layout: repo checkout keeps csrc/ at the top level; installed
# wheels carry it inside the package (setup.py build_py copies it to
# deepspeed_tpu/csrc).
if (REPO_ROOT / "csrc").is_dir():
    CSRC = REPO_ROOT / "csrc"
else:
    CSRC = PKG_ROOT / "csrc"


def _default_cache_dir():
    env = os.environ.get("DS_TPU_OP_CACHE")
    if env:
        return Path(env)
    # Per-user cache (torch-extensions-style ~/.cache layout), NOT a
    # source-tree path: builds are content-addressed, and a single
    # location means a DS_BUILD_OPS prebuild at pip-install time (which
    # runs in a throwaway copy of the tree) is found by the installed
    # package at runtime.
    return Path(os.environ.get("XDG_CACHE_HOME",
                               Path.home() / ".cache")) / \
        "deepspeed_tpu" / "op_cache"


CACHE_DIR = _default_cache_dir()


class OpBuilder:
    """Base class; subclasses set NAME and sources()."""

    NAME = None
    BUILD_ENV_GATE = "DS_BUILD_OPS"

    def __init__(self):
        self._lib = None

    # -- interface ---------------------------------------------------------
    def sources(self):
        raise NotImplementedError

    def include_paths(self):
        return []

    def cxx_args(self):
        args = ["-O3", "-std=c++17", "-fPIC", "-shared", "-fopenmp"]
        args += self._simd_args()
        return args

    def _simd_args(self):
        flags = []
        cpuinfo = ""
        try:
            cpuinfo = Path("/proc/cpuinfo").read_text()
        except OSError:
            pass
        if "avx512f" in cpuinfo:
            flags.append("-mavx512f")
        if "avx2" in cpuinfo:
            # The AVX2 kernels use _mm256_fmadd/_fnmadd, which need FMA;
            # every AVX2 CPU has it, but containers can mask the cpuinfo
            # flag — always pair -mfma with -mavx2.
            flags += ["-mavx2", "-mfma"]
        elif "fma" in cpuinfo:
            flags.append("-mfma")
        return flags

    def is_compatible(self):
        """Can this op build here? (the ``ds_report`` compatibility column)"""
        return self.command_exists("g++")

    @staticmethod
    def command_exists(cmd):
        from shutil import which
        return which(cmd) is not None

    # -- build/load --------------------------------------------------------
    def _build_key(self):
        h = hashlib.sha256()
        for src in self.sources():
            h.update(Path(src).read_bytes())
        h.update(" ".join(self.cxx_args()).encode())
        return h.hexdigest()[:16]

    def lib_path(self):
        return CACHE_DIR / f"{self.NAME}_{self._build_key()}.so"

    def jit_load(self, verbose=True):
        out = self.lib_path()
        if not out.exists():
            CACHE_DIR.mkdir(parents=True, exist_ok=True)
            cmd = (["g++"] + self.cxx_args() +
                   [f"-I{p}" for p in self.include_paths()] +
                   [str(s) for s in self.sources()] + ["-o", str(out)])
            if verbose:
                logger.info(f"building op {self.NAME}: {' '.join(cmd)}")
            # Per-process tmp name: concurrent builders (pytest workers,
            # launcher ranks) must not interleave writes before the atomic
            # rename.
            tmp = out.with_suffix(f".so.tmp.{os.getpid()}")
            try:
                subprocess.run(cmd[:-1] + [str(tmp)], check=True,
                               capture_output=True, text=True)
            except subprocess.CalledProcessError as e:
                raise RuntimeError(
                    f"failed to build native op {self.NAME}:\n{e.stderr}")
            os.replace(tmp, out)
        return ctypes.CDLL(str(out))

    def load(self, verbose=True):
        if self._lib is None:
            self._lib = self.jit_load(verbose=verbose)
        return self._lib


class CPUAdamBuilder(OpBuilder):
    """Host AdamW for ZeRO-Offload (reference ``op_builder/cpu_adam.py:7``,
    kernel `csrc/adam/cpu_adam.cpp`)."""

    NAME = "cpu_adam"

    def sources(self):
        return [CSRC / "adam" / "cpu_adam.cpp"]

    def load(self, verbose=True):
        lib = super().load(verbose=verbose)
        i64, f32 = ctypes.c_int64, ctypes.c_float
        pf = ctypes.POINTER(ctypes.c_float)
        pu16 = ctypes.POINTER(ctypes.c_uint16)
        lib.ds_create_adam.argtypes = [ctypes.c_int, f32, f32, f32, f32,
                                       f32, ctypes.c_int, ctypes.c_int]
        lib.ds_destroy_adam.argtypes = [ctypes.c_int]
        lib.ds_adam_step.argtypes = [ctypes.c_int, i64, f32, f32, pf, pf,
                                     pf, pf, i64]
        lib.ds_adam_step.restype = ctypes.c_int
        lib.ds_fp32_to_bf16.argtypes = [pf, pu16, i64]
        lib.ds_simd_width.restype = ctypes.c_int
        return lib


class SparseAttnBuilder(OpBuilder):
    """Block-sparse LUT construction (reference ``op_builder/sparse_attn
    .py:6`` — its only C++ is the ``sdd_segment`` LUT helper; ours is
    `csrc/sparse_attention/lut_builder.cpp`)."""

    NAME = "sparse_attn"

    def sources(self):
        return [CSRC / "sparse_attention" / "lut_builder.cpp"]

    def load(self, verbose=True):
        lib = super().load(verbose=verbose)
        i64 = ctypes.c_int64
        p64 = ctypes.POINTER(ctypes.c_int64)
        p32 = ctypes.POINTER(ctypes.c_int32)
        lib.ds_build_lut.argtypes = [p64, i64, i64, i64, i64, p32, p32]
        lib.ds_lut_max_nnz.argtypes = [p64, i64, i64, i64]
        lib.ds_lut_max_nnz.restype = i64
        return lib


class UtilsBuilder(OpBuilder):
    """flatten/unflatten packing (reference ``op_builder/utils.py:4``,
    kernel `csrc/utils/flatten_unflatten.cpp`)."""

    NAME = "utils"

    def sources(self):
        return [CSRC / "utils" / "flatten_unflatten.cpp"]

    def load(self, verbose=True):
        lib = super().load(verbose=verbose)
        i64 = ctypes.c_int64
        pf = ctypes.POINTER(ctypes.c_float)
        ppf = ctypes.POINTER(pf)
        pi64 = ctypes.POINTER(i64)
        lib.ds_flatten.argtypes = [ppf, pi64, ctypes.c_int32, pf]
        lib.ds_unflatten.argtypes = [pf, pi64, ctypes.c_int32, ppf]
        return lib
