"""Native-op registry (reference ``op_builder/__init__.py:12-20`` ALL_OPS)."""

from deepspeed_tpu.ops.op_builder.builder import (
    CPUAdamBuilder, OpBuilder, SparseAttnBuilder, UtilsBuilder)

ALL_OPS = {
    CPUAdamBuilder.NAME: CPUAdamBuilder,
    SparseAttnBuilder.NAME: SparseAttnBuilder,
    UtilsBuilder.NAME: UtilsBuilder,
}

__all__ = ["OpBuilder", "CPUAdamBuilder", "SparseAttnBuilder",
           "UtilsBuilder", "ALL_OPS"]
