"""fp8 matmuls with per-tensor delayed scaling (Transformer-Engine
recipe, functional-JAX form).

The GEMM itself is a plain ``lax.dot_general`` over quantize-dequantized
operands: each operand is scaled into the fp8 range, cast to
``float8_e4m3fn`` and immediately back (``qdq``), and the dot runs on the
dequantized values. On TPU/GPU, XLA pattern-matches the
``convert(f8) @ convert(f8)`` pair into a native fp8 GEMM; on CPU the
converts stay explicit — which is exactly what the audit's HLO pin
checks for (``f8e4m3fn`` dot operands in the lowered text).

Scaling state (the delayed-scaling recipe):

- forward operands quantize to ``f8e4m3fn`` (qmax 448), backward
  cotangents to ``f8e5m2`` (qmax 57344) — the standard fwd-range /
  bwd-dynamic-range split;
- ``scale = max(amax_history) / (qmax / 2**margin)``, with an all-zero
  history bootstrapping to scale 1;
- each call records the current ``|x|`` max by rolling it into the
  history.

The state plumbing uses the gradient-as-state-update trick (the flax
``fp8_ops`` pattern): amax histories are *differentiable arguments* of
the qdq ``custom_vjp``s, whose backward returns the UPDATED history as
the history's "gradient". The engine differentiates the loss w.r.t.
``(params, fp8_state)`` and the fp8-state "grads" simply ARE the next
step's state — no trace-time mutation, no stale closures, and
``jax.checkpoint`` replays (which re-run the traced body, not the
Python) stay consistent.

Call sites reach the machinery through :func:`fp8_dot_general`, a
drop-in ``dot_general`` replacement (e.g. flax ``nn.Dense(dot_general=
fp8_dot_general)``) that reads the trace-time :func:`fp8_scope` exactly
like the overlap plan: no scope → plain ``lax.dot_general`` (zero
overhead when fp8 is off). With a scope but no state dict (the manual
TP/pipeline path, where per-site state threading isn't available) it
falls back to *current scaling* — scales from the current amax, no
history.
"""

import contextlib
import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

E4M3_MAX = 448.0
E5M2_MAX = 57344.0


def compute_scale(history, qmax, margin=0):
    """Delayed scale from an amax history: ``max(h) / (qmax / 2**margin)``
    with an empty (all-zero) history bootstrapping to scale 1."""
    amax = jnp.max(history)
    amax = jnp.where(amax > 0.0, amax, 1.0)
    return (amax / (qmax / (2.0 ** margin))).astype(jnp.float32)


def update_history(history, x):
    """Roll the current ``|x|`` max into the front of the history."""
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    return jnp.concatenate([amax[None], history[:-1]])


def quantize_dequantize(x, scale, qmax, dtype):
    """Scale into the fp8 range, saturate-cast to ``dtype`` and back —
    the qdq pair XLA fuses into a native fp8 GEMM operand."""
    scaled = (x.astype(jnp.float32) / scale)
    q = jnp.clip(scaled, -qmax, qmax).astype(dtype)
    return (q.astype(jnp.float32) * scale).astype(x.dtype)


# ----------------------------------------------------------------------
# delayed scaling: history-carrying qdq pair (grad-as-state-update)
# ----------------------------------------------------------------------

@jax.custom_vjp
def in_qdq(x, history):
    """Forward-operand qdq (``f8e4m3fn``) against the delayed scale from
    ``history``. Differentiating w.r.t. ``history`` yields the UPDATED
    history — the engine treats that "gradient" as the next state."""
    scale = compute_scale(history, E4M3_MAX, _MARGIN[0])
    return quantize_dequantize(x, scale, E4M3_MAX, jnp.float8_e4m3fn)


def _in_qdq_fwd(x, history):
    scale = compute_scale(history, E4M3_MAX, _MARGIN[0])
    y = quantize_dequantize(x, scale, E4M3_MAX, jnp.float8_e4m3fn)
    return y, update_history(history, x)


def _in_qdq_bwd(new_history, g):
    # Straight-through on x (qdq is identity inside the representable
    # range); the history's "cotangent" carries the roll-in update.
    return g, new_history


in_qdq.defvjp(_in_qdq_fwd, _in_qdq_bwd)


@jax.custom_vjp
def out_qdq(y, history):
    """Identity forward; the BACKWARD qdq-quantizes the cotangent to
    ``f8e5m2`` against the delayed scale from ``history`` and returns
    the updated history (amax of the cotangent) as its "gradient"."""
    del history
    return y


def _out_qdq_fwd(y, history):
    # The margin rides in the residuals: the forward traces INSIDE the
    # active fp8_scope, but the backward is traced by the surrounding
    # value_and_grad AFTER the scope's contextmanager has exited — a
    # global read there would see the restored (stale) margin.
    return y, (history, _MARGIN[0])


def _out_qdq_bwd(res, g):
    history, margin = res
    scale = compute_scale(history, E5M2_MAX, margin)
    gq = quantize_dequantize(g, scale, E5M2_MAX, jnp.float8_e5m2)
    return gq, update_history(history, g)


out_qdq.defvjp(_out_qdq_fwd, _out_qdq_bwd)


# ----------------------------------------------------------------------
# current scaling: stateless variants for the manual TP / pipeline path
# ----------------------------------------------------------------------

def _current_scale(x, qmax, margin):
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    amax = jnp.where(amax > 0.0, amax, 1.0)
    return amax / (qmax / (2.0 ** margin))


def in_qdq_current(x, margin=0):
    """Stateless forward qdq: scale from the CURRENT amax (one extra
    reduction per operand, no history to thread)."""
    scale = _current_scale(x, E4M3_MAX, margin)
    return quantize_dequantize(x, scale, E4M3_MAX, jnp.float8_e4m3fn)


@jax.custom_vjp
def out_qdq_current(y, margin):
    return y


def _oqc_fwd(y, margin):
    return y, margin


def _oqc_bwd(margin, g):
    scale = _current_scale(g, E5M2_MAX, margin)
    gq = quantize_dequantize(g, scale, E5M2_MAX, jnp.float8_e5m2)
    return gq, None


out_qdq_current.defvjp(_oqc_fwd, _oqc_bwd)


# ----------------------------------------------------------------------
# trace-time scope (mirrors overlap_scope) + the dot_general entry point
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Fp8Plan:
    """The resolved ``fp8`` config block: scaling margin, history length,
    and per-site overrides (``{site: {"enabled": bool}}``)."""
    margin: int = 0
    amax_history_len: int = 16
    sites: dict = dataclasses.field(default_factory=dict)

    def site_enabled(self, name):
        ov = (self.sites or {}).get(name) or {}
        return ov.get("enabled", True) is not False


_FP8_PLAN = None
_FP8_STATE = None        # {"<site>:<idx>": history} or None (current scaling)
_FP8_DISCOVER = None     # list collecting state keys in trace order
_FP8_COUNTS = None       # per-site call counter (reset at scope entry)
# margin travels through a one-slot list so the module-level custom_vjps
# above stay closure-free (their traces are cached on the fn objects;
# the margin is read at trace time, inside the active scope).
_MARGIN = [0]


@contextlib.contextmanager
def fp8_scope(plan, state=None, discover=None):
    """Declare an :class:`Fp8Plan` active for layers traced within this
    context (trace-time only, exactly like ``overlap_scope``).

    ``state`` maps ``"<site>:<idx>"`` keys — per-site trace-order call
    indices — to amax-history bundles ``{"in": [H], "kernel": [H],
    "out": [H]}`` for delayed scaling; ``state=None`` selects stateless
    current scaling. ``discover`` (a list) records the keys a trace
    touches instead of consuming state — the engine's state-discovery
    pass."""
    global _FP8_PLAN, _FP8_STATE, _FP8_DISCOVER, _FP8_COUNTS
    prev = (_FP8_PLAN, _FP8_STATE, _FP8_DISCOVER, _FP8_COUNTS, _MARGIN[0])
    _FP8_PLAN, _FP8_STATE, _FP8_DISCOVER = plan, state, discover
    _FP8_COUNTS = {}
    _MARGIN[0] = int(plan.margin) if plan is not None else 0
    try:
        yield
    finally:
        (_FP8_PLAN, _FP8_STATE, _FP8_DISCOVER, _FP8_COUNTS,
         _MARGIN[0]) = prev


def fp8_plan():
    """The active :class:`Fp8Plan`, or None outside any scope."""
    return _FP8_PLAN


def init_history(length):
    """A fresh all-zero amax history (bootstraps to scale 1)."""
    return jnp.zeros((int(length),), jnp.float32)


def init_state_bundle(length):
    """Zero state for one fp8 dot site: histories for the two forward
    operands and the backward cotangent."""
    return {"in": init_history(length), "kernel": init_history(length),
            "out": init_history(length)}


def fp8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                    preferred_element_type=None, site="dense"):
    """Drop-in ``lax.dot_general`` that routes through the fp8 qdq pair
    when an :func:`fp8_scope` is active (and the site enabled). Plug it
    into flax via ``nn.Dense(dot_general=fp8_dot_general)`` — with no
    scope it IS ``lax.dot_general``."""
    plan = _FP8_PLAN
    if plan is None or not plan.site_enabled(site):
        return lax.dot_general(
            lhs, rhs, dimension_numbers, precision=precision,
            preferred_element_type=preferred_element_type)
    if _FP8_STATE is None and _FP8_DISCOVER is None:
        # manual TP / pipeline path: stateless current scaling
        lhs_q = in_qdq_current(lhs, plan.margin)
        rhs_q = in_qdq_current(rhs, plan.margin)
        y = lax.dot_general(
            lhs_q, rhs_q, dimension_numbers, precision=precision,
            preferred_element_type=preferred_element_type)
        return out_qdq_current(y, plan.margin)
    idx = _FP8_COUNTS.get(site, 0)
    _FP8_COUNTS[site] = idx + 1
    key = f"{site}:{idx}"
    if _FP8_DISCOVER is not None:
        _FP8_DISCOVER.append(key)
        bundle = init_state_bundle(plan.amax_history_len)
    else:
        bundle = _FP8_STATE[key]
    lhs_q = in_qdq(lhs, bundle["in"])
    rhs_q = in_qdq(rhs, bundle["kernel"])
    y = lax.dot_general(
        lhs_q, rhs_q, dimension_numbers, precision=precision,
        preferred_element_type=preferred_element_type)
    return out_qdq(y, bundle["out"])
