"""Pallas fused Adam(W) update — the ``multi_tensor_adam.cu`` analog.

The XLA form (`ops/adam/fused_adam.py:adam_update`) leaves kernel
boundaries to the compiler; this kernel makes the one-pass structure
explicit: each tile streams (p, g, m, v) from HBM through VMEM once and
writes (p', m', v') back in the same pass, with the three outputs aliased
onto their inputs (true in-place update, zero extra HBM footprint —
`csrc/adam/multi_tensor_adam.cu:1-163`'s chunked multi-tensor walk,
re-designed as a Pallas grid over row-tiles of the flattened leaf).

``ANALYSIS_MFU.md`` attributes ~6% of the 350M step to Adam state
traffic; whether XLA was already emitting the minimal pass is exactly
what the on-chip A/B (BENCH_PALLAS_ADAM=1) measures.

Hyperparameters ride in SMEM as a single [8] fp32 vector so one compiled
kernel serves every step of an lr schedule.
"""

import functools

import jax
import jax.numpy as jnp

# [rows, LANES] tiles: LANES spans the 128-lane dim fully; 256 rows x 512
# lanes x 4 B = 512 KiB per operand tile -> 7 operands ~ 3.5 MiB of VMEM.
_LANES = 512
_ROWS = 256


def _adam_kernel(adam_w_mode, s_ref, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref):
    lr, b1, b2, eps, wd, bc1, bc2 = (s_ref[i] for i in range(7))
    p = p_ref[:]
    g = g_ref[:].astype(jnp.float32)
    if not adam_w_mode:
        g = g + wd * p                       # ADAM_MODE_1: L2 into grad
    m_new = b1 * m_ref[:] + (1.0 - b1) * g
    v_new = b2 * v_ref[:] + (1.0 - b2) * g * g
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w_mode:
        update = update + wd * p             # ADAM_MODE_0: decoupled decay
    po_ref[:] = p - lr * update
    mo_ref[:] = m_new
    vo_ref[:] = v_new


@functools.partial(jax.jit, static_argnames=("adam_w_mode", "interpret"))
def _leaf_update(p, g, m, v, scalars, adam_w_mode=True, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_shape, orig_dtype = p.shape, p.dtype
    n = p.size
    cols = _LANES
    rows_total = -(-n // cols)
    pad = rows_total * cols - n

    def to2d(x, dtype):
        x = x.reshape(-1).astype(dtype)
        if pad:
            x = jnp.pad(x, (0, pad))
        return x.reshape(rows_total, cols)

    p2, g2 = to2d(p, jnp.float32), to2d(g, jnp.float32)
    m2, v2 = to2d(m, jnp.float32), to2d(v, jnp.float32)

    block_rows = min(_ROWS, rows_total)
    n_blocks = -(-rows_total // block_rows)
    if rows_total % block_rows:
        extra = n_blocks * block_rows - rows_total
        p2, g2, m2, v2 = (jnp.pad(x, ((0, extra), (0, 0)))
                          for x in (p2, g2, m2, v2))

    tile = pl.BlockSpec((block_rows, cols), lambda i: (i, 0))
    out_shape = jax.ShapeDtypeStruct(p2.shape, jnp.float32)
    po, mo, vo = pl.pallas_call(
        functools.partial(_adam_kernel, adam_w_mode),
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  tile, tile, tile, tile],
        out_specs=[tile, tile, tile],
        out_shape=[out_shape, out_shape, out_shape],
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(scalars, p2, g2, m2, v2)

    def back(x):
        return x.reshape(-1)[:n].reshape(orig_shape)

    return back(po).astype(orig_dtype), back(mo), back(vo)


def pallas_adam_update(params, grads, state, lr, beta1=0.9, beta2=0.999,
                       eps=1e-8, weight_decay=0.0, adam_w_mode=True,
                       bias_correction=True, interpret=False):
    """Drop-in for :func:`deepspeed_tpu.ops.adam.fused_adam.adam_update`
    (same signature contract, same math) with the leaf update executed by
    the Pallas kernel. ``state`` is an ``AdamState``; returns
    (new_params, new_state)."""
    from deepspeed_tpu.ops.adam.fused_adam import AdamState

    step = state.step + 1
    sf = step.astype(jnp.float32)
    if bias_correction:
        bc1 = 1.0 - jnp.asarray(beta1, jnp.float32) ** sf
        bc2 = 1.0 - jnp.asarray(beta2, jnp.float32) ** sf
    else:
        bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
    scalars = jnp.stack([jnp.asarray(lr, jnp.float32),
                         jnp.asarray(beta1, jnp.float32),
                         jnp.asarray(beta2, jnp.float32),
                         jnp.asarray(eps, jnp.float32),
                         jnp.asarray(weight_decay, jnp.float32),
                         bc1, bc2, jnp.asarray(0.0, jnp.float32)])

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state.m)
    flat_v = treedef.flatten_up_to(state.v)
    new_p, new_m, new_v = [], [], []
    for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v):
        pn, mn, vn = _leaf_update(p, g, m, v, scalars,
                                  adam_w_mode=adam_w_mode,
                                  interpret=interpret)
        new_p.append(pn)
        new_m.append(mn)
        new_v.append(vn)

    unflat = jax.tree_util.tree_unflatten
    return (unflat(treedef, new_p),
            AdamState(m=unflat(treedef, new_m), v=unflat(treedef, new_v),
                      step=step))
