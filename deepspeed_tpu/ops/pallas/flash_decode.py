"""Flash-decode: split-K attention over the serving ring-buffer cache.

The decode step of the serving engine (`inference/engine.py`) attends
one query token per row over the full ``[max_batch, max_seq]`` KV
cache. The dense path dequantizes the whole cache to compute dtype and
runs a ``[1, max_seq]`` softmax per head — O(max_seq) HBM traffic per
token no matter how short the active requests are. This kernel is the
FlashDecoding-style fix, specialized for the ring buffer:

- **split-K online softmax**: the cache row streams through VMEM in
  ``block_k``-sized KV blocks; partial max/sum accumulators merge
  across blocks in scratch (the cross-block log-sum-exp merge), so the
  ``[1, max_seq]`` score row never materializes.
- **active-length block skipping**: each cache row's occupancy is its
  ``positions[b]`` scalar, prefetched into SMEM before the grid runs.
  Blocks entirely past a row's position are predicated off with
  ``pl.when`` AND their index map clamps to the last active block —
  Pallas skips the DMA when consecutive grid steps ask for the same
  block, so HBM traffic scales with the *occupied* cache, not
  ``max_seq``.
- **fused KV dequantization**: int8/f8e4m3fn/f8e5m2 cache blocks
  (`inference/cache.py` codec storage) enter the kernel in their
  storage dtype with the per-(row, position, head) scales streamed as
  a side input; scores and probs are rescaled in-register. The
  quantized cache never materializes an fp32 copy in HBM — the dense
  path's ``read_kv`` dequant is exactly what this deletes.
- **head folding**: heads fold into the grid's leading dim
  (``[B, S, H, D] → [B*H, S, D]``, the `flash_attention.py` layout),
  so a tensor-parallel head shard (`cache.kv_partition_specs`) runs
  the same kernel over its local heads under ``shard_map`` — the
  block-spec arithmetic never sees the global head count.

- **page-table gathers** (:func:`flash_decode_paged`): the paged pool
  layout (`inference/cache.py` ``page_size > 0``) feeds the kernel a
  second scalar-prefetch input — each row's ``[pages_per_row]`` page
  table — and the KV index map composes the clamp with a table lookup:
  logical block → clamp to the row's last active block → physical
  ``(page, intra-page block)``. The clamp runs BEFORE the lookup, so
  the map only ever dereferences table entries the row has actually
  filled — dead and unallocated pages never cost a DMA, the paged
  generalization of the ring kernel's block skipping. KV blocks are
  cut directly from the 4-D pool (``[1, block_k, 1, D]``), so no
  pool-sized transpose copy materializes either.

Off-TPU the kernel runs in Pallas interpret mode (CPU test meshes);
the dense cached-attention path stays available as the parity oracle
behind ``inference.attention.impl``.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.pallas.flash_attention import DEFAULT_MASK_VALUE

DEFAULT_BLOCK_K = 128

# TPU native sublane tile per element width (lane dim is always 128):
# a compiled block whose second-minor dim doesn't tile to this pads to
# full register tiles on every touch.
_SUBLANE_TILES = {4: 8, 2: 16, 1: 32}


class KernelGeometryError(ValueError):
    """Invalid flash-decode block geometry, raised at call time.

    Subclasses ``ValueError`` so existing call sites (and tests)
    catching the untyped validation keep working; the distinct type
    lets the static analyzer (`analysis/kernels.py`) and the serving
    engine report geometry problems as what they are instead of a
    silently mis-lowered kernel (or, for ``block_k <= 0``, an opaque
    ``ZeroDivisionError`` from the grid arithmetic).
    """


def _validate_block_k(block_k, extent, extent_name, kv_dtype, interpret):
    """Clamp and validate ``block_k`` against the KV extent it tiles.

    ``extent`` is ``max_seq`` for the ring layout and ``page_size``
    for the paged one (a KV block never straddles a page). The
    sublane-tile check only gates the COMPILED path (``interpret``
    False, i.e. a real TPU lowering where Mosaic's tiling constraints
    bite on sub-tile quantized blocks); interpret-mode CPU runs accept
    any divisor so CI toys stay small.
    """
    block_k = int(block_k)
    if block_k < 1:
        raise KernelGeometryError(
            f"attention block_k must be >= 1, got {block_k}")
    block_k = min(block_k, int(extent))
    if extent % block_k:
        raise KernelGeometryError(
            f"{extent_name} {extent} must be a multiple of attention "
            f"block_k {block_k}")
    tile = _SUBLANE_TILES.get(jnp.dtype(kv_dtype).itemsize, 8)
    if not interpret and block_k % tile and block_k != extent:
        raise KernelGeometryError(
            f"attention block_k {block_k} is not a multiple of the "
            f"{jnp.dtype(kv_dtype).name} sublane tile {tile} — the "
            f"compiled kernel would pad every KV block to full "
            f"register tiles; pick a multiple of {tile} (or cover the "
            f"whole {extent_name})")
    return block_k


def _fold_heads(x):
    """[B, S, H, D] → [B*H, S, D] (heads into the grid's leading dim)."""
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _flash_decode_kernel(H, D, block_k, n_kb, quant, paged=False):
    """Kernel factory: one (row*head, kv-block) grid step.

    Scalar-prefetch arg 0 is the ``[B]`` positions vector (SMEM);
    scratch carries the online-softmax state (acc [1, D], running max
    and sum [1, 1]) across the sequential kv-block dim. The paged
    variant carries the page tables as a second scalar-prefetch arg —
    consumed ONLY by the index maps (the body's math is identical; a
    KV block is a KV block wherever it was fetched from), except that
    paged blocks arrive in pool layout ``(1, bk, 1, D)`` instead of
    the folded ``(1, bk, D)``.
    """

    def kernel(pos_ref, *all_refs):
        refs = list(all_refs)
        if paged:
            refs.pop(0)                 # page tables: index-map food only
        q_ref, k_ref, v_ref = refs[:3]
        refs = refs[3:]
        ks_ref = refs.pop(0) if quant else None
        vs_ref = refs.pop(0) if quant else None
        o_ref, acc_ref, m_ref, l_ref = refs
        bh = pl.program_id(0)
        ki = pl.program_id(1)
        p = pos_ref[bh // H]

        @pl.when(ki == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[:] = jnp.zeros_like(l_ref)

        # Block-level active-length predicate: a block whose first
        # position is past the row's occupancy contributes nothing —
        # skip the whole grid step (its DMA was already elided by the
        # clamped index map).
        run = (ki * block_k) <= p

        @pl.when(run)
        def _compute():
            qb = q_ref[0].astype(jnp.float32)              # [1, D]
            kb = (k_ref[0, :, 0, :] if paged
                  else k_ref[0]).astype(jnp.float32)       # [bk, D]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [1, bk]
            if quant:
                # fused dequant: scale the SCORES by the key scales
                # (dot distributes over the per-position scalar) —
                # the kb block itself stays in storage dtype.
                ks = ks_ref[0, :, 0] if paged else ks_ref[0][:, 0]
                s = s * ks[None, :]
            s = s * (D ** -0.5)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(k_pos <= p, s, DEFAULT_MASK_VALUE)
            m_prev = m_ref[0, 0]
            m_new = jnp.maximum(m_prev, s.max())
            pr = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_ref[0, 0] = l_ref[0, 0] * corr + pr.sum()
            m_ref[0, 0] = m_new
            if quant:
                # value scales fold into the probs the same way
                vs = vs_ref[0, :, 0] if paged else vs_ref[0][:, 0]
                pr = pr * vs[None, :]
            vb = (v_ref[0, :, 0, :] if paged
                  else v_ref[0]).astype(jnp.float32)       # [bk, D]
            acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
                pr, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(ki == n_kb - 1)
        def _finish():
            o_ref[0] = (acc_ref[:] /
                        jnp.maximum(l_ref[0, 0], 1e-30)).astype(o_ref.dtype)

    return kernel


def flash_decode(q, k, v, positions, k_scale=None, v_scale=None,
                 block_k=DEFAULT_BLOCK_K, interpret=None):
    """Split-K flash decode over one layer's cache buffers.

    ``q``: ``[B, 1, H, D]`` compute-dtype query (the decode step's
    single token per row). ``k``/``v``: ``[B, S, H, D]`` cache buffers
    in STORAGE dtype — compute dtype, or a codec dtype
    (int8/f8e4m3fn/f8e5m2) with ``k_scale``/``v_scale`` ``[B, S, H]``
    f32 absmax scales (`inference/cache.py` layout). ``positions``:
    ``[B]`` int32, each row's current write position (the mask admits
    cache index ``s`` iff ``s <= positions[b]`` — identical to the
    dense oracle's). Returns ``[B, 1, H, D]`` in ``q.dtype``.

    ``interpret=None`` auto-selects: compiled kernel on TPU, Pallas
    interpret mode elsewhere. Under tensor parallelism call through
    ``shard_map`` with the head axis sharded (`cache.kv_partition_
    specs`); the kernel only ever sees local heads.
    """
    B, S, H, D = k.shape
    if q.shape != (B, 1, H, D):
        raise ValueError(
            f"flash_decode takes one query token per row: q shape "
            f"{q.shape} != {(B, 1, H, D)}")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    block_k = _validate_block_k(block_k, S, "max_seq", k.dtype, interpret)
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    quant = k_scale is not None
    n_kb = S // block_k

    qh = q.transpose(0, 2, 1, 3).reshape(B * H, 1, D)
    kh = _fold_heads(k)
    vh = _fold_heads(v)

    def q_map(bh, ki, pos_ref):
        return (bh, 0, 0)

    def kv_map(bh, ki, pos_ref):
        # Clamp past-occupancy block indices to the row's last active
        # block: consecutive grid steps then request the SAME block and
        # Pallas elides the DMA — the skipped blocks cost no HBM reads.
        return (bh, jnp.minimum(ki, pos_ref[bh // H] // block_k), 0)

    in_specs = [
        pl.BlockSpec((1, 1, D), q_map),
        pl.BlockSpec((1, block_k, D), kv_map),
        pl.BlockSpec((1, block_k, D), kv_map),
    ]
    args = [qh, kh, vh]
    if quant:
        in_specs += [pl.BlockSpec((1, block_k, 1), kv_map),
                     pl.BlockSpec((1, block_k, 1), kv_map)]
        args += [k_scale.transpose(0, 2, 1).reshape(B * H, S, 1),
                 v_scale.transpose(0, 2, 1).reshape(B * H, S, 1)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * H, n_kb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((1, D), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        _flash_decode_kernel(H, D, block_k, n_kb, quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, 1, D), q.dtype),
        interpret=interpret,
    )(jnp.asarray(positions, jnp.int32), *args)
    return out.reshape(B, H, 1, D).transpose(0, 2, 1, 3)


def flash_decode_paged(q, k, v, positions, page_tables, k_scale=None,
                       v_scale=None, block_k=DEFAULT_BLOCK_K,
                       interpret=None):
    """Split-K flash decode over a paged KV pool.

    ``q``: ``[B, 1, H, D]`` as in :func:`flash_decode`. ``k``/``v``:
    the POOL buffers ``[n_pages, page_size, H, D]`` in storage dtype
    (scales ``[n_pages, page_size, H]`` when quantized —
    `inference/cache.py` paged layout). ``page_tables``: ``[B,
    pages_per_row]`` int32 physical page ids per row (entry 0 = the
    trash page for unallocated slots). ``positions``: ``[B]`` int32
    write positions, same mask contract as the ring kernel.

    Both scalar-prefetch inputs live in SMEM before the grid runs; the
    KV index map clamps the logical block to the row's last active
    block FIRST and only then looks up the physical page, so blocks
    past a row's occupancy re-request the previous physical block
    (DMA elided) and unallocated table entries are never dereferenced.
    ``block_k`` clamps to ``page_size`` and must tile it — a KV block
    never straddles a page boundary, which is what keeps the gather a
    single block index per grid step.
    """
    n_pages, page_size, H, D = k.shape
    B = q.shape[0]
    if q.shape != (B, 1, H, D):
        raise ValueError(
            f"flash_decode_paged takes one query token per row: q "
            f"shape {q.shape} != {(B, 1, H, D)}")
    if page_tables.shape[0] != B:
        raise ValueError(
            f"page_tables rows {page_tables.shape[0]} != batch {B}")
    n_pt = page_tables.shape[1]
    S = n_pt * page_size
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    block_k = _validate_block_k(block_k, page_size, "page_size",
                                k.dtype, interpret)
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    quant = k_scale is not None
    n_kb = S // block_k
    bpp = page_size // block_k          # kv-blocks per page

    qh = q.transpose(0, 2, 1, 3).reshape(B * H, 1, D)

    def q_map(bh, ki, pos_ref, pt_ref):
        return (bh, 0, 0)

    def _physical(bh, ki, pos_ref, pt_ref):
        # clamp BEFORE the table lookup: the map only dereferences
        # entries covering positions the row has written.
        kc = jnp.minimum(ki, pos_ref[bh // H] // block_k)
        return pt_ref[bh // H, kc // bpp], kc % bpp

    def kv_map(bh, ki, pos_ref, pt_ref):
        page, intra = _physical(bh, ki, pos_ref, pt_ref)
        return (page, intra, bh % H, 0)

    def sc_map(bh, ki, pos_ref, pt_ref):
        page, intra = _physical(bh, ki, pos_ref, pt_ref)
        return (page, intra, bh % H)

    in_specs = [
        pl.BlockSpec((1, 1, D), q_map),
        pl.BlockSpec((1, block_k, 1, D), kv_map),
        pl.BlockSpec((1, block_k, 1, D), kv_map),
    ]
    args = [qh, k, v]
    if quant:
        in_specs += [pl.BlockSpec((1, block_k, 1), sc_map),
                     pl.BlockSpec((1, block_k, 1), sc_map)]
        args += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * H, n_kb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((1, D), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        _flash_decode_kernel(H, D, block_k, n_kb, quant, paged=True),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, 1, D), q.dtype),
        interpret=interpret,
    )(jnp.asarray(positions, jnp.int32),
      jnp.asarray(page_tables, jnp.int32), *args)
    return out.reshape(B, H, 1, D).transpose(0, 2, 1, 3)
