"""Flash attention for TPU.

The fused-attention capability of the reference's transformer kernel
(`csrc/transformer/softmax_kernels.cu` masked scaled softmax +
strided-batch attention GEMMs, `csrc/includes/strided_batch_gemm.h`),
re-designed as an online-softmax tiled kernel so the [T, T] score matrix
never materializes in HBM.

Implementations:
- ``pallas``: TPU Pallas forward kernel (online softmax over KV tiles,
  MXU-tiled, fp32 accumulators in VMEM scratch).
- ``xla``: blockwise lax.scan with the same online-softmax math — runs
  everywhere (CPU test meshes), differentiable, memory O(T·block).
- ``dense``: plain softmax attention (reference math for parity tests).

``flash_attention`` routes: TPU → pallas forward with a custom VJP whose
backward uses the blockwise XLA path; other platforms → xla path.
"""

import functools

import jax
import jax.numpy as jnp

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


# ---------------------------------------------------------------------------
# dense reference
# ---------------------------------------------------------------------------

def dense_attention(q, k, v, causal=True, sm_scale=None):
    """Plain attention; q,k,v: [B, T, H, D] → [B, T, H, D]."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * sm_scale
    if causal:
        T, S = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool))
        scores = jnp.where(mask[None, None], scores, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


# ---------------------------------------------------------------------------
# blockwise XLA (online softmax over KV blocks via lax.scan)
# ---------------------------------------------------------------------------

def _blockwise_attention(q, k, v, causal, sm_scale, block_k=256):
    """Online-softmax attention; memory O(T * block_k) per head."""
    B, T, H, D = q.shape
    S = k.shape[1]
    block_k = min(block_k, S)
    n_blocks = (S + block_k - 1) // block_k
    pad = n_blocks * block_k - S
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))

    qf = q.astype(jnp.float32) * sm_scale
    kb = k.reshape(B, n_blocks, block_k, H, D).astype(jnp.float32)
    vb = v.reshape(B, n_blocks, block_k, H, D).astype(jnp.float32)
    kb = jnp.moveaxis(kb, 1, 0)  # [n_blocks, B, block_k, H, D]
    vb = jnp.moveaxis(vb, 1, 0)

    q_pos = jnp.arange(T)

    def body(carry, inputs):
        acc, m, l = carry
        k_blk, v_blk, blk_idx = inputs
        s = jnp.einsum("bthd,bshd->bhts", qf, k_blk)  # [B,H,T,block_k]
        kv_pos = blk_idx * block_k + jnp.arange(block_k)
        valid = kv_pos < S
        if causal:
            valid = valid[None, :] & (kv_pos[None, :] <= q_pos[:, None])
            s = jnp.where(valid[None, None], s, DEFAULT_MASK_VALUE)
        else:
            s = jnp.where(valid[None, None, None], s, DEFAULT_MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1)
        acc = acc * correction[..., None] + \
            jnp.einsum("bhts,bshd->bhtd", p, v_blk)
        return (acc, m_new, l_new), None

    acc0 = jnp.zeros((B, H, T, D), jnp.float32)
    m0 = jnp.full((B, H, T), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc0, m0, l0), (kb, vb, jnp.arange(n_blocks)))
    out = acc / l[..., None]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)  # [B,T,H,D]


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _pallas_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    assert T % block_q == 0 and S % block_k == 0, (
        f"seq lens ({T},{S}) must divide blocks ({block_q},{block_k})")
    n_q = T // block_q
    n_k = S // block_k

    # [B, T, H, D] → [B*H, T, D]: heads fold into the grid's leading dim so
    # block shapes end in (seq_tile, D) — the TPU-tileable layout.
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    q, k, v = to_bh(q), to_bh(k), to_bh(v)

    def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[:] = jnp.zeros_like(l_ref)

        run = True
        if causal:
            # Skip fully-masked tiles above the diagonal.
            run = (ki * block_k) <= (qi * block_q + block_q - 1)

        @pl.when(run if causal else True)
        def _compute():
            qb = q_ref[0].astype(jnp.float32) * sm_scale   # [bq, D]
            kb = k_ref[0].astype(jnp.float32)              # [bk, D]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [bq, bk]
            if causal:
                q_pos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_pos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(k_pos <= q_pos, s, DEFAULT_MASK_VALUE)
            m_prev = m_ref[:, 0]
            m_new = jnp.maximum(m_prev, s.max(axis=-1))
            p = jnp.exp(s - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_ref[:, 0] = l_ref[:, 0] * corr + p.sum(axis=-1)
            m_ref[:, 0] = m_new
            vb = v_ref[0].astype(jnp.float32)              # [bk, D]
            acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(ki == n_k - 1)
        def _finish():
            o_ref[0] = (acc_ref[:] /
                        l_ref[:, 0][:, None]).astype(o_ref.dtype)

    grid = (B * H, n_q, n_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
    )(q, k, v)
    # [B*H, T, D] → [B, T, H, D]
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_pallas(q, k, v, causal, sm_scale, block_q, block_k):
    return _pallas_fwd(q, k, v, causal, sm_scale, block_q, block_k)


def _flash_pallas_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out = _pallas_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v)


def _flash_pallas_bwd(causal, sm_scale, block_q, block_k, res, g):
    # Backward via the blockwise XLA path (Pallas bwd kernel is a planned
    # upgrade); recomputes attention flash-style, so still O(T·block) memory.
    q, k, v = res
    def f(q, k, v):
        return _blockwise_attention(q, k, v, causal, sm_scale, block_k)
    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


_flash_pallas.defvjp(_flash_pallas_fwd, _flash_pallas_bwd)


def flash_attention(q, k, v, causal=True, sm_scale=None,
                    block_q=512, block_k=512, implementation="auto"):
    """Memory-efficient attention; q,k,v: [B, T, H, D] → [B, T, H, D].

    ``implementation``: "auto" (pallas on TPU, xla elsewhere), "pallas",
    "xla", or "dense".
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if implementation == "auto":
        platform = jax.devices()[0].platform
        implementation = "pallas" if platform == "tpu" else "xla"
    if implementation == "dense":
        return dense_attention(q, k, v, causal, sm_scale)
    if implementation == "xla":
        return _blockwise_attention(q, k, v, causal, sm_scale)
    if implementation == "pallas":
        T = q.shape[1]
        bq = min(block_q, T)
        bk = min(block_k, k.shape[1])
        # Fall back when shapes don't tile cleanly.
        if T % bq != 0 or k.shape[1] % bk != 0:
            return _blockwise_attention(q, k, v, causal, sm_scale)
        return _flash_pallas(q, k, v, causal, sm_scale, bq, bk)
    raise ValueError(f"unknown implementation {implementation!r}")
