"""Flash attention for TPU.

The fused-attention capability of the reference's transformer kernel
(`csrc/transformer/softmax_kernels.cu` masked scaled softmax +
strided-batch attention GEMMs, `csrc/includes/strided_batch_gemm.h`),
re-designed as an online-softmax tiled kernel so the [T, T] score matrix
never materializes in HBM.

Implementations:
- ``pallas``: TPU Pallas forward + backward kernels (online softmax over
  KV tiles, MXU-tiled, fp32 accumulators in VMEM scratch). The forward
  also emits the per-row logsumexp; the backward is the FlashAttention-2
  split — one kernel accumulating dQ over KV tiles, one accumulating
  dK/dV over Q tiles — so the [T, T] score matrix never materializes in
  either direction.
- ``xla``: blockwise lax.scan with the same online-softmax math — runs
  everywhere (CPU test meshes), differentiable, memory O(T·block).
- ``dense``: plain softmax attention (reference math for parity tests).

``flash_attention`` routes: TPU → pallas kernels; other platforms → xla
path (or pallas in interpreter mode when explicitly requested).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
# Additive form of a hard key mask (added to scores, so it must stay well
# inside fp32 range): exp(s - 1e9) == 0.0 exactly in fp32.
MASK_BIAS = -1e9

# Counter-based dropout: resolution of the keep threshold (top 24 bits of
# the hash compared against keep_prob * 2^24).
_DROPOUT_RESOLUTION = 1 << 24
# murmur3 fmix32 constants (as wrapping int32)
_FMIX_C1 = -2048144789      # 0x85EBCA6B
_FMIX_C2 = -1028477387      # 0xC2B2AE35
_GOLDEN = -1640531527       # 0x9E3779B9


def dropout_multiplier(seed, head, q_pos, k_pos, rate):
    """Counter-based attention-prob dropout multiplier: 0 or 1/keep_prob.

    The fused-dropout capability of the reference's transformer kernel
    (`csrc/transformer/dropout_kernels.cu`, cuRAND Philox seeded from
    `csrc/includes/context.h:177`), re-designed counter-based: the mask at
    global coordinates (head, q_pos, k_pos) is a pure integer-hash
    function (murmur3 fmix32 avalanche over a linear combination of the
    coordinates and the step seed). Because it is plain int32 arithmetic,
    it computes bitwise-identically inside the Pallas TPU kernels, the
    interpret-mode kernels, the blockwise-XLA path and the dense
    reference — which is what makes flash-with-dropout testable against
    dense-with-the-same-mask, keeps the backward's regenerated mask equal
    to the forward's without storing [T, S] bytes, and makes remat replay
    the identical mask. (``pltpu.prng_random_bits`` would be
    hardware-only: it is a zero-stub under interpret mode.)

    ``seed``/``head`` scalars (traced ok), ``q_pos``/``k_pos`` int32
    arrays that broadcast to the tile shape; ``rate`` static Python float
    in [0, 1). Returns fp32 of the broadcast shape.
    """
    keep_prob = 1.0 - rate
    h = (jnp.asarray(q_pos, jnp.int32) * jnp.int32(_GOLDEN)
         + jnp.asarray(k_pos, jnp.int32) * jnp.int32(_FMIX_C2)
         + jnp.asarray(head, jnp.int32) * jnp.int32(_FMIX_C1)
         + jnp.asarray(seed, jnp.int32))
    h = _fmix32(h)
    # Top 24 bits as a uniform value in [0, 2^24): unsigned comparison in
    # int32-safe range (both operands < 2^24).
    u24 = jax.lax.shift_right_logical(h, 8)
    thr = jnp.int32(int(round(keep_prob * _DROPOUT_RESOLUTION)))
    return (u24 < thr).astype(jnp.float32) * jnp.float32(1.0 / keep_prob)


# ---------------------------------------------------------------------------
# dense reference
# ---------------------------------------------------------------------------

def _to_key_bias(key_padding_mask, key_bias):
    """Resolve the public mask args to one additive [B, S] fp32 bias (or
    None): a bool ``key_padding_mask`` becomes 0 / MASK_BIAS; an explicit
    ``key_bias`` (soft additive penalties included) passes through."""
    assert key_padding_mask is None or key_bias is None, (
        "pass key_padding_mask OR key_bias, not both")
    if key_padding_mask is not None:
        return jnp.where(jnp.asarray(key_padding_mask, bool),
                         0.0, MASK_BIAS).astype(jnp.float32)
    if key_bias is not None:
        return key_bias.astype(jnp.float32)
    return None


def dropout_seed_from_rng(rng):
    """Derive the int32 per-step dropout seed from a JAX PRNG key — the
    one canonical way model code feeds :func:`dropout_multiplier` (every
    attention path must use this so a shared rng stream gives identical
    semantics everywhere)."""
    return jax.lax.bitcast_convert_type(
        jax.random.bits(rng, (), jnp.uint32), jnp.int32)


def _fmix32(h):
    """murmur3 finalizer: full avalanche over an int32."""
    h = h ^ jax.lax.shift_right_logical(h, 16)
    h = h * jnp.int32(_FMIX_C1)
    h = h ^ jax.lax.shift_right_logical(h, 13)
    h = h * jnp.int32(_FMIX_C2)
    h = h ^ jax.lax.shift_right_logical(h, 16)
    return h


def fold_in_seed(seed, data):
    """Mix ``data`` (a rank index, shard id, ...) into a dropout seed with
    full avalanche. A LINEAR stride (seed + data * C) is not enough: if C
    collides with one of :func:`dropout_multiplier`'s coordinate
    multipliers, the "new" seed reproduces the old mask at shifted
    coordinates (seed + r*GOLDEN ≡ the rank-0 mask at q_pos + r). The
    avalanche destroys any affine relationship to the coordinate terms."""
    h = jnp.asarray(seed, jnp.int32) ^ (
        jnp.asarray(data, jnp.int32) * jnp.int32(0x7F4A7C15))
    return _fmix32(h)


def _dropout_multiplier_full(B, H, T, S, rate, seed, head_offset=0,
                             num_heads=None):
    """The [B, H, T, S] dropout multiplier the kernels generate tile-wise,
    materialized whole (dense reference / tests). Head coordinate is the
    GLOBAL folded b*Hg + head_offset + h index — with the defaults
    (offset 0, Hg = H) that is the plain bh = b*H + h of the kernels'
    grid dim 0; under tensor parallelism the local heads are a slice and
    the globalized coordinate keeps the mask invariant to the sharding."""
    Hg = H if num_heads is None else num_heads
    bh = (jnp.arange(B)[:, None] * Hg + head_offset
          + jnp.arange(H)[None, :])                        # [B, H]
    return dropout_multiplier(
        seed, bh[:, :, None, None],
        jnp.arange(T)[None, None, :, None],
        jnp.arange(S)[None, None, None, :], rate)


def dense_attention(q, k, v, causal=True, sm_scale=None,
                    key_padding_mask=None, key_bias=None,
                    dropout_rate=0.0, dropout_seed=None,
                    dropout_head_offset=0, dropout_num_heads=None):
    """Plain attention; q,k,v: [B, T, H, D] → [B, T, H, D].
    ``key_padding_mask`` [B, S] bool (True = attend) or ``key_bias``
    [B, S] additive fp32. ``dropout_rate``/``dropout_seed``: attention-prob
    dropout with the shared counter-based mask (post-softmax, matching
    every other implementation bit-for-bit). ``dropout_head_offset`` /
    ``dropout_num_heads``: GLOBAL head coordinates when the local heads
    are a tensor-parallel shard (see :func:`flash_attention`)."""
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    bias = _to_key_bias(key_padding_mask, key_bias)
    scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * sm_scale
    if causal:
        T, S = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool))
        scores = jnp.where(mask[None, None], scores, DEFAULT_MASK_VALUE)
    if bias is not None:
        scores = scores + bias[:, None, None, :]
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0:
        B, T, H, _ = q.shape
        probs = probs * _dropout_multiplier_full(
            B, H, T, k.shape[1], dropout_rate, dropout_seed,
            head_offset=dropout_head_offset,
            num_heads=dropout_num_heads)
    return jnp.einsum("bhts,bshd->bthd", probs.astype(q.dtype), v)


# ---------------------------------------------------------------------------
# blockwise XLA (online softmax over KV blocks via lax.scan)
# ---------------------------------------------------------------------------

def _blockwise_attention(q, k, v, causal, sm_scale, block_k=256,
                         key_bias=None, dropout_rate=0.0, dropout_seed=None,
                         dropout_head_offset=0, dropout_num_heads=None):
    """Online-softmax attention; memory O(T * block_k) per head.
    ``key_bias`` [B, S] additive fp32 (resolved by the caller).
    Dropout uses the shared counter-based mask — bitwise-identical to the
    Pallas kernels' — applied to the normalized probs (the l normalizer
    sums the undropped probs, as softmax-then-dropout requires); head
    coordinates are globalized via ``dropout_head_offset`` /
    ``dropout_num_heads`` under tensor parallelism."""
    B, T, H, D = q.shape
    S = k.shape[1]
    if key_bias is None:
        key_bias = jnp.zeros((B, S), jnp.float32)
    kpm = key_bias
    block_k = min(block_k, S)
    n_blocks = (S + block_k - 1) // block_k
    pad = n_blocks * block_k - S
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        kpm = jnp.pad(kpm, ((0, 0), (0, pad)))

    qf = q.astype(jnp.float32) * sm_scale
    kb = k.reshape(B, n_blocks, block_k, H, D).astype(jnp.float32)
    vb = v.reshape(B, n_blocks, block_k, H, D).astype(jnp.float32)
    kb = jnp.moveaxis(kb, 1, 0)  # [n_blocks, B, block_k, H, D]
    vb = jnp.moveaxis(vb, 1, 0)
    mb = jnp.moveaxis(kpm.reshape(B, n_blocks, block_k), 1, 0)

    q_pos = jnp.arange(T)
    Hg = H if dropout_num_heads is None else dropout_num_heads
    bh_idx = (jnp.arange(B)[:, None] * Hg + dropout_head_offset
              + jnp.arange(H)[None, :])                           # [B, H]

    def body(carry, inputs):
        acc, m, l = carry
        k_blk, v_blk, m_blk, blk_idx = inputs
        s = jnp.einsum("bthd,bshd->bhts", qf, k_blk)  # [B,H,T,block_k]
        kv_pos = blk_idx * block_k + jnp.arange(block_k)
        valid = kv_pos < S
        if causal:
            valid = valid[None, :] & (kv_pos[None, :] <= q_pos[:, None])
            s = jnp.where(valid[None, None], s, DEFAULT_MASK_VALUE)
        else:
            s = jnp.where(valid[None, None, None], s, DEFAULT_MASK_VALUE)
        # additive key bias: [B, block_k] → [B, 1, 1, block_k]
        s = s + m_blk[:, None, None, :]
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1)
        p_acc = p
        if dropout_rate > 0.0:
            p_acc = p * dropout_multiplier(
                dropout_seed, bh_idx[:, :, None, None],
                q_pos[None, None, :, None],
                kv_pos[None, None, None, :], dropout_rate)
        acc = acc * correction[..., None] + \
            jnp.einsum("bhts,bshd->bhtd", p_acc, v_blk)
        return (acc, m_new, l_new), None

    acc0 = jnp.zeros((B, H, T, D), jnp.float32)
    m0 = jnp.full((B, H, T), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc0, m0, l0), (kb, vb, mb, jnp.arange(n_blocks)))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)  # [B,T,H,D]


# ---------------------------------------------------------------------------
# Pallas TPU kernels (forward + FlashAttention-2-style backward)
# ---------------------------------------------------------------------------

def _to_bh(x):
    """[B, T, H, D] → [B*H, T, D]: heads fold into the grid's leading dim so
    block shapes end in (seq_tile, D) — the TPU-tileable layout."""
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _from_bh(x, B, H):
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


# Per-row scalars (lse, delta) live in HBM as [B*H, T, 1] — compact, not
# lane-broadcast. A (1, block_q, 1) block DMAs block_q contiguous words and
# lands in VMEM as a [block_q, 1] sublane vector, which broadcasts over the
# [block_q, block_k] score tile for free (the same m[:, None] pattern the
# forward's scratch uses). The official jax flash kernel instead broadcasts
# these across all 128 lanes in HBM ([.., T, 128] fp32) — 128x the bytes,
# re-streamed on every q-step of the dK/dV grid; at long sequence lengths
# that stream dwarfs the q/k/v traffic itself.
def _pallas_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                interpret=False, key_bias=None,
                dropout_rate=0.0, dropout_seed=None,
                dropout_head_offset=None, dropout_num_heads=None):
    """Returns (out [B,T,H,D], lse [B*H,T,1]) — lse is the softmax row
    logsumexp residual consumed by the backward kernels.
    ``key_bias`` [B, S] additive fp32 rides as a [B, S, 1] array indexed
    per batch (bh // H). ``dropout_rate`` (static) / ``dropout_seed``
    (int32 scalar, SMEM): in-kernel attention-prob dropout — applied to
    the accumulated probs while ``l`` keeps summing the undropped probs
    (softmax normalizes before dropout zeroes).
    ``dropout_head_offset`` (traced int32, rides in SMEM beside the
    seed) / ``dropout_num_heads`` (static): mask coordinates use the
    GLOBAL head index off + bh%H (+ b*Hg) so a tensor-parallel head
    shard reproduces the replicated run's mask bitwise; the defaults
    reduce to the plain folded bh."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S = k.shape[1]
    Hg = H if dropout_num_heads is None else int(dropout_num_heads)
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    assert T % block_q == 0 and S % block_k == 0, (
        f"seq lens ({T},{S}) must divide blocks ({block_q},{block_k})")
    n_q = T // block_q
    n_k = S // block_k
    masked = key_bias is not None
    dropping = dropout_rate > 0.0

    q, k, v = _to_bh(q), _to_bh(k), _to_bh(v)
    kpm = None
    if masked:
        kpm = key_bias.astype(jnp.float32)[..., None]        # [B, S, 1]

    def kernel(q_ref, k_ref, v_ref, *refs):
        refs = list(refs)
        kpm_ref = refs.pop(0) if masked else None
        seed_ref = refs.pop(0) if dropping else None
        o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        bh = pl.program_id(0)
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[:] = jnp.zeros_like(l_ref)

        run = True
        if causal:
            # Skip fully-masked tiles above the diagonal.
            run = (ki * block_k) <= (qi * block_q + block_q - 1)

        @pl.when(run if causal else True)
        def _compute():
            qb = q_ref[0].astype(jnp.float32) * sm_scale   # [bq, D]
            kb = k_ref[0].astype(jnp.float32)              # [bk, D]
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [bq, bk]
            if causal or dropping:
                q_pos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_pos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
            if causal:
                s = jnp.where(k_pos <= q_pos, s, DEFAULT_MASK_VALUE)
            if masked:
                # [bk, 1] sublane vector → additive row bias over lanes
                s = s + kpm_ref[0][:, 0][None, :]
            m_prev = m_ref[:, 0]
            m_new = jnp.maximum(m_prev, s.max(axis=-1))
            p = jnp.exp(s - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_ref[:, 0] = l_ref[:, 0] * corr + p.sum(axis=-1)
            m_ref[:, 0] = m_new
            pd = p
            if dropping:
                # Global head coordinate: bh%H local head + SMEM offset
                # (+ batch stride Hg). Defaults make this exactly bh.
                g_head = bh + (bh // H) * (Hg - H) + seed_ref[1]
                pd = p * dropout_multiplier(
                    seed_ref[0], g_head, q_pos, k_pos, dropout_rate)
            vb = v_ref[0].astype(jnp.float32)              # [bk, D]
            acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
                pd, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(ki == n_k - 1)
        def _finish():
            # fully-masked rows: l == 0 → guard the divide (outputs for
            # padded q positions are meaningless and masked downstream)
            l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
            o_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
            lse_ref[0] = (m_ref[:, 0] + jnp.log(l_safe))[:, None]

    grid = (B * H, n_q, n_k)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
    ]
    args = [q, k, v]
    if masked:
        in_specs.append(pl.BlockSpec(
            (1, block_k, 1), lambda bh, qi, ki: (bh // H, ki, 0)))
        args.append(kpm)
    if dropping:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        off = 0 if dropout_head_offset is None else dropout_head_offset
        args.append(jnp.stack(
            [jnp.asarray(dropout_seed, jnp.int32).reshape(()),
             jnp.asarray(off, jnp.int32).reshape(())]))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return _from_bh(out, B, H), lse


def _pallas_bwd(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k,
                interpret=False, key_bias=None,
                dropout_rate=0.0, dropout_seed=None,
                dropout_head_offset=None, dropout_num_heads=None):
    """FlashAttention-2 backward. Two kernels:

    - dQ: grid (BH, n_q, n_k), accumulates dq over KV tiles in VMEM.
    - dK/dV: grid (BH, n_k, n_q), accumulates dk, dv over Q tiles in VMEM.
      When a key bias is present it also emits per-head dbias partials
      (column-sums of the pre-scale ds), reduced over heads in XLA — the
      true gradient of the additive bias.

    delta = rowsum(dO ⊙ O) is precomputed in XLA (it is a cheap fused
    elementwise+reduce); with dropout, rowsum(dP ⊙ P) still equals
    rowsum(dO ⊙ O) because the mask multiplier appears in both factors'
    chain. Dropout masks are regenerated in-kernel from the same
    counter-based hash as the forward — nothing [T, S]-shaped is stored.
    All matmuls run in fp32 on the MXU.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, H, D = q.shape
    S = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    n_q = T // block_q
    n_k = S // block_k

    in_dtype = q.dtype
    H = q.shape[2]
    Hg = H if dropout_num_heads is None else int(dropout_num_heads)
    masked = key_bias is not None
    dropping = dropout_rate > 0.0
    kpm = key_bias.astype(jnp.float32)[..., None] if masked else None
    seed_arr = None
    if dropping:
        off = 0 if dropout_head_offset is None else dropout_head_offset
        seed_arr = jnp.stack(
            [jnp.asarray(dropout_seed, jnp.int32).reshape(()),
             jnp.asarray(off, jnp.int32).reshape(())])
    qh, kh, vh = _to_bh(q), _to_bh(k), _to_bh(v)
    oh, gh = _to_bh(out), _to_bh(g)
    delta = jnp.sum(gh.astype(jnp.float32) * oh.astype(jnp.float32),
                    axis=-1, keepdims=True)                # [BH, T, 1]

    def positions(qi, ki):
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        return q_pos, k_pos

    def scores(q_ref, k_ref, qi, ki, kpm_ref=None):
        qb = q_ref[0].astype(jnp.float32)                  # [bq, D]
        kb = k_ref[0].astype(jnp.float32)                  # [bk, D]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            q_pos, k_pos = positions(qi, ki)
            s = jnp.where(k_pos <= q_pos, s, DEFAULT_MASK_VALUE)
        if kpm_ref is not None:
            s = s + kpm_ref[0][:, 0][None, :]              # additive bias
        return s

    def drop_tile(seed_ref, bh, qi, ki):
        # NB: bh is bound at kernel top — pl.program_id inside a pl.when
        # body breaks the interpret-mode lowering. Head coordinate is
        # globalized (TP head shard: off + bh%H, batch stride Hg) —
        # identical to the forward's, so the regenerated mask matches.
        q_pos, k_pos = positions(qi, ki)
        g_head = bh + (bh // H) * (Hg - H) + seed_ref[1]
        return dropout_multiplier(seed_ref[0], g_head, q_pos, k_pos,
                                  dropout_rate)

    def dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                  *refs):
        refs = list(refs)
        kpm_ref = refs.pop(0) if masked else None
        seed_ref = refs.pop(0) if dropping else None
        dq_ref, dq_acc = refs
        bh = pl.program_id(0)
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            dq_acc[:] = jnp.zeros_like(dq_acc)

        run = True
        if causal:
            run = (ki * block_k) <= (qi * block_q + block_q - 1)

        @pl.when(run if causal else True)
        def _compute():
            s = scores(q_ref, k_ref, qi, ki, kpm_ref)
            lse = lse_ref[0][:, :1]                        # [bq, 1]
            p = jnp.exp(s - lse)                           # [bq, bk]
            gb = g_ref[0].astype(jnp.float32)              # [bq, D]
            vb = v_ref[0].astype(jnp.float32)              # [bk, D]
            dp = jax.lax.dot_general(
                gb, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [bq, bk]
            if dropping:
                dp = dp * drop_tile(seed_ref, bh, qi, ki)
            ds = p * (dp - delta_ref[0][:, :1]) * sm_scale
            kb = k_ref[0].astype(jnp.float32)
            dq_acc[:] += jax.lax.dot_general(
                ds, kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [bq, D]

        @pl.when(ki == n_k - 1)
        def _finish():
            dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)

    dq_in_specs = [
        pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_q, D), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
    ]
    dq_args = [qh, kh, vh, gh, lse, delta]
    if masked:
        dq_in_specs.append(pl.BlockSpec(
            (1, block_k, 1), lambda bh, qi, ki: (bh // H, ki, 0)))
        dq_args.append(kpm)
    if dropping:
        dq_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dq_args.append(seed_arr)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, n_q, n_k),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qh.shape, in_dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    def dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                   *refs):
        refs = list(refs)
        kpm_ref = refs.pop(0) if masked else None
        seed_ref = refs.pop(0) if dropping else None
        if masked:
            dk_ref, dv_ref, dbias_ref, dk_acc, dv_acc, dbias_acc = refs
        else:
            dk_ref, dv_ref, dk_acc, dv_acc = refs
            dbias_ref = dbias_acc = None
        bh = pl.program_id(0)
        ki = pl.program_id(1)
        qi = pl.program_id(2)

        @pl.when(qi == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)
            if masked:
                dbias_acc[:] = jnp.zeros_like(dbias_acc)

        run = True
        if causal:
            # Q tiles strictly above the diagonal see nothing of this KV tile.
            run = (ki * block_k) <= (qi * block_q + block_q - 1)

        @pl.when(run if causal else True)
        def _compute():
            s = scores(q_ref, k_ref, qi, ki, kpm_ref)
            p = jnp.exp(s - lse_ref[0][:, :1])             # [bq, bk]
            gb = g_ref[0].astype(jnp.float32)              # [bq, D]
            if dropping:
                mult = drop_tile(seed_ref, bh, qi, ki)
                pd = p * mult
            else:
                pd = p
            dv_acc[:] += jax.lax.dot_general(
                pd, gb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [bk, D]
            vb = v_ref[0].astype(jnp.float32)
            dp = jax.lax.dot_general(
                gb, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [bq, bk]
            if dropping:
                dp = dp * mult
            ds0 = p * (dp - delta_ref[0][:, :1])           # pre-scale ds
            ds = ds0 * sm_scale
            qb = q_ref[0].astype(jnp.float32)
            dk_acc[:] += jax.lax.dot_general(
                ds, qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [bk, D]
            if masked:
                # d(bias_j) = Σ_t ds0[t, j] (bias is added after sm_scale)
                dbias_acc[:, 0] += ds0.sum(axis=0)

        @pl.when(qi == n_q - 1)
        def _finish():
            dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)
            if masked:
                dbias_ref[0] = dbias_acc[:]

    dkv_in_specs = [
        pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        pl.BlockSpec((1, block_q, D), lambda bh, ki, qi: (bh, qi, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bh, ki, qi: (bh, qi, 0)),
        pl.BlockSpec((1, block_q, 1), lambda bh, ki, qi: (bh, qi, 0)),
    ]
    dkv_args = [qh, kh, vh, gh, lse, delta]
    if masked:
        dkv_in_specs.append(pl.BlockSpec(
            (1, block_k, 1), lambda bh, ki, qi: (bh // H, ki, 0)))
        dkv_args.append(kpm)
    if dropping:
        dkv_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dkv_args.append(seed_arr)
    dkv_out_specs = [
        pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, D), lambda bh, ki, qi: (bh, ki, 0)),
    ]
    dkv_out_shapes = [
        jax.ShapeDtypeStruct(kh.shape, in_dtype),
        jax.ShapeDtypeStruct(vh.shape, in_dtype),
    ]
    dkv_scratch = [
        pltpu.VMEM((block_k, D), jnp.float32),
        pltpu.VMEM((block_k, D), jnp.float32),
    ]
    if masked:
        # Per-head dbias partials [BH, S, 1]: each (bh, ki) block is owned
        # by one contiguous qi sweep, so no cross-head accumulation races;
        # the cheap head reduction happens in XLA below.
        dkv_out_specs.append(pl.BlockSpec(
            (1, block_k, 1), lambda bh, ki, qi: (bh, ki, 0)))
        dkv_out_shapes.append(
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32))
        dkv_scratch.append(pltpu.VMEM((block_k, 1), jnp.float32))
    outs = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, n_k, n_q),
        in_specs=dkv_in_specs,
        out_specs=dkv_out_specs,
        out_shape=dkv_out_shapes,
        scratch_shapes=dkv_scratch,
        interpret=interpret,
    )(*dkv_args)
    if masked:
        dk, dv, dbias_part = outs
        dbias = dbias_part[:, :, 0].reshape(B, H, S).sum(axis=1)  # [B, S]
    else:
        dk, dv = outs
        dbias = None

    return (_from_bh(dq, B, H), _from_bh(dk, B, H), _from_bh(dv, B, H),
            dbias)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12))
def _flash_pallas(q, k, v, key_bias, dropout_seed, dropout_head_offset,
                  causal, sm_scale, block_q, block_k, dropout_rate,
                  dropout_num_heads, interpret=False):
    out, _ = _pallas_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                         interpret, key_bias=key_bias,
                         dropout_rate=dropout_rate,
                         dropout_seed=dropout_seed,
                         dropout_head_offset=dropout_head_offset,
                         dropout_num_heads=dropout_num_heads)
    return out


def _flash_pallas_fwd(q, k, v, key_bias, dropout_seed, dropout_head_offset,
                      causal, sm_scale, block_q, block_k, dropout_rate,
                      dropout_num_heads, interpret):
    out, lse = _pallas_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                           interpret, key_bias=key_bias,
                           dropout_rate=dropout_rate,
                           dropout_seed=dropout_seed,
                           dropout_head_offset=dropout_head_offset,
                           dropout_num_heads=dropout_num_heads)
    return out, (q, k, v, key_bias, dropout_seed, dropout_head_offset,
                 out, lse)


def _flash_pallas_bwd(causal, sm_scale, block_q, block_k, dropout_rate,
                      dropout_num_heads, interpret, res, g):
    (q, k, v, key_bias, dropout_seed, dropout_head_offset,
     out, lse) = res
    dq, dk, dv, dbias = _pallas_bwd(q, k, v, out, lse, g, causal, sm_scale,
                                    block_q, block_k, interpret,
                                    key_bias=key_bias,
                                    dropout_rate=dropout_rate,
                                    dropout_seed=dropout_seed,
                                    dropout_head_offset=dropout_head_offset,
                                    dropout_num_heads=dropout_num_heads)
    dkb = None if key_bias is None else dbias.astype(key_bias.dtype)
    # int32 seed/offset: cotangent type is float0
    f0 = lambda x: (None if x is None
                    else np.zeros(jnp.shape(x), jax.dtypes.float0))
    return dq, dk, dv, dkb, f0(dropout_seed), f0(dropout_head_offset)


_flash_pallas.defvjp(_flash_pallas_fwd, _flash_pallas_bwd)


def flash_attention(q, k, v, causal=True, sm_scale=None,
                    block_q=512, block_k=512, implementation="auto",
                    key_padding_mask=None, key_bias=None,
                    dropout_rate=0.0, dropout_seed=None,
                    dropout_head_offset=0, dropout_num_heads=None):
    """Memory-efficient attention; q,k,v: [B, T, H, D] → [B, T, H, D].

    ``implementation``: "auto" (pallas on TPU, xla elsewhere), "pallas"
    (interpreter mode off-TPU — slow, for parity tests), "xla", or "dense".
    ``key_padding_mask`` [B, S] bool (True = attend) or ``key_bias``
    [B, S] additive fp32 (soft penalties honored exactly, with true
    gradients on every implementation): applied to scores everywhere;
    outputs at fully-masked *query* positions are unspecified (mask them
    downstream, as the loss does).

    ``dropout_rate`` (static float) / ``dropout_seed`` (int32 scalar,
    traced ok — e.g. derived per step from a PRNG key): attention-prob
    dropout computed inside the kernels from a counter-based hash of the
    global (head, query, key) coordinates (see :func:`dropout_multiplier`)
    — the in-kernel-dropout capability of the reference's fused
    transformer (`csrc/transformer/dropout_kernels.cu`), with the same
    mask bits on every implementation.

    ``dropout_head_offset`` (traced int32 ok) / ``dropout_num_heads``
    (static int): when the local heads are a tensor-parallel SHARD of a
    larger attention (Megatron head partition), pass this rank's first
    global head and the global head count — the mask then hashes global
    coordinates, so the sharded run reproduces the replicated run's
    dropout bitwise (round 5; previously TP blocks had to fall back to
    dense attention under dropout).
    """
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    if dropout_rate:
        if not isinstance(dropout_rate, (int, float)):
            raise TypeError("dropout_rate must be a static Python float")
        if not 0.0 <= dropout_rate < 1.0:
            raise ValueError(f"dropout_rate {dropout_rate} not in [0, 1)")
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        if dropout_num_heads is not None:
            import numbers
            if not isinstance(dropout_num_heads, numbers.Integral):
                raise TypeError("dropout_num_heads must be a static int")
            dropout_num_heads = int(dropout_num_heads)
            if dropout_num_heads < q.shape[2]:
                raise ValueError(
                    f"dropout_num_heads {dropout_num_heads} < local heads "
                    f"{q.shape[2]}")
        dropout_seed = jnp.asarray(dropout_seed, jnp.int32)
    bias = _to_key_bias(key_padding_mask, key_bias)
    on_tpu = jax.devices()[0].platform == "tpu"
    if implementation == "auto":
        implementation = "pallas" if on_tpu else "xla"
    drop_kw = dict(dropout_rate=dropout_rate, dropout_seed=dropout_seed,
                   dropout_head_offset=dropout_head_offset,
                   dropout_num_heads=dropout_num_heads)
    if implementation == "dense":
        return dense_attention(q, k, v, causal, sm_scale, key_bias=bias,
                               **drop_kw)
    if implementation == "xla":
        return _blockwise_attention(q, k, v, causal, sm_scale,
                                    key_bias=bias, **drop_kw)
    if implementation == "pallas":
        T = q.shape[1]
        bq = min(block_q, T)
        bk = min(block_k, k.shape[1])
        # Fall back when shapes don't tile cleanly.
        if T % bq != 0 or k.shape[1] % bk != 0:
            return _blockwise_attention(q, k, v, causal, sm_scale,
                                        key_bias=bias, **drop_kw)
        return _flash_pallas(q, k, v, bias, dropout_seed,
                             dropout_head_offset, causal, sm_scale,
                             bq, bk, float(dropout_rate),
                             dropout_num_heads, not on_tpu)
    raise ValueError(f"unknown implementation {implementation!r}")
