"""Public surface of the Pallas TPU kernels.

Call sites import the kernel entry points from here
(``from deepspeed_tpu.ops.pallas import flash_decode``) instead of
deep-importing the defining modules — the module layout below this
package is an implementation detail (the flash-attention forward and
both backward kernels live in one file today; the static analyzer
`analysis/kernels.py` doesn't care either way, it finds every
``pallas_call`` in the traced program).

Every kernel auto-selects Pallas interpret mode off-TPU, so this
package imports (and the kernels run, slowly) on CPU test meshes.
"""

from deepspeed_tpu.ops.pallas.flash_attention import (
    DEFAULT_MASK_VALUE,
    dense_attention,
    flash_attention,
)
from deepspeed_tpu.ops.pallas.flash_decode import (
    DEFAULT_BLOCK_K,
    KernelGeometryError,
    flash_decode,
    flash_decode_paged,
)
from deepspeed_tpu.ops.pallas.fused_adam import pallas_adam_update

__all__ = [
    "DEFAULT_BLOCK_K",
    "DEFAULT_MASK_VALUE",
    "KernelGeometryError",
    "dense_attention",
    "flash_attention",
    "flash_decode",
    "flash_decode_paged",
    "pallas_adam_update",
]
