"""JAX version-compat surface for the handful of APIs that moved.

The repo targets the modern spellings (``jax.shard_map`` with
``check_vma``, ``jax.set_mesh``); older jaxlibs (<= 0.4.x) only ship
``jax.experimental.shard_map.shard_map`` with ``check_rep`` and the
``with mesh:`` resource-env context. Every call site imports from here
so the version split lives in exactly one file.
"""

import contextlib

import jax

__all__ = ["shard_map", "set_mesh", "axis_size", "get_abstract_mesh"]


if hasattr(jax.sharding, "get_abstract_mesh"):

    def get_abstract_mesh():
        mesh = jax.sharding.get_abstract_mesh()
        return None if mesh is None or mesh.empty else mesh

else:

    def get_abstract_mesh():
        # Legacy: the ``with mesh:`` resource env holds a physical mesh.
        from jax._src import mesh as mesh_lib
        mesh = mesh_lib.thread_resources.env.physical_mesh
        return None if mesh.empty else mesh


if hasattr(jax.lax, "axis_size"):

    def axis_size(axis_name):
        return jax.lax.axis_size(axis_name)

else:

    def axis_size(axis_name):
        # pre-0.5: the bound axis frame carries the static size (returns a
        # plain int under shard_map tracing, same as jax.lax.axis_size)
        frame = jax.core.axis_frame(axis_name)
        return frame if isinstance(frame, int) else frame.size


if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  axis_names=None):
        if axis_names is None:
            return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             axis_names=set(axis_names))

else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  axis_names=None):
        # pre-0.5 spellings: replication checking is ``check_rep``, and
        # the manual-axis subset is expressed inversely — ``auto`` is the
        # set of mesh axes left to GSPMD (modern ``axis_names`` lists the
        # manually-mapped ones).
        auto = frozenset() if axis_names is None else \
            frozenset(mesh.axis_names) - frozenset(axis_names)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma,
                          auto=auto)


if hasattr(jax, "set_mesh"):

    def set_mesh(mesh):
        return jax.set_mesh(mesh)

else:

    @contextlib.contextmanager
    def set_mesh(mesh):
        # Legacy resource-env context: ``with mesh:`` gives
        # with_sharding_constraint(PartitionSpec) the same axis names.
        with mesh:
            yield mesh
