"""Static HLO communication accounting.

The reference *claims* its 1-bit Adam moves ~5x less data
(`README.md:19,40`, `runtime/fp16/onebit_adam.py:104-228`) but never
measures it; NCCL traffic is invisible to the framework. Under XLA the
wire volume is a *compile-time* artifact: every collective is an HLO op
with a static shape, so the bytes a compiled step moves per device can be
read off the HLO text. ``collective_bytes`` does exactly that — the basis
of the pinned byte-ratio test in ``tests/unit/test_onebit_adam.py``.
"""

import re

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# e.g. "f32[8,128]{1,0}" or "u8[16]" or "f32[]"
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

# `%name = <shape-or-tuple> <op>(` — ops may be async "-start" forms;
# "-done" forms return the same buffer and are skipped to avoid double
# counting.
_COLLECTIVES = ("all-reduce", "all-gather", "all-to-all", "reduce-scatter",
                "collective-permute", "collective-broadcast")
_OP_RE = re.compile(
    r"=\s+(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<op>" + "|".join(_COLLECTIVES) + r")(?P<suffix>-start|-done)?\(")


def _shape_bytes(shape_text):
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue  # token/opaque types carry no payload
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_bytes(hlo_text):
    """Sum output bytes of every collective op in an HLO dump.

    Returns ``{op_name: bytes, ..., "total": bytes}``. Async pairs are
    counted once (the ``-start``); tuple outputs sum their array elements.
    For ``all-reduce``/``all-to-all`` the output size equals the input
    size, so "output bytes" is the per-device payload in both directions
    of a symmetric exchange — a consistent basis for *ratios* between two
    programs, which is what the tests pin.
    """
    counts = {}
    for m in _OP_RE.finditer(hlo_text):
        if m.group("suffix") == "-done":
            continue
        op = m.group("op")
        b = _shape_bytes(m.group("shape"))
        # async-start outputs are (operand_alias, result, scratch...);
        # halve to avoid counting the aliased input buffer.
        if m.group("suffix") == "-start" and m.group("shape").startswith("("):
            b //= 2
        counts[op] = counts.get(op, 0) + b
    counts["total"] = sum(counts.values())
    return counts
