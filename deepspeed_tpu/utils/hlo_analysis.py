"""Static HLO communication accounting (compatibility shim).

The reference *claims* its 1-bit Adam moves ~5x less data
(`README.md:19,40`, `runtime/fp16/onebit_adam.py:104-228`) but never
measures it; NCCL traffic is invisible to the framework. Under XLA the
wire volume is a *compile-time* artifact: every collective is an HLO op
with a static shape, so the bytes a compiled step moves per device can be
read off the HLO text. ``collective_bytes`` does exactly that — the basis
of the pinned byte-ratio test in ``tests/unit/test_onebit_adam.py``.

The implementation now lives in `deepspeed_tpu/analysis/hlo.py` as the
parser core of the compiled-program audit subsystem; this module
re-exports it for existing imports. The historical flat-program
LIMITATION (each op counted ONCE even inside a ``while``/``scan`` body)
is fixed there: accounting is trip-count-aware by default — ``while``
bodies are weighted by their static trip count, so the executed-1F1B
pipeline's per-tick ``collective-permute`` volume is finally
expressible. Pass ``trip_aware=False`` for the old flat behavior.
"""

import warnings

from deepspeed_tpu.analysis.hlo import (  # noqa: F401
    _COLLECTIVES,
    _DTYPE_BYTES,
    _OP_RE,
    _RING_SEND_FACTORS,
    _SHAPE_RE,
    _element_bytes,
    _shape_bytes,
    collective_bytes,
    ring_send_bytes,
)

warnings.warn(
    "deepspeed_tpu.utils.hlo_analysis is deprecated; import from "
    "deepspeed_tpu.analysis.hlo (or deepspeed_tpu.analysis) instead",
    DeprecationWarning, stacklevel=2)

__all__ = ["collective_bytes", "ring_send_bytes"]
