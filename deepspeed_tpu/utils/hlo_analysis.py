"""Static HLO communication accounting.

The reference *claims* its 1-bit Adam moves ~5x less data
(`README.md:19,40`, `runtime/fp16/onebit_adam.py:104-228`) but never
measures it; NCCL traffic is invisible to the framework. Under XLA the
wire volume is a *compile-time* artifact: every collective is an HLO op
with a static shape, so the bytes a compiled step moves per device can be
read off the HLO text. ``collective_bytes`` does exactly that — the basis
of the pinned byte-ratio test in ``tests/unit/test_onebit_adam.py``.

LIMITATION — flat programs only: each HLO op is counted ONCE, but an op
inside a ``while``/``scan`` body executes trip-count times. The pinned
proofs (1-bit collective, ZeRO stage volumes at accum=1) are flat in
their collectives — grad exchange and param refresh sit outside the
accumulation scan. The executed-1F1B pipeline is NOT: its per-tick
``ppermute`` lives inside the schedule scan, so this accounting cannot
express pipeline transfer volume (measured: the static number is one
tick's buffer regardless of micro-batch count). Pinning that would need
trip-count-aware parsing.
"""

import re

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# e.g. "f32[8,128]{1,0}" or "u8[16]" or "f32[]"
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

# `%name = <shape-or-tuple> <op>(` — ops may be async "-start" forms;
# "-done" forms return the same buffer and are skipped to avoid double
# counting.
_COLLECTIVES = ("all-reduce", "all-gather", "all-to-all", "reduce-scatter",
                "collective-permute", "collective-broadcast")
# The shape is everything between "=" and the op name — matched
# non-greedily so nested variadic tuples like ((f32[8], f32[4]),
# (f32[8], f32[4])) capture whole (a "[^)]*" shape class truncates them
# at the first close-paren and silently undercounts).
_OP_RE = re.compile(
    r"=\s+(?P<shape>.+?)\s+"
    r"(?P<op>" + "|".join(_COLLECTIVES) + r")(?P<suffix>-start|-done)?\(")


def _element_bytes(shape_text, skip_scalars=False):
    """(dtype, bytes) of each array element appearing in a (tuple) shape.
    ``skip_scalars`` drops zero-rank elements (async-start context/scratch
    scalars like ``u32[]``, which are bookkeeping, not payload)."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue  # token/opaque types carry no payload
        if skip_scalars and not dims:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        sizes.append((dtype, n * _DTYPE_BYTES[dtype]))
    return sizes


def _shape_bytes(shape_text):
    return sum(b for _, b in _element_bytes(shape_text))


def collective_bytes(hlo_text, by_dtype=False):
    """Sum output bytes of every collective op in an HLO dump.

    Returns ``{op_name: bytes, ..., "total": bytes}``. Async pairs are
    counted once (the ``-start``, result element only — its output tuple
    also aliases the operand); sync tuple outputs sum their array
    elements.
    For ``all-reduce``/``all-to-all`` the output size equals the input
    size, so "output bytes" is the per-device payload in both directions
    of a symmetric exchange — a consistent basis for *ratios* between two
    programs, which is what the tests pin.

    With ``by_dtype=True`` every per-op entry is a ``{dtype: bytes}``
    dict instead ("total" stays a plain sum) — how the quantized-allreduce
    proof separates the int8 gradient exchange from same-op fp32 traffic
    (scale vectors, the ZeRO-1 param-refresh gather) sharing the program.
    """
    counts = {}
    for m in _OP_RE.finditer(hlo_text):
        if m.group("suffix") == "-done":
            continue
        op = m.group("op")
        shape = m.group("shape")
        # async-start outputs are (operands..., results..., scratch...):
        # count only the result half. Halving the whole tuple's bytes is
        # exact only for symmetric collectives (all-reduce);
        # all-gather-start / reduce-scatter-start pair shard-sized
        # operands with differently-sized results. Scratch entries are
        # zero-rank scalars (collective-permute-start appends two u32[]
        # contexts) — drop them FIRST, then the remaining flattened list
        # is (operands..., results...) with matching counts, variadic
        # included, and the second half is the results.
        if m.group("suffix") == "-start" and shape.startswith("("):
            elems = _element_bytes(shape, skip_scalars=True)
            elems = elems[len(elems) // 2:]
        else:
            elems = _element_bytes(shape)
        per_op = counts.setdefault(op, {})
        for dtype, b in elems:
            per_op[dtype] = per_op.get(dtype, 0) + b
    if by_dtype:
        out = {op: dict(d) for op, d in counts.items()}
        out["total"] = sum(b for d in counts.values() for b in d.values())
        return out
    flat = {op: sum(d.values()) for op, d in counts.items()}
    flat["total"] = sum(flat.values())
    return flat


# Per-device ring-algorithm send bytes as a multiple of the op's OUTPUT
# bytes (N = ring size): all-reduce sends 2·(N-1)/N · M; all-gather sends
# (N-1)/N · M (output M, shard M/N moved N-1 times); reduce-scatter
# output is the M/N shard but each device sends M·(N-1)/N = (N-1)·out;
# all-to-all and collective-permute move (N-1)/N and 1× their payload.
_RING_SEND_FACTORS = {
    "all-reduce": lambda n: 2 * (n - 1) / n,
    "all-gather": lambda n: (n - 1) / n,
    "reduce-scatter": lambda n: float(n - 1),
    "all-to-all": lambda n: (n - 1) / n,
    "collective-permute": lambda n: 1.0,
    "collective-broadcast": lambda n: 1.0,
}
# Every parsed collective must have a send factor — fail at import, not
# at some caller's KeyError, when _COLLECTIVES grows.
assert set(_RING_SEND_FACTORS) == set(_COLLECTIVES)


def ring_send_bytes(hlo_text, n_devices, by_dtype=False):
    """Per-device bytes each device *sends* under ring algorithms.

    Converts ``collective_bytes``'s output-bytes basis into the send-volume
    basis the ZeRO paper's communication claims use (2M for an all-reduce
    of M bytes, M for all-gather / reduce-scatter) so ratios between
    compiled programs can be compared against published numbers directly.
    Approximation: every collective is assumed to span ``n_devices`` (true
    for the single-axis ZeRO tests this backs; subgroup collectives would
    need per-op replica-group parsing).

    ``by_dtype=True`` keys each op's sends by element dtype, mirroring
    ``collective_bytes(by_dtype=True)``.
    """
    out = collective_bytes(hlo_text, by_dtype=True)
    sends = {}
    for op, d in out.items():
        if op == "total":
            continue
        factor = _RING_SEND_FACTORS[op](n_devices)
        sends[op] = {dt: int(b * factor) for dt, b in d.items()}
    if by_dtype:
        sends["total"] = sum(b for d in sends.values() for b in d.values())
        return sends
    flat = {op: sum(d.values()) for op, d in sends.items()}
    flat["total"] = sum(flat.values())
    return flat
