"""Logging utilities.

TPU-native analog of the reference's ``deepspeed/utils/logging.py``
(`utils/logging.py:7,40` in the reference): a singleton package logger plus a
rank-filtered ``log_dist``. Rank is taken from ``jax.process_index()`` when JAX
is initialized (multi-host pods), falling back to 0.
"""

import logging
import sys
import functools

LOG_NAME = "deepspeed_tpu"


@functools.lru_cache(None)
def _create_logger(name=LOG_NAME, level=logging.INFO):
    logger_ = logging.getLogger(name)
    logger_.setLevel(level)
    logger_.propagate = False
    if not logger_.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setLevel(level)
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"))
        logger_.addHandler(handler)
    return logger_


logger = _create_logger()


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log ``message`` only on the given process ranks.

    ``ranks=None`` or ``ranks=[-1]`` logs on every process (mirrors the
    reference semantics of ``log_dist``).
    """
    should_log = ranks is None or (len(ranks) > 0 and ranks[0] == -1)
    if not should_log:
        should_log = _process_index() in set(ranks)
    if should_log:
        rank = _process_index()
        logger.log(level, f"[Rank {rank}] {message}")
