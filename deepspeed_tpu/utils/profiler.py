"""DEPRECATED shim — the profiler moved to
``deepspeed_tpu.telemetry.profiler``.

Kept (same pattern as the `utils/hlo_analysis.py` migration) so seed-era
imports keep working one release; new code should import from
`deepspeed_tpu.telemetry` (or `deepspeed_tpu.telemetry.profiler`).
"""

import warnings

from deepspeed_tpu.telemetry.profiler import (  # noqa: F401
    _KNOWN_KEYS,
    TraceProfiler,
    device_report,
)

warnings.warn(
    "deepspeed_tpu.utils.profiler is deprecated; import from "
    "deepspeed_tpu.telemetry.profiler (or deepspeed_tpu.telemetry) "
    "instead",
    DeprecationWarning, stacklevel=2)

__all__ = ["TraceProfiler", "device_report"]
