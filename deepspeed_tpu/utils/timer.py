"""DEPRECATED shim — the timers moved to ``deepspeed_tpu.telemetry.timers``.

Kept (same pattern as the `utils/hlo_analysis.py` migration) so seed-era
imports keep working one release; new code should import from
`deepspeed_tpu.telemetry` (or `deepspeed_tpu.telemetry.timers`).
"""

import warnings

from deepspeed_tpu.telemetry.timers import (  # noqa: F401
    SynchronizedWallClockTimer,
    ThroughputTimer,
    _synchronize,
)

warnings.warn(
    "deepspeed_tpu.utils.timer is deprecated; import from "
    "deepspeed_tpu.telemetry.timers (or deepspeed_tpu.telemetry) instead",
    DeprecationWarning, stacklevel=2)

__all__ = ["SynchronizedWallClockTimer", "ThroughputTimer"]
