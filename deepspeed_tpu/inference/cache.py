"""Bucketed ring-buffer KV cache, and its paged generalization.

One cache = one statically-shaped buffer per layer, ``[max_batch,
max_seq, n_head, head_dim]`` for keys and values (``scan_layers``
models stack a leading layer axis so the whole cache rides the same
``lax.scan`` as the params). Rows are the ring: a finished request's
row is handed to the next admitted request and simply overwritten —
admission/eviction never changes a compiled shape, which is what keeps
the decode loop at exactly one compile (`engine.compile_counts`).

The **paged** layout (``KVCacheSpec.page_size > 0``) replaces the
per-row ring with one pool of fixed-size pages per layer,
``[n_pages, page_size, n_head, head_dim]``, addressed through per-row
page tables (``[B, pages_per_row]`` int32) that enter the compiled
programs as plain data. The pool shape and the table shape are both
static, so page allocation, freeing, prefix sharing and host-tier
park/resume are pure host-side metadata churn — the same 2-compile
contract as the ring, with capacity decoupled from ``max_batch *
max_seq``. Physical page 0 is the TRASH page: the allocator never
hands it out, unallocated table entries point at it, and inactive
decode rows write their garbage token there, so every gather/scatter
stays in-bounds without per-row branches.

Causality comes from explicit positions, not shapes: every write lands
at the token's absolute position and every read masks cache index
``s`` unless ``s <= query position``. A slot past a row's live prefix
is either stale (from the row's previous tenant) or garbage from a
padded prefill chunk — both masked, and both overwritten before the
mask ever exposes them (the decode step writes position ``p`` before
attending to it).

Optional int8/fp8 storage reuses the wire-codec recipe from
``runtime/comm/codecs.py`` (absmax scale into the codec's ``qmax``,
zero guard, round+clip for int) at per-(row, position, head) scale
granularity — one f32 scale per head vector, the KV analog of the
per-chunk wire scales.
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.comm.codecs import CODECS, get_codec


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Static shape + storage format of one engine's KV cache."""
    n_layer: int
    max_batch: int
    max_seq: int
    n_head: int
    head_dim: int
    dtype: Any = jnp.bfloat16       # storage dtype (codec dtype when quantized)
    codec: Optional[str] = None     # None | "int8" | "f8e4m3fn" | "f8e5m2"
    stacked: bool = False           # scan_layers layout (leading layer axis)
    page_size: int = 0              # 0 = ring layout; >0 = paged pool
    n_pages: int = 0                # pool pages incl. the trash page

    @property
    def paged(self):
        return self.page_size > 0

    @property
    def pages_per_row(self):
        """Page-table width: pages covering one row's max_seq span."""
        return self.max_seq // self.page_size if self.paged else 0


def spec_for_model(cfg, max_batch, max_seq, kv_cache_dtype=None,
                   page_size=0, n_pages=0):
    """Resolve a :class:`KVCacheSpec` from a ``GPT2Config`` and the
    ``inference.kv_cache_dtype`` knob (None = model compute dtype,
    "bf16"/"f32" = plain storage, a codec name = quantized storage).
    ``page_size > 0`` selects the paged pool layout; ``n_pages=0`` then
    defaults to ring-capacity parity plus the trash page."""
    codec = None
    if kv_cache_dtype is None:
        dtype = cfg.dtype
    elif kv_cache_dtype == "bf16":
        dtype = jnp.bfloat16
    elif kv_cache_dtype in ("f32", "fp32"):
        dtype = jnp.float32
    elif kv_cache_dtype in CODECS:
        codec = kv_cache_dtype
        dtype = CODECS[kv_cache_dtype].dtype
    else:
        raise ValueError(
            f"kv_cache_dtype must be None, 'bf16', 'f32', or a codec "
            f"name from {sorted(CODECS)}; got {kv_cache_dtype!r}")
    if max_seq > cfg.n_positions:
        raise ValueError(
            f"max seq bucket {max_seq} exceeds the model's n_positions "
            f"{cfg.n_positions}")
    page_size, n_pages = int(page_size), int(n_pages)
    if page_size:
        if max_seq % page_size:
            raise ValueError(
                f"page_size {page_size} must divide max_seq {max_seq}")
        if not n_pages:
            # ring-capacity parity: every row can still fill its full
            # max_seq span concurrently, plus the reserved trash page.
            n_pages = int(max_batch) * (int(max_seq) // page_size) + 1
        if n_pages < 2:
            raise ValueError(
                f"n_pages must be >= 2 (page 0 is the trash page), "
                f"got {n_pages}")
    return KVCacheSpec(
        n_layer=cfg.n_layer, max_batch=int(max_batch),
        max_seq=int(max_seq), n_head=cfg.n_head,
        head_dim=cfg.n_embd // cfg.n_head, dtype=dtype, codec=codec,
        stacked=bool(cfg.scan_layers), page_size=page_size,
        n_pages=n_pages if page_size else 0)


def _layer_leaves(spec):
    if spec.paged:
        shape = (spec.n_pages, spec.page_size, spec.n_head,
                 spec.head_dim)
    else:
        shape = (spec.max_batch, spec.max_seq, spec.n_head,
                 spec.head_dim)
    leaves = {"k": jnp.zeros(shape, spec.dtype),
              "v": jnp.zeros(shape, spec.dtype)}
    if spec.codec is not None:
        sshape = shape[:-1]
        leaves["k_scale"] = jnp.zeros(sshape, jnp.float32)
        leaves["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return leaves


def init_kv_cache(spec):
    """Zero-filled cache pytree keyed like the model's params: per-layer
    ``h_<i>`` subtrees (unrolled) or one stacked ``h`` subtree
    (``scan_layers``)."""
    layer = _layer_leaves(spec)
    if spec.stacked:
        return {"h": jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (spec.n_layer,) + a.shape),
            layer)}
    return {f"h_{i}": jax.tree_util.tree_map(jnp.array, layer)
            for i in range(spec.n_layer)}


def kv_cache_nbytes(cache):
    return sum(int(leaf.nbytes)
               for leaf in jax.tree_util.tree_leaves(cache))


def cache_dtype_census(cache):
    """``{dtype_str: leaf count}`` over the cache's k/v payload leaves
    (scales excluded) — the decode audit's cache-dtype-hygiene fact."""
    census = {}
    flat = jax.tree_util.tree_flatten_with_path(cache)[0]
    for path, leaf in flat:
        key = str(getattr(path[-1], "key", path[-1]))
        if key.endswith("_scale"):
            continue
        dt = str(jnp.dtype(leaf.dtype))
        census[dt] = census.get(dt, 0) + 1
    return census


def kv_partition_specs(spec, model_axis="model"):
    """PartitionSpecs sharding the cache's head axis over the TP mesh
    axis — the cache analog of the model's Megatron column-parallel QKV
    (`models/gpt2.py:gpt2_partition_specs`): each TP shard holds the
    heads it computes, so decode attention runs collective-free and the
    row-parallel ``c_proj`` psum GSPMD inserts is the only combine.
    The ring row axis and the paged pool's page axis sit in the same
    slot (axis 0 / axis 1 stacked), so one spec covers both layouts."""
    from jax.sharding import PartitionSpec as P
    lead = (None,) if spec.stacked else ()
    # no trailing None after the sharded head axis: jit keys compiled
    # programs on the exact sharding object, and GSPMD canonicalizes
    # output specs without trailing Nones — a trailing-None input spec
    # would mismatch the pinned output and recompile on the 2nd call.
    payload = P(*lead, None, None, model_axis)
    scale = P(*lead, None, None, model_axis)

    def per_layer():
        leaves = {"k": payload, "v": payload}
        if spec.codec is not None:
            leaves["k_scale"] = scale
            leaves["v_scale"] = scale
        return leaves

    if spec.stacked:
        return {"h": per_layer()}
    return {f"h_{i}": per_layer() for i in range(spec.n_layer)}


# ---------------------------------------------------------------------------
# in-jit cache ops (used by models/gpt2.py's cached attention path)
# ---------------------------------------------------------------------------

def _codec_of(layer_cache):
    """Recover the storage codec from the cache leaves themselves (a
    traced pytree can't carry the name): quantized caches are the ones
    with scale leaves, and the payload dtype names the codec."""
    if "k_scale" not in layer_cache:
        return None
    dt = jnp.dtype(layer_cache["k"].dtype)
    for codec in CODECS.values():
        if jnp.dtype(codec.dtype) == dt:
            return codec
    raise ValueError(
        f"quantized KV cache stores dtype {dt} which matches no codec "
        f"in {sorted(CODECS)}")


def _quantize(x, codec):
    """Per-(row, position, head) absmax quantization — the
    ``encode_chunks`` recipe with the head vector as the chunk."""
    codec = get_codec(codec)
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = absmax / codec.qmax
    safe = jnp.where(scale > 0.0, scale, 1.0)
    scaled = xf / safe[..., None]
    if codec.integer:
        q = jnp.clip(jnp.round(scaled), -codec.qmax, codec.qmax)
    else:
        q = jnp.clip(scaled, -codec.qmax, codec.qmax)
    return q.astype(codec.dtype), scale


def _dequantize(q, scale, dtype):
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _row_write(buf, new, start):
    """Write ``new`` [B, T, ...] into ``buf`` [B, S, ...] at per-row
    offsets ``start`` [B] (positions are contiguous per row, so one
    dynamic_update_slice per row covers the whole chunk)."""
    def one(row_buf, row_new, p):
        idx = (p,) + (0,) * (row_buf.ndim - 1)
        return jax.lax.dynamic_update_slice(row_buf, row_new, idx)
    return jax.vmap(one)(buf, new, start)


def write_kv(layer_cache, k_new, v_new, positions):
    """Write one chunk's keys/values (``[B, T, H, D]``, compute dtype)
    into a layer's cache at ``positions`` [B, T]; quantizes on the way
    in when the cache stores a codec dtype."""
    codec = _codec_of(layer_cache)
    start = positions[:, 0]
    if codec is None:
        dt = layer_cache["k"].dtype
        return {"k": _row_write(layer_cache["k"], k_new.astype(dt), start),
                "v": _row_write(layer_cache["v"], v_new.astype(dt), start)}
    k_q, k_s = _quantize(k_new, codec)
    v_q, v_s = _quantize(v_new, codec)
    return {
        "k": _row_write(layer_cache["k"], k_q, start),
        "v": _row_write(layer_cache["v"], v_q, start),
        "k_scale": _row_write(layer_cache["k_scale"], k_s, start),
        "v_scale": _row_write(layer_cache["v_scale"], v_s, start),
    }


def read_kv(layer_cache, dtype):
    """The full ``[B, S, H, D]`` key/value buffers in compute ``dtype``
    (dequantized when stored quantized)."""
    codec = _codec_of(layer_cache)
    if codec is None:
        return (layer_cache["k"].astype(dtype),
                layer_cache["v"].astype(dtype))
    return (_dequantize(layer_cache["k"], layer_cache["k_scale"], dtype),
            _dequantize(layer_cache["v"], layer_cache["v_scale"], dtype))


def attention_mask(layer_cache, positions, page_table=None):
    """The dense path's ``[B, T, S]`` position mask (cache index ``s``
    visible to the query at position ``p`` iff ``s <= p``). Exposed so
    callers running several layers per step (`models/gpt2.py`) can
    compute it ONCE and pass it down — rebuilt per layer it is the
    compiled decode program's only per-layer iota. With a paged cache
    the buffer no longer carries the sequence length (``shape[-3]`` is
    ``page_size``); ``S`` is ``pages_per_row * page_size`` off the page
    table instead — the mask itself is layout-independent."""
    if page_table is not None:
        S = page_table.shape[-1] * layer_cache["k"].shape[-3]
    else:
        S = layer_cache["k"].shape[-3]
    return jnp.arange(S)[None, None, :] <= positions[:, :, None]


# ---------------------------------------------------------------------------
# paged pool ops
# ---------------------------------------------------------------------------

def paged_write_kv(layer_cache, k_new, v_new, positions, page_table):
    """Write one chunk's keys/values into the page pool through a
    page table. ``layer_cache`` holds ``[n_pages, page_size, H, D]``
    pool leaves; ``page_table`` is ``[B, pages_per_row]`` int32 of
    physical page ids (0 = trash for unallocated slots); positions are
    contiguous per row as in :func:`write_kv`. Two shapes exist:

    - decode (``T == 1``): a scatter of one ``[H, D]`` vector per row
      at ``(table[b, p // page_size], p % page_size)``. Inactive rows
      sit at position 0 with table entry 0 and collide harmlessly on
      the trash page.
    - prefill (``B == 1``): one ``dynamic_update_slice`` of the whole
      chunk into a single page — the engine pins ``page_size %
      prefill_chunk == 0`` so a chunk never straddles pages.
    - speculative verify (``B > 1, T > 1``): a general advanced-index
      scatter — each (row, step) token resolves its own (page, slot)
      through the table, so a chunk MAY straddle a page boundary.
      Positions past a row's allocated pages hit table entry 0 and
      land on the trash page (rejected-tail rollback: those writes are
      garbage by construction and never become visible).

    Quantization on the way in mirrors :func:`write_kv`: the pool's
    per-(page, slot, head) scales are exactly the ring's per-(row,
    position, head) scales under the page mapping, which is what lets
    the flash kernel's fused dequant carry over unchanged.
    """
    codec = _codec_of(layer_cache)
    page_size = layer_cache["k"].shape[-3]
    B, T = positions.shape
    start = positions[:, 0]

    if T == 1:
        pp = jnp.take_along_axis(
            page_table, (start // page_size)[:, None], axis=1)[:, 0]
        off = start % page_size

        def scatter(buf, vals):
            return buf.at[pp, off].set(vals[:, 0].astype(buf.dtype))
    elif B == 1:
        pp = page_table[0, start[0] // page_size]
        off = start[0] % page_size

        def scatter(buf, vals):
            idx = (pp, off) + (0,) * (buf.ndim - 2)
            return jax.lax.dynamic_update_slice(
                buf, vals.astype(buf.dtype), idx)
    else:
        pages = jnp.take_along_axis(
            page_table, positions // page_size, axis=1)     # [B, T]
        offs = positions % page_size

        def scatter(buf, vals):
            return buf.at[pages, offs].set(vals.astype(buf.dtype))

    if codec is None:
        return {"k": scatter(layer_cache["k"], k_new),
                "v": scatter(layer_cache["v"], v_new)}
    k_q, k_s = _quantize(k_new, codec)
    v_q, v_s = _quantize(v_new, codec)
    return {
        "k": scatter(layer_cache["k"], k_q),
        "v": scatter(layer_cache["v"], v_q),
        "k_scale": scatter(layer_cache["k_scale"], k_s),
        "v_scale": scatter(layer_cache["v_scale"], v_s),
    }


def paged_read_kv(layer_cache, page_table, dtype):
    """Gather each row's pages into contiguous ``[B, S, H, D]``
    key/value buffers in compute ``dtype`` (S = pages_per_row *
    page_size) — the dense oracle's view of the paged pool. Trash /
    unallocated entries gather page 0's garbage, which the position
    mask hides exactly like ring remnants."""
    codec = _codec_of(layer_cache)

    def gather(buf):
        g = jnp.take(buf, page_table, axis=0)   # [B, n_pt, ps, ...]
        B, n_pt, ps = g.shape[:3]
        return g.reshape((B, n_pt * ps) + g.shape[3:])

    if codec is None:
        return (gather(layer_cache["k"]).astype(dtype),
                gather(layer_cache["v"]).astype(dtype))
    return (_dequantize(gather(layer_cache["k"]),
                        gather(layer_cache["k_scale"]), dtype),
            _dequantize(gather(layer_cache["v"]),
                        gather(layer_cache["v_scale"]), dtype))


def _flash_attend(q, layer_cache, positions, block_k, mesh):
    """Flash split-K attention straight over the STORAGE buffers —
    quantized caches stream int8/f8 payloads + f32 scales into the
    kernel (`ops/pallas/flash_decode.py`) and never materialize a
    dequantized ``[B, S, H, D]`` copy. With a TP ``mesh`` the call runs
    under ``shard_map`` over the head axis, matching
    :func:`kv_partition_specs` — each shard's kernel sees only its
    local heads, collective-free."""
    from deepspeed_tpu.ops.pallas import flash_decode

    pos = positions[:, 0]
    scales = ()
    if "k_scale" in layer_cache:
        scales = (layer_cache["k_scale"], layer_cache["v_scale"])

    if mesh is None:
        return flash_decode(q, layer_cache["k"], layer_cache["v"], pos,
                            *scales, block_k=block_k)

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    head = P(None, None, "model", None)
    in_specs = (head, head, head, P(None)) + \
        ((P(None, None, "model"),) * 2 if scales else ())
    sharded = shard_map(
        lambda q_, k_, v_, p_, *s_: flash_decode(q_, k_, v_, p_, *s_,
                                                 block_k=block_k),
        mesh=mesh, in_specs=in_specs, out_specs=head, check_rep=False)
    return sharded(q, layer_cache["k"], layer_cache["v"], pos, *scales)


def _flash_attend_paged(q, layer_cache, positions, page_table, block_k,
                        mesh):
    """Paged twin of :func:`_flash_attend`: the kernel gathers KV
    blocks straight out of the pool through the scalar-prefetched page
    table (`ops/pallas/flash_decode.py:flash_decode_paged`) — no
    pool-sized gather/copy ever materializes. The pool's head axis
    shards exactly like the ring's, so the TP ``shard_map`` only swaps
    in the replicated page-table spec."""
    from deepspeed_tpu.ops.pallas import flash_decode_paged

    pos = positions[:, 0]
    scales = ()
    if "k_scale" in layer_cache:
        scales = (layer_cache["k_scale"], layer_cache["v_scale"])

    if mesh is None:
        return flash_decode_paged(q, layer_cache["k"], layer_cache["v"],
                                  pos, page_table, *scales,
                                  block_k=block_k)

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    head = P(None, None, "model", None)
    in_specs = (head, head, head, P(None), P(None, None)) + \
        ((P(None, None, "model"),) * 2 if scales else ())
    sharded = shard_map(
        lambda q_, k_, v_, p_, t_, *s_: flash_decode_paged(
            q_, k_, v_, p_, t_, *s_, block_k=block_k),
        mesh=mesh, in_specs=in_specs, out_specs=head, check_rep=False)
    return sharded(q, layer_cache["k"], layer_cache["v"], pos,
                   page_table, *scales)


def cached_attention(q, k_new, v_new, layer_cache, positions,
                     compute_dtype, impl="dense", block_k=128,
                     mesh=None, mask=None, page_table=None):
    """Write this chunk's k/v, then attend over the whole cache row.

    ``q``/``k_new``/``v_new``: ``[B, T, H, D]`` (T = 1 for a decode
    step, ``prefill_chunk`` for a prefill chunk); ``positions``:
    ``[B, T]`` absolute token positions, contiguous per row. Returns
    ``(y [B, T, H, D], updated layer_cache)``.

    ``impl="flash"`` routes decode steps (T == 1) through the Pallas
    split-K kernel (`ops/pallas/flash_decode.py`): online-softmax over
    ``block_k``-sized cache blocks with past-occupancy blocks skipped,
    and quantized storage dequantized IN-kernel (scales as a side
    input — no fp32 cache copy). Prefill chunks (T > 1) always use the
    dense path, which stays the parity oracle. ``mesh``: a TP mesh
    whose ``model`` axis shards the cache's head dim — the flash call
    then runs under ``shard_map`` per local head shard. ``mask``: a
    precomputed :func:`attention_mask` (dense path only) so multi-layer
    callers hoist it out of the per-layer body.

    The mask admits cache index ``s`` for the query at position ``p``
    iff ``s <= p`` — the cached generalization of the training path's
    ``tril(T, T)``: within a prefill chunk it reproduces the triangle,
    across chunks it exposes exactly the already-written prefix, and
    for padded chunk tails / recycled-row remnants it hides everything
    until a real token overwrites the slot.

    ``page_table`` (``[B, pages_per_row]`` int32) switches the layout:
    writes route through :func:`paged_write_kv`, the dense path attends
    over :func:`paged_read_kv`'s gathered view, and flash decode steps
    run the page-gather kernel. The attention math itself is layout-
    blind — pages only change where bytes live, never what the mask
    admits — which is what makes the ring the paged path's oracle.
    """
    if page_table is None:
        layer_cache = write_kv(layer_cache, k_new, v_new, positions)
        if impl == "flash" and q.shape[1] == 1:
            y = _flash_attend(q, layer_cache, positions, block_k, mesh)
            return y.astype(compute_dtype), layer_cache
        k_full, v_full = read_kv(layer_cache, compute_dtype)
    else:
        layer_cache = paged_write_kv(layer_cache, k_new, v_new,
                                     positions, page_table)
        if impl == "flash" and q.shape[1] == 1:
            y = _flash_attend_paged(q, layer_cache, positions,
                                    page_table, block_k, mesh)
            return y.astype(compute_dtype), layer_cache
        if mask is None:
            mask = attention_mask(layer_cache, positions, page_table)
        k_full, v_full = paged_read_kv(layer_cache, page_table,
                                       compute_dtype)
    D = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, compute_dtype))
    att = jnp.einsum("bthd,bshd->bhts", q, k_full) * scale
    if mask is None:
        mask = attention_mask(layer_cache, positions)
    att = jnp.where(mask[:, None], att, jnp.finfo(att.dtype).min)
    att = jax.nn.softmax(att.astype(jnp.float32),
                         axis=-1).astype(compute_dtype)
    y = jnp.einsum("bhts,bshd->bthd", att, v_full)
    return y, layer_cache


def slice_rows(cache, slot, stacked, rows=1):
    """The ``rows``-row sub-cache starting at row ``slot`` (a traced
    scalar is fine — this is how the prefill jit addresses its target
    row without baking the slot into the compiled program)."""
    axis = 1 if stacked else 0
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, slot, rows, axis=axis),
        cache)


def update_rows(cache, rows_tree, slot, stacked):
    """Inverse of :func:`slice_rows`: write an updated row block back."""
    axis = 1 if stacked else 0

    def upd(a, r):
        idx = [0] * a.ndim
        idx[axis] = slot
        return jax.lax.dynamic_update_slice(a, r, tuple(idx))

    return jax.tree_util.tree_map(upd, cache, rows_tree)
