"""Self-speculative decoding: truncated-depth draft + one-shot verify.

One decode step per generated token leaves the chip idle between
HBM-bound cache reads. This module spends those idle FLOPs on
speculation WITHOUT a second model: the draft is the SAME network cut
short — the first ``draft_layers`` blocks of the stack, then the usual
``ln_f`` + tied head (the ``scan_layers`` PLD machinery already made
depth a scan length, so truncation is a scan-length override plus a
leading-axis slice of the stacked params/cache — no second weight set,
and the draft shares the KV cache up to its own layers).

A speculative round at row position ``p`` with pending token ``t0``:

1. **draft** (``j`` calls of one compiled program): feed ``t0`` at
   ``p``, sample ``d1``; feed ``d1`` at ``p+1``, sample ``d2``; …
   Each call runs the truncated forward and writes the DRAFT layers'
   KV at its position.
2. **verify** (ONE compiled full-depth call): teacher-force
   ``[t0, d1..dj]`` (padded to the static width ``k+1``) at positions
   ``p..p+k``. Because the draft's layer-``i`` activations (``i <
   draft_layers``) are bit-identical to the full model's on the same
   inputs, verify's full-depth KV writes subsume the draft's — the
   shared cache stays consistent by construction.
3. **accept** (in-program, no host round trip): the longest draft
   prefix that matches. Greedy: exact argmax match. Sampled: the
   standard rejection-sampling rule — accept ``d_{i+1}`` when
   ``u_i * q_i(d_{i+1}) <= p_i(d_{i+1})`` under the SAME
   temperature/top-k/top-p filters (`sampling.filtered_logits`), with
   the correction token drawn from the normalized residual
   ``max(p - q, 0)`` so outputs remain distributionally correct.
   ``m`` accepted drafts emit ``m+1`` tokens (``d1..dm`` plus the
   correction/bonus) — every round makes progress.

**Rollback never reaches a jit boundary.** Rejected-tail KV (ring
slots / paged page slots past ``p+m``) is simply left stale: the next
round REWRITES every slot it will read before reading it (draft and
verify both write their chunk's KV ahead of attention, and the hoisted
position mask hides everything past the query position), and a paged
row's writes past its allocated pages land on the trash page (page 0).
Host-side rollback is a position-pointer decrement (ring) or an
occupancy decrement with pages left allocated (paged) — pure
bookkeeping.

The compile contract grows from a pinned 2 to a pinned **3** programs
— prefill, draft-step, verify-accept — held warmup-to-drain; the plain
decode program still exists but must show 0 jit-cache entries in a
speculative serve (the ``speculative`` audit rule pins exactly that).
Degenerate configs (``k == 0`` or ``draft_layers >= n_layer``) build
no decoder at all and fall back to the exact 2-program path.
"""

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.analysis.audit import donated_jit

DEFAULT_DRAFT_LAYERS = 0        # 0 = auto: n_layer // 2
DEFAULT_SPECULATIVE_K = 4


def _cfg_get(cfg, key, default):
    if cfg is None:
        return default
    if isinstance(cfg, dict):
        v = cfg.get(key, default)
    else:
        v = getattr(cfg, key, default)
    return default if v is None else v


def _slice_layers(tree, d):
    """Leading-axis prefix of every leaf of a stacked ``h`` subtree —
    the first ``d`` layers' params or cache slices."""
    return jax.tree_util.tree_map(lambda leaf: leaf[:d], tree)


def _writeback_layers(full, part):
    """Write a ``[d, ...]`` updated prefix back into the full
    ``[n_layer, ...]`` stacked tree (index-0 dynamic_update_slice —
    donation-aliasable, layers >= d flow through untouched)."""
    def upd(f, p):
        return jax.lax.dynamic_update_slice(
            f, p.astype(f.dtype), (0,) * f.ndim)
    return jax.tree_util.tree_map(upd, full, part)


def _emit_tokens(tokens, acc, corr):
    """Assemble the emitted-token block: slot ``t < acc`` carries the
    accepted draft ``d_{t+1}``, slot ``t == acc`` the correction/bonus,
    later slots are dead padding the host never reads."""
    B, k1 = tokens.shape
    pos = jnp.arange(k1)[None, :]
    shifted = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((B, 1), tokens.dtype)], axis=1)
    out = jnp.where(pos < acc[:, None], shifted,
                    jnp.where(pos == acc[:, None], corr[:, None], 0))
    return out.astype(jnp.int32)


def greedy_accept(pred, tokens, draft_len):
    """Greedy verify-accept. ``pred`` ``[B, k+1]`` argmax of the
    teacher-forced full-depth logits; ``tokens`` ``[B, k+1]`` =
    ``[pending, d1..dj, pad]``; ``draft_len`` ``[B]`` clamps how many
    drafts are real (padding can never be accepted). Returns
    ``(acc_len [B], out_tokens [B, k+1])`` — ``acc_len`` accepted
    drafts, so ``acc_len + 1`` tokens emit (the slot at ``acc_len`` is
    the correction, or the free bonus token when everything matched)."""
    k = tokens.shape[1] - 1
    i = jnp.arange(k)[None, :]
    ok = (i < draft_len[:, None]) & (pred[:, :-1] == tokens[:, 1:])
    acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
    corr = jnp.take_along_axis(pred, acc[:, None], axis=1)[:, 0]
    return acc, _emit_tokens(tokens, acc, corr)


def rejection_accept(probs, tokens, draft_len, q_dists, key):
    """Rejection-sampling verify-accept (Leviathan-style), vectorized —
    no scan, no host round trip.

    ``probs`` ``[B, k+1, V]``: verify (target) probabilities under the
    serving filters; ``q_dists`` ``[B, k, V]``: the draft distributions
    each ``d_{i+1}`` was actually sampled from (zeros past
    ``draft_len`` — a zero q can never win an accept test). Accept
    ``d_{i+1}`` iff ``u_i * q_i(d_{i+1}) <= p_i(d_{i+1})``; the
    correction at the first rejection samples the normalized residual
    ``max(p - q, 0)`` (falling back to ``p`` itself when the residual
    mass underflows — q == p on the whole support), and the
    all-accepted bonus slot sees q == 0, so its "residual" is exactly
    the full next-token distribution ``p_j``. Returns
    ``(acc_len, out_tokens, new_key)``."""
    B, k1, V = probs.shape
    k = k1 - 1
    key, ku, kc = jax.random.split(key, 3)
    u = jax.random.uniform(ku, (B, k), jnp.float32)
    drafts = tokens[:, 1:]
    p_d = jnp.take_along_axis(
        probs[:, :k], drafts[..., None], axis=2)[..., 0]
    q_d = jnp.take_along_axis(q_dists, drafts[..., None], axis=2)[..., 0]
    i = jnp.arange(k)[None, :]
    ok = (i < draft_len[:, None]) & (u * q_d <= p_d)
    acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
    p_m = jnp.take_along_axis(probs, acc[:, None, None], axis=1)[:, 0]
    q_pad = jnp.concatenate(
        [q_dists, jnp.zeros((B, 1, V), q_dists.dtype)], axis=1)
    q_m = jnp.take_along_axis(q_pad, acc[:, None, None], axis=1)[:, 0]
    residual = jnp.maximum(p_m - q_m, 0.0)
    mass = jnp.sum(residual, axis=-1, keepdims=True)
    corr_probs = jnp.where(mass > 1e-9, residual, p_m)
    corr = jax.random.categorical(
        kc, jnp.log(corr_probs + 1e-38), axis=-1).astype(jnp.int32)
    return acc, _emit_tokens(tokens, acc, corr), key


class SpeculativeDecoder:
    """The draft-step and verify-accept compiled programs plus their
    host bookkeeping, hung off an :class:`InferenceEngine` as
    ``engine.speculative``. Shares the engine's params, cache, PRNG
    key stream and sharding pins — it adds programs, not state."""

    def __init__(self, engine, k, draft_layers, min_accept_to_grow=0.0):
        n_layer = engine.model.config.n_layer
        if k < 1:
            raise ValueError(f"speculative k must be >= 1, got {k}")
        if not 0 < draft_layers < n_layer:
            raise ValueError(
                f"speculative draft_layers must be in 1..{n_layer - 1} "
                f"(0 < draft_layers < n_layer), got {draft_layers}")
        if k + 1 >= engine.max_seq:
            raise ValueError(
                f"speculative k={k} leaves no room in max_seq="
                f"{engine.max_seq} (need k + 1 < max_seq)")
        self.engine = engine
        self.k = int(k)
        self.draft_layers = int(draft_layers)
        self.min_accept_to_grow = float(min_accept_to_grow)
        if self.min_accept_to_grow < 0:
            raise ValueError(
                f"speculative min_accept_to_grow must be >= 0, got "
                f"{min_accept_to_grow}")
        # adaptive draft length: a host-side controller over the TRACED
        # [B] draft_len input — j is data, so varying it costs nothing.
        self._j = self.k
        self.rounds = 0
        self.row_rounds = 0             # sum of active rows over rounds
        self.accepted_total = 0         # accepted DRAFT tokens
        self.emitted_total = 0          # tokens emitted (drafts + corrections)
        self.drafted_total = 0          # draft tokens proposed
        if engine.kv_layout == "paged":
            self._draft = donated_jit(self._draft_fn_paged,
                                      donate_argnums=(1,))
            self._verify = donated_jit(self._verify_fn_paged,
                                       donate_argnums=(1,))
        else:
            self._draft = donated_jit(self._draft_fn,
                                      donate_argnums=(1,))
            self._verify = donated_jit(self._verify_fn,
                                       donate_argnums=(1,))

    # -- compiled programs --------------------------------------------------

    def _truncated_apply(self, params, cache, tokens, positions,
                         page_table=None):
        """The early-exit forward: first ``draft_layers`` blocks + ln_f
        + tied head. Under ``scan_layers`` the stacked params and cache
        leaves are sliced to ``[:d]`` (nn.scan splits params along axis
        0, so the leading axis must equal the scan length) and the
        updated cache prefix is written back in place; unrolled trees
        pass whole and merge the partial ``h_0..h_{d-1}`` updates."""
        eng = self.engine
        d = self.draft_layers
        stacked = eng.spec.stacked
        if stacked:
            params = {**params, "h": _slice_layers(params["h"], d)}
            sub = {"h": _slice_layers(cache["h"], d)}
        else:
            sub = cache
        mesh = eng.mesh if eng._cache_shardings is not None else None
        logits, new_kv = eng.model.apply(
            {"params": params}, tokens, deterministic=True,
            positions=positions, kv_cache=sub,
            attn_impl=eng.attention_impl,
            attn_block_k=eng.attention_block_k, attn_mesh=mesh,
            kv_page_table=page_table, truncate_layers=d)
        if stacked:
            cache = {**cache,
                     "h": _writeback_layers(cache["h"], new_kv["h"])}
        else:
            cache = {**cache, **new_kv}
        return logits, cache

    def _draft_step(self, params, cache, tokens, positions, key,
                    page_table=None):
        eng = self.engine
        logits, cache = self._truncated_apply(
            params, cache, tokens[:, None], positions[:, None],
            page_table=page_table)
        logits = logits[:, 0]
        from deepspeed_tpu.inference.sampling import (
            filtered_logits,
            sample_logits,
        )
        nxt, key = sample_logits(
            logits, key, temperature=eng.temperature,
            top_k=eng.top_k, top_p=eng.top_p)
        if eng.temperature == 0.0:
            # greedy: no draft distribution to carry (accept is exact
            # match), key passes through untouched
            return nxt, key, eng._pin_cache(cache)
        q = jax.nn.softmax(
            filtered_logits(logits, eng.temperature, eng.top_k,
                            eng.top_p), axis=-1)
        return nxt, q, key, eng._pin_cache(cache)

    def _draft_fn(self, params, cache, tokens, positions, key):
        return self._draft_step(params, cache, tokens, positions, key)

    def _draft_fn_paged(self, params, cache, tokens, positions,
                        page_tables, key):
        return self._draft_step(params, cache, tokens, positions, key,
                                page_table=page_tables)

    def _verify_step(self, params, cache, tokens, positions, draft_len,
                     q_dists, key, page_tables=None):
        eng = self.engine
        mesh = eng.mesh if eng._cache_shardings is not None else None
        # always dense: the flash-decode kernel is single-query; the
        # dense path's hoisted position mask already handles T = k+1.
        logits, cache = eng.model.apply(
            {"params": params}, tokens, deterministic=True,
            positions=positions, kv_cache=cache, attn_impl="dense",
            attn_block_k=eng.attention_block_k, attn_mesh=mesh,
            kv_page_table=page_tables)
        logits = logits.astype(jnp.float32)
        if eng.temperature == 0.0:
            pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            acc, out = greedy_accept(pred, tokens, draft_len)
        else:
            from deepspeed_tpu.inference.sampling import filtered_logits
            probs = jax.nn.softmax(
                filtered_logits(logits, eng.temperature, eng.top_k,
                                eng.top_p), axis=-1)
            acc, out, key = rejection_accept(probs, tokens, draft_len,
                                             q_dists, key)
        return acc, out, key, eng._pin_cache(cache)

    def _verify_fn(self, params, cache, tokens, positions, draft_len,
                   q_dists, key):
        return self._verify_step(params, cache, tokens, positions,
                                 draft_len, q_dists, key)

    def _verify_fn_paged(self, params, cache, tokens, positions,
                         page_tables, draft_len, q_dists, key):
        return self._verify_step(params, cache, tokens, positions,
                                 draft_len, q_dists, key,
                                 page_tables=page_tables)

    # -- host API -----------------------------------------------------------

    def draft_len(self):
        """Current global draft length j (1..k) for the next round."""
        return self._j

    def observe(self, active_rows, drafted, accepted_drafts, emitted):
        """Per-round controller + counters. ``drafted`` / ``accepted_
        drafts`` count DRAFT tokens proposed/accepted over the round's
        ``active_rows``; ``emitted`` counts every token the scheduler
        consumed (accepted drafts + one correction/bonus per row). With
        ``min_accept_to_grow > 0`` the draft length grows by one
        toward k while the round's mean acceptance clears the
        threshold and shrinks (floor 1) when it doesn't — draft_len is
        traced data, so adaptation costs zero recompiles. The default
        0.0 pins j = k."""
        self.rounds += 1
        self.row_rounds += int(active_rows)
        self.drafted_total += int(drafted)
        self.accepted_total += int(accepted_drafts)
        self.emitted_total += int(emitted)
        if self.min_accept_to_grow > 0 and active_rows:
            mean = accepted_drafts / float(active_rows)
            if mean >= self.min_accept_to_grow:
                self._j = min(self.k, self._j + 1)
            else:
                self._j = max(1, self._j - 1)

    def draft(self, tokens, positions, page_tables=None):
        """One compiled draft step: ``[max_batch]`` tokens/positions in,
        ``(next_tokens, q_dist_or_None)`` out (numpy). ``q`` is the
        filtered draft distribution each token was sampled from
        (None for greedy engines — exact match needs no q)."""
        eng = self.engine
        t = jnp.asarray(np.asarray(tokens, np.int32))
        p = jnp.asarray(np.asarray(positions, np.int32))
        args = [eng.params, eng.cache, t, p]
        if eng.kv_layout == "paged":
            args.append(jnp.asarray(np.asarray(page_tables, np.int32)))
        args.append(eng._sample_key)
        if eng.temperature == 0.0:
            nxt, eng._sample_key, eng.cache = self._draft(*args)
            return np.asarray(nxt), None
        nxt, q, eng._sample_key, eng.cache = self._draft(*args)
        return np.asarray(nxt), np.asarray(q)

    def _q_arg(self, q_dists):
        if self.engine.temperature == 0.0:
            # greedy verify never reads q; a fixed tiny dummy keeps the
            # traced signature shape-stable
            return jnp.zeros((1,), jnp.float32)
        return jnp.asarray(np.asarray(q_dists, np.float32))

    def verify(self, tokens, positions, draft_len, q_dists=None,
               page_tables=None):
        """The one full-depth verify-accept call. ``tokens`` ``[B,
        k+1]`` = ``[pending, d1..dj, pad]``; ``positions`` ``[B, k+1]``
        their absolute slots; ``draft_len`` ``[B]`` real drafts per row
        (0 for inactive rows); ``q_dists`` ``[B, k, V]`` for sampled
        engines. Returns ``(acc_len [B], out_tokens [B, k+1])`` numpy —
        row i emits ``out_tokens[i, :acc_len[i] + 1]``."""
        eng = self.engine
        t = jnp.asarray(np.asarray(tokens, np.int32))
        p = jnp.asarray(np.asarray(positions, np.int32))
        dl = jnp.asarray(np.asarray(draft_len, np.int32))
        args = [eng.params, eng.cache, t, p]
        if eng.kv_layout == "paged":
            args.append(jnp.asarray(np.asarray(page_tables, np.int32)))
        args += [dl, self._q_arg(q_dists), eng._sample_key]
        acc, out, eng._sample_key, eng.cache = self._verify(*args)
        return np.asarray(acc), np.asarray(out)

    # -- audit surface ------------------------------------------------------

    def draft_lowering_args(self):
        """The exact avals :meth:`draft` calls with — lowering through
        these is a jit-cache hit, never a fresh compile."""
        eng = self.engine
        args = [eng.params, eng.cache,
                jnp.zeros((eng.max_batch,), jnp.int32),
                jnp.zeros((eng.max_batch,), jnp.int32)]
        if eng.kv_layout == "paged":
            args.append(jnp.zeros((eng.max_batch, eng.pages_per_row),
                                  jnp.int32))
        args.append(eng._sample_key)
        return tuple(args)

    def verify_lowering_args(self):
        eng = self.engine
        args = [eng.params, eng.cache,
                jnp.zeros((eng.max_batch, self.k + 1), jnp.int32),
                jnp.zeros((eng.max_batch, self.k + 1), jnp.int32)]
        if eng.kv_layout == "paged":
            args.append(jnp.zeros((eng.max_batch, eng.pages_per_row),
                                  jnp.int32))
        q = jnp.zeros((1,), jnp.float32) if eng.temperature == 0.0 \
            else jnp.zeros((eng.max_batch, self.k,
                            eng.model.config.vocab_size), jnp.float32)
        args += [jnp.zeros((eng.max_batch,), jnp.int32), q,
                 eng._sample_key]
        return tuple(args)

    def draft_hlo(self):
        return self._draft.lower(
            *self.draft_lowering_args()).compile().as_text()

    def verify_hlo(self):
        return self._verify.lower(
            *self.verify_lowering_args()).compile().as_text()

    def facts(self):
        return {
            "k": self.k,
            "draft_layers": self.draft_layers,
            "n_layer": self.engine.model.config.n_layer,
            "min_accept_to_grow": self.min_accept_to_grow,
            "draft_len": self._j,
            "rounds": self.rounds,
            "row_rounds": self.row_rounds,
            "drafted_total": self.drafted_total,
            "accepted_total": self.accepted_total,
            "emitted_total": self.emitted_total,
            # tokens a row advances per compiled round (> 1.0 is the
            # whole point: the non-speculative loop is pinned at 1.0)
            "mean_accepted": (self.emitted_total
                              / float(max(self.row_rounds, 1))),
            # fraction of proposed draft tokens that survived verify
            "draft_efficiency": (self.accepted_total
                                 / float(max(self.drafted_total, 1))),
        }


def build_speculative(engine, config):
    """Parse the ``inference.speculative`` block and hang a
    :class:`SpeculativeDecoder` off the engine — or None when disabled
    OR degenerate (``k == 0`` / ``draft_layers >= n_layer``: a draft
    as deep as the model verifies nothing, so these configs fall back
    to the exact 2-program non-speculative path with no dead third
    compile)."""
    spec_cfg = _cfg_get(config, "speculative", None)
    if not spec_cfg:
        return None
    enabled = bool(_cfg_get(spec_cfg, "enabled", True))
    k = int(_cfg_get(spec_cfg, "k", DEFAULT_SPECULATIVE_K))
    draft_layers = int(_cfg_get(spec_cfg, "draft_layers",
                                DEFAULT_DRAFT_LAYERS))
    grow = float(_cfg_get(spec_cfg, "min_accept_to_grow", 0.0))
    if not enabled or k == 0:
        return None
    if k < 0:
        raise ValueError(f"speculative k must be >= 0, got {k}")
    n_layer = engine.model.config.n_layer
    if draft_layers == 0:
        draft_layers = n_layer // 2
    if draft_layers >= n_layer or draft_layers <= 0:
        # degenerate depth (including n_layer == 1, where no proper
        # truncation exists): plain decode
        return None
    return SpeculativeDecoder(engine, k, draft_layers,
                              min_accept_to_grow=grow)
