"""Jitted autoregressive serving engine.

The training side of this framework compiles ONE step and reuses it;
serving gets the same discipline: one chunked-prefill program, one
decode program, and a host-side continuous-batching scheduler that
admits/evicts requests between decode steps without ever changing a
compiled shape (the PR 4 recompile detector is the enforcement
mechanism — see :func:`engine.InferenceEngine.compile_counts`).

Pieces:

- :mod:`.cache` — bucketed ring-buffer KV cache (rows recycled across
  requests), optionally stored int8/fp8 through the shared codec
  registry (`runtime/comm/codecs.py`).
- :mod:`.engine` — the two compiled programs over the GPT-2 family
  (unrolled and ``scan_layers``), TP-shardable via the model's
  Megatron PartitionSpecs.
- :mod:`.scheduler` — continuous batching: admit/evict/pad loop over an
  open-loop request queue, emitting ``decode_step`` telemetry events.
- :mod:`.router` / :mod:`.fleet` — multi-replica serving: an admission
  router owning the global queue in front of N replicas (subprocess
  workers under the ``ds_tpu_run`` env contract, or in-process threads
  for tests), with heartbeat health checks, dead-replica drain and
  redispatch, deadlines, and backpressure (docs/inference.md).
- :mod:`.serve` — the ``ds_tpu_serve`` CLI (``--replicas N`` for fleet
  mode).
"""

from deepspeed_tpu.inference.cache import (
    KVCacheSpec,
    cache_dtype_census,
    init_kv_cache,
    kv_cache_nbytes,
    spec_for_model,
)
from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.inference.fleet import (
    ProcessReplica,
    ThreadReplica,
    build_process_fleet,
)
from deepspeed_tpu.inference.paging import HostPageCorruptError
from deepspeed_tpu.inference.router import (
    FleetResult,
    FleetRouter,
    RequestAbortedError,
)
from deepspeed_tpu.inference.scheduler import (
    Completion,
    ContinuousBatchingScheduler,
    Request,
)
