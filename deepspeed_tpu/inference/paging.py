"""Host-side paging for the paged KV cache: allocator, radix-tree
prefix cache, and the host-RAM tier for parked sessions.

Everything in this module is admission-time METADATA. The compiled
programs only ever see the statically-shaped page pool and fixed-width
int32 page tables (`inference/cache.py` paged layout); what this
module decides — which physical page backs which logical span, which
prompt pages are shared between requests, which parked session's pages
live in host RAM right now — changes the *contents* of those tables,
never a compiled shape. That is the whole design: allocator churn,
prefix hits and park/resume ride the serving loop without touching the
2-compile contract (`engine.compile_counts`).

Three cooperating pieces, driven by :class:`PagedCacheManager`:

- :class:`PageAllocator` — free list + per-page refcounts over the
  pool. Physical page 0 is reserved as the TRASH page (unallocated
  table entries and inactive decode rows point at it), so every
  device-side gather/scatter is in-bounds by construction.
- :class:`RadixPrefixCache` — a radix tree over prompt tokens with
  fixed ``page_size``-token edges: one node per interned page, children
  keyed by the next page's token tuple. A request whose prompt walks
  ``m`` nodes shares those ``m`` physical pages (refcounted — the
  sharing IS copy-on-write at page granularity: writes only ever land
  in pages past the shared span, so divergence allocates private pages
  instead of copying) and prefill resumes at token ``m * page_size``.
- :class:`HostPageStore` — parked sessions' pages evacuated to host
  RAM under allocator pressure, snapshot-isolated and CRC-stamped with
  the hot-checkpoint discipline (`runtime/resilience/hotckpt.py` /
  `checkpoint.py:_leaf_checksums`); resume pages them back in through
  freshly allocated device pages.

Whole-page sharing only: a prefix hit maps ``min(matched, floor((len
(prompt)-1)/page_size))`` pages, never a partial page — partial-page
sharing would need a device-side copy program (a third compile) for
the divergent tail, whereas whole pages make COW semantics emerge from
"writes never target shared pages".
"""

import dataclasses
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.resilience import fault_injection
from deepspeed_tpu.runtime.resilience.checkpoint import _leaf_checksums

TRASH_PAGE = 0


class OutOfPagesError(RuntimeError):
    """The pool has no free page and nothing left to evict."""


class HostPageCorruptError(RuntimeError):
    """A parked session's host-RAM snapshot failed CRC verification at
    page-in. Scattering rotted bytes into the pool would poison the
    session's whole continuation, so the snapshot is unusable — but the
    PROMPT still exists, so the scheduler recovers by dropping the
    parked pages and re-prefilling from scratch instead of crashing
    the engine (`PagedCacheManager.admit` catches this)."""

    def __init__(self, session_id, bad_leaves):
        self.session_id = session_id
        self.bad_leaves = list(bad_leaves)
        super().__init__(
            f"host page tier: CRC mismatch for session {session_id!r} "
            f"on {len(self.bad_leaves)} leaves "
            f"(first: {self.bad_leaves[:3]})")


class PageAllocator:
    """Free-list page allocator with refcounts over ``n_pages``
    physical pages; page 0 (the trash page) is never handed out."""

    def __init__(self, n_pages):
        self.n_pages = int(n_pages)
        if self.n_pages < 2:
            raise ValueError(
                f"n_pages must be >= 2 (page 0 is reserved), got "
                f"{self.n_pages}")
        # LIFO free list: recently freed pages are re-used first, which
        # keeps the working set of hot pages small.
        self._free = list(range(self.n_pages - 1, 0, -1))
        self._refs = np.zeros(self.n_pages, np.int32)

    @property
    def free_pages(self):
        return len(self._free)

    @property
    def resident_pages(self):
        """Allocated pages (excluding trash)."""
        return self.n_pages - 1 - len(self._free)

    def alloc(self):
        """One free physical page id (refcount 1), or None when the
        pool is exhausted — callers run their eviction ladder then."""
        if not self._free:
            return None
        page = self._free.pop()
        self._refs[page] = 1
        return page

    def incref(self, page):
        if page == TRASH_PAGE:
            raise ValueError("cannot take a reference on the trash page")
        if self._refs[page] < 1:
            raise ValueError(f"incref on free page {page}")
        self._refs[page] += 1

    def decref(self, page):
        if self._refs[page] < 1:
            raise ValueError(f"decref on free page {page}")
        self._refs[page] -= 1
        if self._refs[page] == 0:
            self._free.append(page)

    def refcount(self, page):
        return int(self._refs[page])


class _RadixNode:
    __slots__ = ("chunk", "page", "children", "parent", "last_use")

    def __init__(self, chunk, page, parent):
        self.chunk = chunk          # page_size-token tuple (edge label)
        self.page = page            # physical page holding this span's KV
        self.children = {}          # chunk tuple -> _RadixNode
        self.parent = parent
        self.last_use = 0


class RadixPrefixCache:
    """Radix tree over prompt tokens with fixed ``page_size``-token
    edges. Each node owns one allocator reference on its page; a
    matching request takes its OWN reference per shared page, so a node
    evicted mid-flight never frees a page a live row still maps."""

    def __init__(self, allocator, page_size):
        self.allocator = allocator
        self.page_size = int(page_size)
        self._root = _RadixNode(None, None, None)
        self._clock = 0
        self._nodes = 0
        self.hits = 0
        self.misses = 0

    def __len__(self):
        return self._nodes

    def _chunks(self, tokens):
        ps = self.page_size
        for i in range(0, (len(tokens) // ps) * ps, ps):
            yield tuple(tokens[i:i + ps])

    def match(self, tokens):
        """Longest interned prefix: a list of physical page ids, one
        per matched full page. Touches the walked nodes' LRU clocks and
        bumps the hit/miss counters."""
        self._clock += 1
        node, pages = self._root, []
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            child.last_use = self._clock
            pages.append(child.page)
            node = child
        if pages:
            self.hits += 1
        else:
            self.misses += 1
        return pages

    def insert(self, tokens, pages):
        """Intern a prompt's full pages (``pages[i]`` backs tokens
        ``[i*ps, (i+1)*ps)``). New nodes take one reference per page;
        already-interned spans are left as-is (same tokens ⟹ same KV
        bytes — prefill is deterministic)."""
        self._clock += 1
        node = self._root
        for i, chunk in enumerate(self._chunks(tokens)):
            if i >= len(pages):
                break
            child = node.children.get(chunk)
            if child is None:
                self.allocator.incref(pages[i])
                child = _RadixNode(chunk, pages[i], node)
                node.children[chunk] = child
                self._nodes += 1
            child.last_use = self._clock
            node = child

    def evict_one(self):
        """Drop the least-recently-used LEAF (interior nodes anchor
        their descendants' prefixes) and release its page reference.
        Returns True if something was evicted."""
        victim = None
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif victim is None or node.last_use < victim.last_use:
                victim = node
        if victim is None:
            return False
        del victim.parent.children[victim.chunk]
        self.allocator.decref(victim.page)
        self._nodes -= 1
        return True


class HostPageStore:
    """Parked sessions' page snapshots in host RAM, CRC-stamped on the
    way in and verified on the way out (the hot-checkpoint tier's
    corruption discipline — resuming a session from silently rotted
    host memory would poison its whole continuation)."""

    def __init__(self):
        self._parked = {}           # session_id -> (tree, checksums, nbytes)

    def __len__(self):
        return len(self._parked)

    def __contains__(self, session_id):
        return session_id in self._parked

    @property
    def nbytes(self):
        return sum(n for _, _, n in self._parked.values())

    def park(self, session_id, host_pages):
        import jax
        nbytes = sum(int(leaf.nbytes)
                     for leaf in jax.tree_util.tree_leaves(host_pages))
        checksums = _leaf_checksums(host_pages)
        if fault_injection.corrupt_host_pages(session_id):
            # Harness-injected rot: flip one byte in the first leaf
            # AFTER the CRCs were stamped, so take() must detect it.
            done = [False]

            def _flip(leaf):
                if done[0]:
                    return leaf
                done[0] = True
                buf = np.array(leaf)
                buf.reshape(-1).view(np.uint8)[0] ^= 0xFF
                return buf

            host_pages = jax.tree_util.tree_map(_flip, host_pages)
        self._parked[session_id] = (host_pages, checksums, nbytes)

    def take(self, session_id):
        """Remove and return a parked snapshot after CRC verification.

        A failed verification removes the snapshot anyway (rotted bytes
        are useless to every future caller) and raises
        :class:`HostPageCorruptError`."""
        tree, checksums, _ = self._parked.pop(session_id)
        actual = _leaf_checksums(tree)
        if actual != checksums:
            bad = sorted(k for k in checksums
                         if actual.get(k) != checksums[k])
            raise HostPageCorruptError(session_id, bad)
        return tree

    def drop(self, session_id):
        self._parked.pop(session_id, None)


@dataclasses.dataclass
class _ParkedSession:
    """A finished-but-retained session: its KV-covered token history
    and the pages that hold it — on device (``pages``) or evacuated to
    the host tier (``on_device=False``; the snapshot lives in the
    :class:`HostPageStore` under the session id)."""
    tokens: List[int]               # tokens whose KV the pages cover
    next_pos: int                   # KV frontier (== len(tokens))
    pages: List[int]                # physical ids (valid on device)
    on_device: bool
    last_use: int = 0


@dataclasses.dataclass
class RowPaging:
    """Per-slot paging state while a request is live."""
    pages: List[int]                # logical page idx -> physical id
    start: int                      # prefill resume point (chunk-aligned)
    prefix_hit: bool = False
    resumed: bool = False
    prefill_chunks: int = 0         # chunks actually run
    prefill_chunks_skipped: int = 0

    def table(self, pages_per_row):
        t = np.zeros(pages_per_row, np.int32)
        t[:len(self.pages)] = self.pages
        return t


class PagedCacheManager:
    """The scheduler's paging brain: admission (prefix match → page
    mapping → mid-prompt prefill plan), per-step page growth, and the
    park/evacuate/resume ladder. Owns the allocator, the radix tree and
    the host store; talks to the engine only through
    ``gather_pages``/``scatter_pages`` and static facts."""

    def __init__(self, engine, session=None):
        if engine.kv_layout != "paged":
            raise ValueError("PagedCacheManager requires a paged engine")
        self.engine = engine
        self.session = session
        self.page_size = engine.page_size
        self.pages_per_row = engine.pages_per_row
        self.allocator = PageAllocator(engine.n_pages)
        self.radix = RadixPrefixCache(self.allocator, self.page_size) \
            if engine.prefix_cache else None
        self.host_store = HostPageStore()
        self.sessions: Dict[str, _ParkedSession] = {}
        self._clock = 0
        self.sessions_admitted = 0
        self.sessions_parked = 0
        self.sessions_resumed = 0
        self.pages_evacuated = 0
        self.pages_paged_in = 0
        self.host_pages_corrupt = 0

    # -- bookkeeping ---------------------------------------------------------

    def _pages_for(self, n_tokens):
        return -(-int(n_tokens) // self.page_size)

    def page_bytes(self):
        """Device bytes of ONE physical page across all layers (pool
        bytes / n_pages) — the unit the bytes/session accounting and
        the bench A/B row count in."""
        from deepspeed_tpu.inference.cache import kv_cache_nbytes
        return kv_cache_nbytes(self.engine.cache) // self.engine.n_pages

    # NB: the radix tree defines __len__, so an EMPTY tree is falsy —
    # these guards must be identity checks or a cold cache would
    # report zero misses until its first insert.
    @property
    def prefix_hits(self):
        return self.radix.hits if self.radix is not None else 0

    @property
    def prefix_misses(self):
        return self.radix.misses if self.radix is not None else 0

    def facts(self):
        return {
            "page_size": self.page_size,
            "n_pages": self.engine.n_pages,
            "pages_free": self.allocator.free_pages,
            "pages_resident": self.allocator.resident_pages,
            "page_bytes": self.page_bytes(),
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "radix_nodes": len(self.radix) if self.radix is not None
                           else 0,
            "sessions_admitted": self.sessions_admitted,
            "sessions_parked_device": sum(
                1 for s in self.sessions.values() if s.on_device),
            "sessions_parked_host": len(self.host_store),
            "sessions_resumed": self.sessions_resumed,
            "pages_evacuated": self.pages_evacuated,
            "pages_paged_in": self.pages_paged_in,
            "host_pages_corrupt": self.host_pages_corrupt,
            "host_tier_bytes": self.host_store.nbytes,
        }

    # -- eviction ladder -----------------------------------------------------

    def _alloc_with_pressure(self):
        """One page, running the pressure ladder on exhaustion: radix
        LRU leaves first (pure cache — losing one only costs future
        prefill skips), then parked device sessions to the host tier.
        Returns None only when both ladders are dry."""
        page = self.allocator.alloc()
        while page is None:
            if self.radix is not None and self.radix.evict_one():
                page = self.allocator.alloc()
                continue
            if self._evacuate_lru_session():
                page = self.allocator.alloc()
                continue
            return None
        return page

    def _evacuate_lru_session(self):
        victims = [(s.last_use, sid) for sid, s in self.sessions.items()
                   if s.on_device]
        if not victims:
            return False
        _, sid = min(victims)
        self._evacuate(sid)
        return True

    def _evacuate(self, sid):
        """Move one device-parked session's pages to the host tier and
        free the device pages. The gather runs eagerly OUTSIDE the two
        compiled programs — parking cost is admission-path latency,
        never a decode-program host transfer."""
        sess = self.sessions[sid]
        self.host_store.park(sid, self.engine.gather_pages(sess.pages))
        for p in sess.pages:
            self.allocator.decref(p)
        self.pages_evacuated += len(sess.pages)
        sess.pages = []
        sess.on_device = False

    def maybe_evacuate(self):
        """Threshold-driven background parking: while the free-pool
        fraction sits below ``host_park_threshold``, push the LRU
        device-parked session out to host RAM."""
        thresh = self.engine.host_park_threshold
        if thresh <= 0.0:
            return
        while (self.allocator.free_pages / float(self.engine.n_pages)
               < thresh) and self._evacuate_lru_session():
            pass

    # -- admission -----------------------------------------------------------

    def admit(self, prompt, session_id=None):
        """Page plan for a new request: resume its parked session if
        the prompt extends one, else walk the radix tree for a shared
        prefix; allocate private pages for the rest of the prompt span.
        Returns a :class:`RowPaging` or None when the pool can't back
        the request right now (the scheduler leaves it queued)."""
        self._clock += 1
        n = len(prompt)
        chunk = self.engine.prefill_chunk
        pages: List[int] = []
        start = 0
        prefix_hit = resumed = False

        sess = self.sessions.get(session_id) if session_id else None
        if sess is not None and 0 < sess.next_pos <= n and \
                list(prompt[:sess.next_pos]) == sess.tokens:
            # session resume: the parked pages already hold KV for
            # prompt[:next_pos]; prefill restarts at the chunk floor of
            # the frontier (deterministically rewriting the partial
            # chunk — same tokens, same bytes).
            del self.sessions[session_id]
            if not sess.on_device:
                n_need = self._pages_for(sess.next_pos)
                fresh = []
                for _ in range(n_need):
                    p = self._alloc_with_pressure()
                    if p is None:
                        for q in fresh:
                            self.allocator.decref(q)
                        self.sessions[session_id] = sess
                        return None
                    fresh.append(p)
                try:
                    self.engine.scatter_pages(
                        fresh, self.host_store.take(session_id))
                except HostPageCorruptError:
                    # The rotted snapshot is gone (take() dropped it);
                    # free the landing pages and fall through to a cold
                    # admission — the session survives as a plain
                    # re-prefill from the prompt.
                    for q in fresh:
                        self.allocator.decref(q)
                    self.host_pages_corrupt += 1
                    sess = None
                else:
                    self.pages_paged_in += len(fresh)
                    sess.pages = fresh
                    sess.on_device = True
            if sess is not None:
                pages = list(sess.pages)  # row takes the session's refs
                start = (min(sess.next_pos, n - 1) // chunk) * chunk
                resumed = True
        if not resumed and self.radix is not None:
            # cap at floor((n-1)/ps): the LAST prompt token always
            # prefills (its logits seed sampling), so a prompt that is
            # entirely interned still runs its final page's chunks.
            matched = self.radix.match(prompt)
            m = min(len(matched), (n - 1) // self.page_size)
            if m:
                for p in matched[:m]:
                    self.allocator.incref(p)
                pages = list(matched[:m])
                start = m * self.page_size
                prefix_hit = True

        fresh = []
        for _ in range(len(pages), self._pages_for(n)):
            p = self._alloc_with_pressure()
            if p is None:
                for q in fresh:
                    self.allocator.decref(q)
                if resumed:
                    # roll the resume back: re-park on device
                    sess.pages = pages
                    self.sessions[session_id] = sess
                else:
                    for q in pages:
                        self.allocator.decref(q)
                return None
            fresh.append(p)
        pages.extend(fresh)

        self.sessions_admitted += 1
        if resumed:
            self.sessions_resumed += 1
        padded_chunks = -(-n // chunk)
        return RowPaging(
            pages=pages, start=start, prefix_hit=prefix_hit,
            resumed=resumed,
            prefill_chunks=padded_chunks - start // chunk,
            prefill_chunks_skipped=start // chunk)

    def after_prefill(self, row, prompt):
        """Intern the freshly prefilled prompt's full pages so later
        requests sharing the prefix hit them."""
        if self.radix is not None:
            self.radix.insert(prompt, row.pages)

    def ensure_position(self, row, pos):
        """Grow the row's mapping to cover a write at ``pos`` (the next
        decode step). False when the pool is dry even after the
        pressure ladder — the scheduler length-finishes the row."""
        li = pos // self.page_size
        if li < len(row.pages):
            return True
        if li >= self.pages_per_row:
            return False
        page = self._alloc_with_pressure()
        if page is None:
            return False
        row.pages.append(page)
        return True

    def ensure_span(self, row, start, end):
        """Grow the row's mapping to cover writes at every position in
        ``[start, end]`` — the speculative round's potentially-ACCEPTED
        frontier (``next_pos .. next_pos + draft_len``; an accepted
        draft's KV must land on a real page, while pad/rejected writes
        past the mapping harmlessly hit the trash page). Walks page by
        page so a multi-page draft window can't skip an allocation;
        False length-finishes the row exactly like
        :meth:`ensure_position`."""
        for pos in range(start, end + 1):
            if not self.ensure_position(row, pos):
                return False
        return True

    # -- release / park ------------------------------------------------------

    def release(self, row, kv_tokens=None, session_id=None):
        """Return a finished row's pages. With a ``session_id`` the
        pages PARK instead (retained on device, LRU-evacuated to host
        under pressure) keyed by the token history their KV covers, so
        a follow-up request on the session resumes without re-prefill;
        otherwise every reference drops back to the allocator."""
        self._clock += 1
        if session_id and kv_tokens:
            covered = min(len(kv_tokens),
                          len(row.pages) * self.page_size)
            old = self.sessions.pop(session_id, None)
            if old is not None and old.on_device:
                for p in old.pages:
                    self.allocator.decref(p)
            self.host_store.drop(session_id)
            self.sessions[session_id] = _ParkedSession(
                tokens=list(kv_tokens[:covered]), next_pos=covered,
                pages=list(row.pages), on_device=True,
                last_use=self._clock)
            self.sessions_parked += 1
            self.maybe_evacuate()
        else:
            for p in row.pages:
                self.allocator.decref(p)
        row.pages = []


def prompt_fingerprint(prompt):
    """Stable id for synthetic/serve bookkeeping (crc of the ids)."""
    return zlib.crc32(np.asarray(prompt, np.int64).tobytes()) & 0xFFFFFFFF
