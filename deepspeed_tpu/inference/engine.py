"""InferenceEngine: the two compiled programs of the serving path.

Exactly two jits, compiled once each, reused for the whole serve
(three with speculative decoding — see `inference/speculative.py`,
which swaps the decode program for a draft/verify pair under the same
never-recompile discipline):

- **prefill** — one chunk of one prompt: ``[1, prefill_chunk]`` tokens
  at explicit positions, written into cache row ``slot`` (a traced
  scalar, so any row reuses the same program). Long prompts are a host
  loop over same-shaped chunks — prompt length never reaches a jit
  boundary, so it can't recompile the loop and a long prompt never
  forces a fresh XLA program while decodes wait.
- **decode** — one token for every row at once: ``[max_batch]`` tokens
  at per-row positions over the full cache. Inactive rows compute
  garbage at position 0 and the scheduler ignores them; their writes
  land on free rows that prefill overwrites at admission.

Everything shape-varying (number of live requests, prompt lengths, per
-request sequence budgets a.k.a. ``seq_buckets``) is host-side
bookkeeping padded to these two static shapes, which is the whole
recompile contract: :meth:`compile_counts` must read ``{"prefill": 1,
"decode": 1}`` from warmup to drain, and :meth:`recompile_findings`
turns any growth into the PR 4 detector's error finding.

``kv_layout="paged"`` swaps the ring rows for a page pool
(`inference/cache.py` paged layout): both programs take fixed-shape
int32 page tables as plain data, so page allocation, prefix sharing
and host-tier park/resume (`inference/paging.py`) are admission-time
metadata under the SAME 2-compile contract — the pool shape and table
shape never change, only their contents.

With a mesh whose ``model`` axis is >1 the engine places params with
the model's Megatron PartitionSpecs (`models/gpt2.py:
gpt2_partition_specs` — the `parallel/tensor_parallel.py` layout) and
the cache with head-sharded specs (`cache.kv_partition_specs`), so
decode matmuls and attention run tensor-parallel with GSPMD inserting
the row-parallel psums.
"""

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.analysis.audit import donated_jit
from deepspeed_tpu.inference.cache import (
    cache_dtype_census,
    init_kv_cache,
    kv_cache_nbytes,
    kv_partition_specs,
    slice_rows,
    spec_for_model,
    update_rows,
)

DEFAULT_MAX_BATCH = 8
DEFAULT_SEQ_BUCKETS = (128, 512)
DEFAULT_PREFILL_CHUNK = 32
DEFAULT_MAX_NEW_TOKENS = 64
DEFAULT_ATTENTION_BLOCK_K = 128
DEFAULT_HOST_PARK_THRESHOLD = 0.25


def _cfg_get(config, key, default):
    if config is None:
        return default
    if isinstance(config, dict):
        v = config.get(key, default)
    else:
        v = getattr(config, key, default)
    return default if v is None else v


class InferenceEngine:
    """Jitted autoregressive decode over a GPT-2 family model.

    ``model`` is a :class:`~deepspeed_tpu.models.gpt2.GPT2LMHead`
    (unrolled or ``scan_layers``); ``params`` its param tree (matching
    layout). ``config`` is the validated ``inference`` block
    (`runtime/config.py:InferenceConfig`) or a plain dict with the same
    keys; ``session`` an optional
    :class:`~deepspeed_tpu.telemetry.session.TelemetrySession` the
    scheduler emits ``decode_step`` events through.
    """

    def __init__(self, model, params, config=None, mesh=None,
                 session=None):
        self.model = model
        cfg = model.config
        self.max_batch = int(_cfg_get(config, "max_batch",
                                      DEFAULT_MAX_BATCH))
        buckets = _cfg_get(config, "seq_buckets", DEFAULT_SEQ_BUCKETS)
        self.seq_buckets = tuple(sorted(int(b) for b in buckets))
        self.prefill_chunk = int(_cfg_get(config, "prefill_chunk",
                                          DEFAULT_PREFILL_CHUNK))
        self.kv_cache_dtype = _cfg_get(config, "kv_cache_dtype", None)
        self.max_new_tokens = int(_cfg_get(config, "max_new_tokens",
                                           DEFAULT_MAX_NEW_TOKENS))
        self.attention_impl = str(_cfg_get(config, "attention_impl",
                                           "dense"))
        self.attention_block_k = int(_cfg_get(config, "attention_block_k",
                                              DEFAULT_ATTENTION_BLOCK_K))
        self.temperature = float(_cfg_get(config, "temperature", 0.0))
        self.top_k = int(_cfg_get(config, "top_k", 0))
        self.top_p = float(_cfg_get(config, "top_p", 1.0))
        self.sampling_seed = int(_cfg_get(config, "sampling_seed", 0))
        self.kv_layout = str(_cfg_get(config, "kv_layout", "ring"))
        self.page_size = int(_cfg_get(config, "page_size", 0))
        self.n_pages = int(_cfg_get(config, "n_pages", 0))
        self.prefix_cache = bool(_cfg_get(config, "prefix_cache", True))
        self.host_park_threshold = float(_cfg_get(
            config, "host_park_threshold", DEFAULT_HOST_PARK_THRESHOLD))
        # disaggregated serving (ISSUE 20): a tiered engine runs ONE of
        # the two programs — "prefill" tier writes paged KV and never
        # decodes, "decode" tier resumes handed-off pages and never
        # prefills. The pin is host-side (calling the other program
        # raises), so each tier's compile_counts() holds exactly one
        # entry warmup-to-drain and the other stays at zero.
        tier = _cfg_get(config, "tier", None)
        self.tier = str(tier) if tier else None
        if self.tier not in (None, "prefill", "decode"):
            raise ValueError(
                f"inference tier must be 'prefill' or 'decode', got "
                f"{self.tier!r}")
        if self.tier is not None and \
                str(_cfg_get(config, "kv_layout", "ring")) != "paged":
            raise ValueError(
                "tiered (disaggregated) engines require kv_layout="
                "'paged' — the KV handoff is a page copy")
        if self.attention_impl not in ("dense", "flash"):
            raise ValueError(
                f"inference.attention.impl must be 'dense' or 'flash', "
                f"got {self.attention_impl!r}")
        if self.kv_layout not in ("ring", "paged"):
            raise ValueError(
                f"inference.kv_layout must be 'ring' or 'paged', got "
                f"{self.kv_layout!r}")
        if not 0.0 <= self.host_park_threshold < 1.0:
            raise ValueError(
                f"host_park_threshold must be in [0, 1), got "
                f"{self.host_park_threshold}")
        if self.temperature < 0.0:
            raise ValueError(f"sampling temperature must be >= 0, got "
                             f"{self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got "
                             f"{self.top_p}")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got "
                             f"{self.max_batch}")
        if not self.seq_buckets or min(self.seq_buckets) < 1:
            raise ValueError(f"seq_buckets must be non-empty positive "
                             f"ints, got {self.seq_buckets}")
        if self.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{self.prefill_chunk}")
        for b in self.seq_buckets:
            if b % self.prefill_chunk:
                # buckets gate how far a row may fill; chunk-aligned
                # buckets keep padded prefill writes inside the buffer.
                raise ValueError(
                    f"every seq bucket must be a multiple of "
                    f"prefill_chunk={self.prefill_chunk}; got bucket {b}")
        self.max_seq = max(self.seq_buckets)
        # flash block size clamps to the cache length and must tile it
        # (the kernel's grid is max_seq / block_k blocks per row).
        self.attention_block_k = min(self.attention_block_k, self.max_seq)
        if self.attention_block_k < 1 or \
                self.max_seq % self.attention_block_k:
            raise ValueError(
                f"attention block_k {self.attention_block_k} must be a "
                f"positive divisor of max_seq {self.max_seq}")
        if self.kv_layout == "paged":
            if not self.page_size:
                # auto: two prefill chunks per page — fine-grained
                # enough for the bytes/session win, coarse enough that
                # page tables stay short.
                self.page_size = min(2 * self.prefill_chunk,
                                     self.max_seq)
            if self.page_size % self.prefill_chunk:
                # a prefill chunk must land inside ONE page (the paged
                # prefill write is a single dynamic_update_slice).
                raise ValueError(
                    f"page_size {self.page_size} must be a multiple of "
                    f"prefill_chunk {self.prefill_chunk}")
            if self.max_seq % self.page_size:
                raise ValueError(
                    f"page_size {self.page_size} must divide max_seq "
                    f"{self.max_seq}")
            # a flash KV block must not straddle a page boundary
            self.attention_block_k = min(self.attention_block_k,
                                         self.page_size)
            if self.page_size % self.attention_block_k:
                raise ValueError(
                    f"attention block_k {self.attention_block_k} must "
                    f"divide page_size {self.page_size}")
        else:
            self.page_size = 0
            self.n_pages = 0
        self.spec = spec_for_model(cfg, self.max_batch, self.max_seq,
                                   self.kv_cache_dtype,
                                   page_size=self.page_size,
                                   n_pages=self.n_pages)
        self.n_pages = self.spec.n_pages
        self.pages_per_row = self.spec.pages_per_row
        self.mesh = mesh
        self.session = session
        self._sample_key = jax.random.PRNGKey(self.sampling_seed)

        self._cache_shardings = None
        if mesh is not None and dict(mesh.shape).get("model", 1) > 1:
            from jax.sharding import NamedSharding, PartitionSpec
            # commit the sampling key (replicated) up front: an
            # uncommitted first-call key would compile the decode
            # program once, come back committed, and recompile on the
            # second step — breaking the 2-program contract under TP.
            self._sample_key = jax.device_put(
                self._sample_key, NamedSharding(mesh, PartitionSpec()))
            from deepspeed_tpu.models.gpt2 import gpt2_partition_specs
            params = jax.tree_util.tree_map(
                lambda leaf, spec: jax.device_put(
                    leaf, NamedSharding(mesh, spec)),
                params, gpt2_partition_specs(params))
            self._cache_shardings = jax.tree_util.tree_map(
                lambda spec: NamedSharding(mesh, spec),
                kv_partition_specs(self.spec),
                is_leaf=lambda x: not isinstance(x, dict))
            cache = jax.tree_util.tree_map(
                jax.device_put, init_kv_cache(self.spec),
                self._cache_shardings)
        else:
            cache = init_kv_cache(self.spec)
        self.params = params
        self.cache = cache

        # cache (arg 1) is donated in both programs: the ring buffer /
        # page pool updates in place instead of doubling HBM every
        # step. Layout picks which trace to compile — page tables are
        # plain int32 DATA inputs with a fixed shape, so allocator
        # churn never reaches a jit boundary.
        if self.kv_layout == "paged":
            self._prefill = donated_jit(self._prefill_fn_paged,
                                        donate_argnums=(1,))
            self._decode = donated_jit(self._decode_fn_paged,
                                       donate_argnums=(1,))
        else:
            self._prefill = donated_jit(self._prefill_fn,
                                        donate_argnums=(1,))
            self._decode = donated_jit(self._decode_fn,
                                       donate_argnums=(1,))

        # speculative decoding (inference.speculative block): a draft
        # + verify program pair hung off the engine, or None when the
        # block is absent/disabled/degenerate — in which case the
        # 2-program contract above is unchanged. When present, the
        # contract is 3 programs (prefill, draft, verify) and the
        # plain decode program must stay at 0 jit-cache entries.
        from deepspeed_tpu.inference.speculative import build_speculative
        self.speculative = build_speculative(self, config)
        if self.tier is not None and self.speculative is not None:
            raise ValueError(
                "inference.speculative cannot combine with a tiered "
                "(disaggregated) engine — the draft/verify pair would "
                "break the one-program-per-tier contract")

    # -- compiled programs --------------------------------------------------

    def _pin_cache(self, cache):
        """Constrain the output cache to the same shardings the input
        carries: without the pin GSPMD may pick a different output
        layout, and the NEXT call's changed input shardings would cost
        the recompile the whole engine exists to avoid."""
        if self._cache_shardings is None:
            return cache
        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, cache,
            self._cache_shardings)

    def _prefill_fn(self, params, cache, tokens, positions, slot):
        row = slice_rows(cache, slot, self.spec.stacked)
        logits, new_row = self.model.apply(
            {"params": params}, tokens, deterministic=True,
            positions=positions, kv_cache=row)
        cache = update_rows(cache, new_row, slot, self.spec.stacked)
        # fp32 on the way out: host-side sampling/parity reads full
        # precision regardless of compute dtype (a no-op for f32 models,
        # so fp32 parity with the full forward stays bit-exact).
        return logits.astype(jnp.float32), self._pin_cache(cache)

    def _decode_fn(self, params, cache, tokens, positions, key):
        # attention impl / block size / sampling knobs are static (read
        # off self at trace time): they select the traced graph, never
        # ride as runtime values — changing them means a new engine.
        mesh = self.mesh if self._cache_shardings is not None else None
        logits, cache = self.model.apply(
            {"params": params}, tokens[:, None], deterministic=True,
            positions=positions[:, None], kv_cache=cache,
            attn_impl=self.attention_impl,
            attn_block_k=self.attention_block_k, attn_mesh=mesh)
        logits = logits[:, 0]
        from deepspeed_tpu.inference.sampling import sample_logits
        next_tokens, key = sample_logits(
            logits, key, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p)
        return next_tokens, logits.astype(jnp.float32), key, \
            self._pin_cache(cache)

    def _prefill_fn_paged(self, params, cache, tokens, positions,
                          page_table):
        # the paged prefill addresses the POOL through the chunk's
        # page table — no row slice/unslice; the whole cache flows
        # through so donation still updates it in place.
        logits, cache = self.model.apply(
            {"params": params}, tokens, deterministic=True,
            positions=positions, kv_cache=cache,
            kv_page_table=page_table)
        return logits.astype(jnp.float32), self._pin_cache(cache)

    def _decode_fn_paged(self, params, cache, tokens, positions,
                         page_tables, key):
        mesh = self.mesh if self._cache_shardings is not None else None
        logits, cache = self.model.apply(
            {"params": params}, tokens[:, None], deterministic=True,
            positions=positions[:, None], kv_cache=cache,
            attn_impl=self.attention_impl,
            attn_block_k=self.attention_block_k, attn_mesh=mesh,
            kv_page_table=page_tables)
        logits = logits[:, 0]
        from deepspeed_tpu.inference.sampling import sample_logits
        next_tokens, key = sample_logits(
            logits, key, temperature=self.temperature,
            top_k=self.top_k, top_p=self.top_p)
        return next_tokens, logits.astype(jnp.float32), key, \
            self._pin_cache(cache)

    # -- host API -----------------------------------------------------------

    def prefill(self, slot, prompt, page_table=None, start=0):
        """Chunked prefill of ``prompt`` (token ids) into cache row
        ``slot``; returns the fp-logits at the last prompt token
        (``[vocab]``, numpy) — what greedy sampling of the first
        generated token reads.

        Paged layout: ``page_table`` (``[pages_per_row]`` ints, pages
        covering the prompt allocated by the scheduler) addresses the
        pool instead of ``slot``, and ``start`` (chunk-aligned) resumes
        mid-prompt — a prefix-cache hit skips the chunks the shared
        pages already hold; a parked-session resume restarts at the
        session's frontier. The skipped span's KV is bit-identical by
        construction: prefill is deterministic, so re-running it would
        write the same bytes the shared pages already carry."""
        if self.tier == "decode":
            raise RuntimeError(
                "decode-tier engine: the prefill program is pinned off "
                "— prefill belongs to the prefill tier")
        n = len(prompt)
        if not 0 < n <= self.max_seq:
            raise ValueError(
                f"prompt length {n} outside (0, max_seq={self.max_seq}]")
        chunk = self.prefill_chunk
        padded = -(-n // chunk) * chunk
        toks = np.zeros((1, padded), np.int32)
        toks[0, :n] = np.asarray(prompt, np.int32)
        last_chunk = (n - 1) // chunk
        paged = self.kv_layout == "paged"
        if paged:
            if page_table is None:
                raise ValueError("paged prefill requires a page_table")
            pt = jnp.asarray(
                np.asarray(page_table, np.int32).reshape(1, -1))
        # the last chunk always runs (it produces the logits the first
        # sampled token reads), so a resume start clamps to it.
        start = min(int(start), last_chunk * chunk) if paged else 0
        if start % chunk:
            raise ValueError(
                f"prefill start {start} must be chunk-aligned "
                f"(chunk={chunk})")
        from deepspeed_tpu.runtime.resilience import fault_injection
        last = None
        for ci in range(start // chunk, padded // chunk):
            # disagg soak seam: an armed prefill_chunk kill dies HERE,
            # mid-prompt, with pages allocated and partially written.
            fault_injection.maybe_kill("prefill_chunk", ci)
            tc = jnp.asarray(toks[:, ci * chunk:(ci + 1) * chunk])
            pc = jnp.arange(ci * chunk, (ci + 1) * chunk,
                            dtype=jnp.int32)[None, :]
            if paged:
                logits, self.cache = self._prefill(
                    self.params, self.cache, tc, pc, pt)
            else:
                logits, self.cache = self._prefill(
                    self.params, self.cache, tc, pc,
                    jnp.asarray(slot, jnp.int32))
            if ci == last_chunk:
                last = np.asarray(logits[0, (n - 1) % chunk])
        return last

    def decode(self, tokens, positions, page_tables=None):
        """One decode step for every cache row at once. ``tokens`` /
        ``positions``: ``[max_batch]`` int arrays (inactive rows padded
        with zeros — their outputs are meaningless and ignored).
        Returns ``(next_tokens [max_batch], logits [max_batch, vocab])``
        as numpy; sampling (greedy argmax, or temperature/top-k/top-p
        with the threaded PRNG key) happens in-program so it costs no
        extra device round trip. Paged layout additionally takes the
        ``[max_batch, pages_per_row]`` page tables (inactive rows all
        zeros — their garbage token lands on the trash page)."""
        if self.tier == "prefill":
            raise RuntimeError(
                "prefill-tier engine: the decode program is pinned off "
                "— decode belongs to the decode tier")
        t = jnp.asarray(np.asarray(tokens, np.int32))
        p = jnp.asarray(np.asarray(positions, np.int32))
        if self.kv_layout == "paged":
            if page_tables is None:
                raise ValueError("paged decode requires page_tables")
            pt = jnp.asarray(np.asarray(page_tables, np.int32))
            nxt, logits, self._sample_key, self.cache = self._decode(
                self.params, self.cache, t, p, pt, self._sample_key)
        else:
            nxt, logits, self._sample_key, self.cache = self._decode(
                self.params, self.cache, t, p, self._sample_key)
        return np.asarray(nxt), np.asarray(logits)

    # -- host-RAM page tier (paged layout only) -----------------------------

    def gather_pages(self, page_ids):
        """Snapshot the given physical pages to host RAM: a per-layer
        ``{"k": [n, page_size, H, D], ...}`` numpy pytree, copied with
        the hot-checkpoint snapshot-isolation discipline
        (`runtime/resilience/hotckpt.py:_snapshot_to_host` — the
        compiled steps donate the pool, so host views must never alias
        live device memory). Runs OUTSIDE the two compiled programs:
        parking is host-side admission work, the steady-state decode
        program stays transfer-free."""
        from deepspeed_tpu.runtime.resilience.hotckpt import (
            _snapshot_to_host)
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        axis = 1 if self.spec.stacked else 0
        gathered = jax.tree_util.tree_map(
            lambda leaf: jnp.take(leaf, ids, axis=axis), self.cache)
        return _snapshot_to_host(gathered)

    def gather_pages_device(self, page_ids):
        """Like :meth:`gather_pages` but the snapshot STAYS on device:
        a pytree of fresh (immutable) device arrays, never a host round
        trip. This is the in-process disaggregated handoff's source
        half — the decode tier scatters these arrays straight into its
        own pool (:meth:`scatter_pages` accepts device values), so the
        prefill→decode page copy is device-to-device and keyed purely
        by page ids. The copies are materialized eagerly so they can't
        alias pool buffers a later donated prefill call invalidates."""
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        axis = 1 if self.spec.stacked else 0
        gathered = jax.tree_util.tree_map(
            lambda leaf: jnp.take(leaf, ids, axis=axis), self.cache)
        jax.block_until_ready(gathered)
        return gathered

    def scatter_pages(self, page_ids, host_pages):
        """Inverse of :meth:`gather_pages`: write a host page snapshot
        back into (freshly allocated) physical pages — the resume half
        of the host tier."""
        ids = np.asarray(page_ids, np.int32)
        axis = 1 if self.spec.stacked else 0

        def upd(leaf, vals):
            vals = jnp.asarray(vals, leaf.dtype)
            if axis == 0:
                return leaf.at[ids].set(vals)
            return leaf.at[:, ids].set(vals)

        self.cache = jax.tree_util.tree_map(upd, self.cache, host_pages)
        if self._cache_shardings is not None:
            # eager .at updates drop the committed sharding; re-place
            # so the next compiled call sees the pinned layout.
            self.cache = jax.tree_util.tree_map(
                jax.device_put, self.cache, self._cache_shardings)

    def sample_first(self, last_logits):
        """Sample the FIRST generated token from prefill's last-prompt-
        token logits (``[vocab]`` numpy) with the same temperature /
        top-k / top-p pipeline the compiled decode step uses — one tiny
        eager call at admission time, sharing the decode key stream so
        a fixed request stream samples reproducibly."""
        from deepspeed_tpu.inference.sampling import sample_logits
        tok, self._sample_key = sample_logits(
            jnp.asarray(last_logits, jnp.float32), self._sample_key,
            temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p)
        return int(tok)

    def reset(self):
        """Zero the cache (rows all free). Compiled programs survive —
        a reset must not cost a recompile."""
        cache = init_kv_cache(self.spec)
        if self._cache_shardings is not None:
            cache = jax.tree_util.tree_map(
                jax.device_put, cache, self._cache_shardings)
        self.cache = cache

    # -- recompile detector + audit surface ---------------------------------

    def compile_counts(self):
        """Jit-cache entry counts ``{"prefill": n, "decode": n}`` — the
        serving analog of `analysis/audit.py:compiled_cache_size`. 1/1
        after warmup and FOREVER after is the contract; growth means a
        shape or dtype leaked into a compiled boundary."""
        progs = [("prefill", self._prefill), ("decode", self._decode)]
        if self.speculative is not None:
            progs += [("draft", self.speculative._draft),
                      ("verify", self.speculative._verify)]
        out = {}
        for name, fn in progs:
            cs = getattr(fn, "_cache_size", None)
            try:
                out[name] = int(cs()) if callable(cs) else None
            except Exception:
                out[name] = None
        return out

    def recompile_findings(self, baseline=1):
        """In-engine recompile detector: error Findings when either
        compiled program's cache outgrew ``baseline`` entries."""
        from deepspeed_tpu.analysis.rules import SEV_ERROR, Finding
        findings = []
        for name, n in self.compile_counts().items():
            if n is not None and n > baseline:
                findings.append(Finding(
                    "decode", SEV_ERROR,
                    f"{name} program has {n} jit cache entries "
                    f"(expected {baseline}) — the serving loop "
                    f"recompiled mid-stream",
                    {"program": name, "cache_size": n,
                     "expected": baseline}))
        return findings

    def decode_lowering_args(self):
        """The exact avals :meth:`decode` calls with — lowering through
        these is a jit-cache hit, never a fresh compile."""
        if self.kv_layout == "paged":
            return (self.params, self.cache,
                    jnp.zeros((self.max_batch,), jnp.int32),
                    jnp.zeros((self.max_batch,), jnp.int32),
                    jnp.zeros((self.max_batch, self.pages_per_row),
                              jnp.int32),
                    self._sample_key)
        return (self.params, self.cache,
                jnp.zeros((self.max_batch,), jnp.int32),
                jnp.zeros((self.max_batch,), jnp.int32),
                self._sample_key)

    def decode_hlo(self):
        """Compiled HLO text of the decode program (audit/bench food)."""
        args = self.decode_lowering_args()
        return self._decode.lower(*args).compile().as_text()

    def cache_facts(self):
        """Static cache facts for audits and the bench row."""
        facts = {"bytes": kv_cache_nbytes(self.cache),
                 "dtype_census": cache_dtype_census(self.cache),
                 "kv_cache_dtype": self.kv_cache_dtype,
                 "kv_layout": self.kv_layout,
                 "max_batch": self.max_batch,
                 "max_seq": self.max_seq,
                 "seq_buckets": list(self.seq_buckets),
                 "prefill_chunk": self.prefill_chunk,
                 "stacked": self.spec.stacked}
        if self.kv_layout == "paged":
            facts.update(page_size=self.page_size,
                         n_pages=self.n_pages,
                         pages_per_row=self.pages_per_row)
        if self.tier is not None:
            facts["tier"] = self.tier
        if self.speculative is not None:
            facts["speculative"] = self.speculative.facts()
        return facts
