"""Disaggregated prefill/decode serving: tier workers and the KV
handoff between them.

The colocated scheduler interleaves chunked prefill with decode inside
one loop, so a long prompt's chunk train steals decode steps from every
live row on the replica. Disaggregation splits the two compiled
programs onto separate *tiers*: prefill workers only ever run the
prefill program (writing paged KV and sampling the first token), decode
workers only ever run the decode step, and a finished prompt moves
between them through an explicit KV **handoff**. Each tier therefore
pins exactly ONE compiled program from warmup to drain — the fleet
holds 2 programs total instead of 2 per replica — and the tiers scale
independently (N prefill workers against M decode workers, each with
its own ``max_batch``).

The handoff is admission METADATA, never a compiled shape: what travels
is the page contents plus a tiny :class:`HandoffMeta` record (first
token, KV frontier, page geometry), and the decode tier installs the
pages through the same ``scatter_pages`` seam session page-in uses.
Two transports implement one store contract
(``park``/``install``/``parked``/``peek``/``drop``):

- :class:`DeviceHandoffStore` — in-process: ``gather_pages_device``
  snapshots the pages into fresh immutable device arrays (no aliasing
  with the donated pool) and ``install`` is a device-to-device scatter.
  Consume-once: a decode worker that dies after installing re-prefills,
  because nothing durable was parked.
- :class:`FileHandoffStore` — cross-process: pages ride the PR 16
  host-tier discipline (CRC-stamped with ``_leaf_checksums``, verified
  at install, :class:`HostPageCorruptError` on rot → cold re-prefill)
  through an npz file in a shared directory. The file is RETAINED until
  the request completes, so a dead decode worker resumes from the
  parked snapshot instead of re-prefilling.

:class:`PrefillWorker` / :class:`DecodeWorker` are the per-tier loops
(driven by tier replicas in `fleet.py` or a worker process in
`fleet_worker.py`); :class:`DisaggCoordinator` drives both tiers
synchronously in one process — deterministic, thread-free — for parity
tests, ``audit_disagg`` and the bench A/B row.
"""

import collections
import json
import os
import threading
import time

import numpy as np

from deepspeed_tpu.inference.paging import (
    HostPageCorruptError,
    PagedCacheManager,
    RowPaging,
)
from deepspeed_tpu.inference.scheduler import ContinuousBatchingScheduler
from deepspeed_tpu.runtime.resilience import fault_injection
from deepspeed_tpu.runtime.resilience.checkpoint import _leaf_checksums

META_FIELDS = ("rid", "prompt_len", "first_token", "next_pos",
               "page_size", "pages_per_row", "n_pages", "parked")


class HandoffMeta:
    """The admission metadata half of a KV handoff: everything the
    decode tier needs to seed a slot WITHOUT running prefill. Geometry
    fields (``page_size``/``pages_per_row``) are carried so the decode
    tier can refuse a cross-geometry handoff before touching its pool —
    the static half of that pin lives in ``rule_decode``."""

    def __init__(self, rid, prompt_len, first_token, next_pos,
                 page_size, pages_per_row, n_pages, parked):
        self.rid = str(rid)
        self.prompt_len = int(prompt_len)
        self.first_token = int(first_token)
        self.next_pos = int(next_pos)
        self.page_size = int(page_size)
        self.pages_per_row = int(pages_per_row)
        self.n_pages = int(n_pages)
        self.parked = bool(parked)

    def to_dict(self):
        return {k: getattr(self, k) for k in META_FIELDS}

    @classmethod
    def from_dict(cls, d):
        return cls(**{k: d[k] for k in META_FIELDS})


class DeviceHandoffStore:
    """In-process handoff transport: page snapshots held as immutable
    device arrays, consume-once (``install`` pops). ``parked`` is
    always False — nothing here survives a worker death, so the router
    re-prefills instead of resuming."""

    durable = False

    def __init__(self):
        self._held = {}             # rid -> (device pytree, meta, nbytes)
        self._lock = threading.Lock()

    def __len__(self):
        return len(self._held)

    def park(self, rid, engine, page_ids, meta):
        vals = engine.gather_pages_device(page_ids)
        import jax
        nbytes = sum(int(leaf.nbytes)
                     for leaf in jax.tree_util.tree_leaves(vals))
        with self._lock:
            self._held[rid] = (vals, meta, nbytes)
        return nbytes

    def install(self, rid, engine, page_ids):
        with self._lock:
            vals, meta, _ = self._held.pop(rid)     # KeyError if gone
        engine.scatter_pages(page_ids, vals)
        return meta

    def parked(self, rid):
        return False

    def peek(self, rid):
        with self._lock:
            held = self._held.get(rid)
        return held[1] if held is not None else None

    def drop(self, rid):
        with self._lock:
            self._held.pop(rid, None)


class FileHandoffStore:
    """Cross-process handoff transport: CRC-stamped npz snapshots in a
    shared directory, written atomically and retained until ``drop`` —
    a handed-off session IS parked, so a dead decode worker resumes
    from the file instead of re-prefilling. Verification failure at
    install removes the snapshot (rotted bytes help nobody) and raises
    :class:`HostPageCorruptError`, which the decode worker surfaces as
    a ``handoff_corrupt`` message → the router cold re-prefills."""

    durable = True

    def __init__(self, dirpath):
        self.dir = os.path.abspath(dirpath)
        os.makedirs(self.dir, exist_ok=True)

    def _stem(self, rid):
        import zlib
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(rid))
        return os.path.join(
            self.dir, f"{safe}-{zlib.crc32(str(rid).encode()):08x}")

    def park(self, rid, engine, page_ids, meta):
        import jax
        host = engine.gather_pages(page_ids)
        checksums = _leaf_checksums(host)
        if fault_injection.corrupt_host_pages(rid):
            # Harness-injected rot: flip one byte in the first leaf
            # AFTER the CRCs were stamped, so install() must detect it.
            done = [False]

            def _flip(leaf):
                if done[0]:
                    return leaf
                done[0] = True
                buf = np.array(leaf)
                buf.reshape(-1).view(np.uint8)[0] ^= 0xFF
                return buf

            host = jax.tree_util.tree_map(_flip, host)
        leaves = [np.asarray(leaf)
                  for leaf in jax.tree_util.tree_leaves(host)]
        nbytes = sum(int(leaf.nbytes) for leaf in leaves)
        stem = self._stem(rid)
        with open(stem + ".npz.tmp", "wb") as f:
            np.savez(f, **{f"leaf_{i}": leaf
                           for i, leaf in enumerate(leaves)})
        os.replace(stem + ".npz.tmp", stem + ".npz")
        with open(stem + ".json.tmp", "w") as f:
            json.dump({"meta": meta.to_dict(), "checksums": checksums,
                       "n_leaves": len(leaves), "nbytes": nbytes}, f)
        os.replace(stem + ".json.tmp", stem + ".json")
        return nbytes

    def install(self, rid, engine, page_ids):
        import jax
        stem = self._stem(rid)
        try:
            with open(stem + ".json") as f:
                manifest = json.load(f)
        except OSError:
            raise KeyError(rid)
        with np.load(stem + ".npz") as z:
            leaves = [z[f"leaf_{i}"]
                      for i in range(manifest["n_leaves"])]
        tree = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(engine.cache), leaves)
        actual = _leaf_checksums(tree)
        if actual != manifest["checksums"]:
            bad = sorted(k for k in manifest["checksums"]
                         if actual.get(k) != manifest["checksums"][k])
            self.drop(rid)
            raise HostPageCorruptError(rid, bad)
        engine.scatter_pages(page_ids, tree)
        return HandoffMeta.from_dict(manifest["meta"])

    def parked(self, rid):
        return os.path.exists(self._stem(rid) + ".json")

    def peek(self, rid):
        try:
            with open(self._stem(rid) + ".json") as f:
                return HandoffMeta.from_dict(json.load(f)["meta"])
        except OSError:
            return None

    def drop(self, rid):
        stem = self._stem(rid)
        for ext in (".npz", ".json"):
            try:
                os.remove(stem + ext)
            except OSError:
                pass


def _bucket_for(engine, request):
    """Smallest seq bucket fitting prompt + budget (the scheduler's
    rule, shared so the prefill tier's early finishes bucket the same
    way the decode tier would have)."""
    need = len(request.prompt) + request.max_new_tokens
    for b in engine.seq_buckets:
        if need <= b:
            return b
    return engine.max_seq


class PrefillWorker:
    """The prefill tier's loop: admit → chunked prefill → sample first
    token → hand the pages off. Never calls the decode program, so the
    engine's decode jit cache holds zero entries for the worker's whole
    life (``engine.tier == "prefill"`` turns that into a hard raise).

    A request whose FIRST token already finishes it (eos, a 1-token
    budget, a bucket-clamped prompt) completes here and never travels —
    the same outcome the colocated loop's post-admission check
    produces. Everything else becomes a ``prefilled`` output carrying
    the :class:`HandoffMeta` for the router to dispatch decode-side.
    """

    tier = "prefill"

    def __init__(self, engine, store, session=None):
        if getattr(engine, "kv_layout", "ring") != "paged":
            raise ValueError(
                "disaggregated tiers require kv_layout='paged' — the "
                "KV handoff is a page copy")
        if getattr(engine, "tier", None) not in (None, "prefill"):
            raise ValueError(
                f"PrefillWorker needs a prefill-tier engine, got "
                f"tier={engine.tier!r}")
        self.engine = engine
        self.store = store
        self.session = session if session is not None \
            else engine.session
        self.paging = PagedCacheManager(engine, session=self.session)
        self.queue = collections.deque()
        self.outbox = []
        self.steps = 0
        self.prefills = 0
        self.handoffs = 0
        self.handoff_bytes = 0
        self.completed = 0

    @property
    def has_work(self):
        return bool(self.queue)

    def submit(self, request, meta=None):
        if not request.prompt:
            raise ValueError(f"request {request.rid}: empty prompt")
        if len(request.prompt) >= self.engine.max_seq:
            raise ValueError(
                f"request {request.rid}: prompt length "
                f"{len(request.prompt)} does not fit the largest seq "
                f"bucket {self.engine.max_seq}")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.rid}: max_new_tokens must be >= 1")
        if request.submit_t is None:
            request.submit_t = time.monotonic()
        self.queue.append(request)

    def drain_outputs(self):
        out, self.outbox = self.outbox, []
        return out

    def _complete(self, req, tokens, reason, row=None):
        comp = {
            "kind": "completion", "rid": req.rid,
            "prompt_len": len(req.prompt), "tokens": list(tokens),
            "finish_reason": reason,
            "bucket": _bucket_for(self.engine, req), "slot": 0,
            "steps": 0, "resumed": False,
            "prefix_hit": row.prefix_hit if row else False,
            "prefill_chunks": row.prefill_chunks if row else 0,
            "prefill_chunks_skipped":
                row.prefill_chunks_skipped if row else 0,
            "redispatched": req.redispatched, "restarts": req.restarts,
            "tier": "prefill"}
        if row is not None:
            self.paging.release(row)
        self.completed += 1
        self.outbox.append(comp)

    def step(self):
        """Prefill ONE queued request end to end (admission → handoff
        or local completion). Returns True while the queue holds more
        work."""
        if not self.queue:
            return False
        req = self.queue.popleft()
        # Cross-tier session parking is future work: the prefill tier
        # only reuses prompt KV through the radix prefix cache.
        row = self.paging.admit(req.prompt, session_id=None)
        if row is None:
            # This worker frees its pages synchronously after every
            # handoff, so a dry pool here is PERMANENT (the prompt
            # outsizes the pool even with every radix leaf evicted) —
            # a typed completion beats an admission spin.
            self._complete(req, [], "incomplete", row=None)
            return bool(self.queue)
        t0 = time.perf_counter()
        last_logits = self.engine.prefill(
            0, req.prompt,
            page_table=row.table(self.paging.pages_per_row),
            start=row.start)
        self.paging.after_prefill(row, req.prompt)
        first = self.engine.sample_first(last_logits)
        wall = time.perf_counter() - t0
        self.steps += 1
        self.prefills += 1
        self._emit(req, row, wall)
        reason = None
        if req.eos_id is not None and first == req.eos_id:
            reason = "eos"
        elif req.max_new_tokens <= 1:
            reason = "max_new_tokens"
        elif len(req.prompt) >= _bucket_for(self.engine, req):
            reason = "length"
        if reason is not None:
            self._complete(req, [first], reason, row=row)
            return bool(self.queue)
        meta = HandoffMeta(
            rid=req.rid, prompt_len=len(req.prompt), first_token=first,
            next_pos=len(req.prompt), page_size=self.engine.page_size,
            pages_per_row=self.engine.pages_per_row,
            n_pages=len(row.pages), parked=self.store.durable)
        nbytes = self.store.park(req.rid, self.engine, row.pages, meta)
        # The row's references drop; interned prefix pages survive via
        # the radix tree's own refs, so later prompts still hit them.
        self.paging.release(row)
        self.handoffs += 1
        self.handoff_bytes += nbytes
        self.outbox.append({
            "kind": "prefilled", "rid": req.rid,
            "prompt_len": len(req.prompt), "handoff": meta.to_dict(),
            "handoff_bytes": nbytes, "prefix_hit": row.prefix_hit,
            "prefill_chunks": row.prefill_chunks,
            "prefill_chunks_skipped": row.prefill_chunks_skipped,
            "wall_s": wall})
        return bool(self.queue)

    def _emit(self, req, row, wall_s):
        if self.session is None:
            return
        self.session.emit(
            "prefill_step", tier="prefill", rid=req.rid, step=self.steps,
            prompt_len=len(req.prompt), chunks=row.prefill_chunks,
            chunks_skipped=row.prefill_chunks_skipped,
            prefix_hit=row.prefix_hit, queue_depth=len(self.queue),
            wall_s=wall_s,
            pages_free=self.paging.allocator.free_pages)
        reg = self.session.registry
        reg.histogram(
            "prefill_step_seconds",
            help="host wall per prefill-tier admission").observe(wall_s)
        reg.counter(
            "prefill_requests_total",
            help="requests prefilled by the prefill tier").inc()

    def stats(self):
        counts = self.engine.compile_counts() if hasattr(
            self.engine, "compile_counts") else {}
        return {"tier": "prefill", "compile_counts": counts,
                "steps": self.steps, "completed": self.completed,
                "prefills": self.prefills, "handoffs": self.handoffs,
                "handoff_bytes": self.handoff_bytes}


class DecodeWorker:
    """The decode tier's loop: install handed-off pages, seed a slot
    through ``admit_prefilled`` (no prefill call — the prefill jit
    cache stays empty, and ``engine.tier == "decode"`` makes any slip a
    hard raise), then run the plain continuous-batching decode loop.

    Handoff failures are typed outputs, not crashes: a CRC-rotted
    snapshot (``handoff_corrupt``) or a consumed/missing one
    (``handoff_missing``) tells the router to cold re-prefill; a
    geometry mismatch (``handoff_error``) is a config bug re-prefill
    can't fix, reported as a failed completion."""

    tier = "decode"

    def __init__(self, engine, store, session=None):
        if getattr(engine, "kv_layout", "ring") != "paged":
            raise ValueError(
                "disaggregated tiers require kv_layout='paged' — the "
                "KV handoff is a page copy")
        if getattr(engine, "tier", None) not in (None, "decode"):
            raise ValueError(
                f"DecodeWorker needs a decode-tier engine, got "
                f"tier={engine.tier!r}")
        self.engine = engine
        self.store = store
        self.session = session if session is not None \
            else engine.session
        self.sched = ContinuousBatchingScheduler(
            engine, session=self.session)
        self.pending = collections.deque()   # (request, HandoffMeta)
        self.outbox = []
        self._reported = 0
        self.installed = 0
        self.corrupt = 0
        self.completed = 0

    @property
    def has_work(self):
        return bool(self.pending) or bool(self.sched.queue) or any(
            s is not None for s in self.sched.slots)

    def submit(self, request, meta=None):
        if meta is None:
            raise ValueError(
                f"request {request.rid}: the decode tier only accepts "
                f"handoffs (no prefill program here)")
        if not isinstance(meta, HandoffMeta):
            meta = HandoffMeta.from_dict(meta)
        # Cross-tier session parking is future work: pages parked here
        # could never be resumed (admission happens on the other tier),
        # so they would leak in this pool until eviction pressure.
        request.session_id = None
        self.pending.append((request, meta))

    def drain_outputs(self):
        out, self.outbox = self.outbox, []
        return out

    def _free(self, pages):
        for p in pages:
            self.sched.paging.allocator.decref(p)

    def _try_install(self):
        pg = self.sched.paging
        while self.pending:
            if all(s is not None for s in self.sched.slots):
                return
            req, meta = self.pending[0]
            if meta.page_size != pg.page_size or \
                    meta.pages_per_row != pg.pages_per_row:
                self.pending.popleft()
                self.outbox.append({
                    "kind": "handoff_error", "rid": req.rid,
                    "error": f"handoff geometry mismatch: prefill tier "
                             f"page_size={meta.page_size}/"
                             f"pages_per_row={meta.pages_per_row}, "
                             f"decode tier {pg.page_size}/"
                             f"{pg.pages_per_row}"})
                continue
            pages, dry = [], False
            for _ in range(meta.n_pages):
                p = pg._alloc_with_pressure()
                if p is None:
                    dry = True
                    break
                pages.append(p)
            if dry:
                self._free(pages)
                if any(s is not None for s in self.sched.slots):
                    return          # live rows will free pages; retry
                # nothing live and the ladder is dry: this handoff can
                # never land in this pool — typed completion, not a spin
                self.pending.popleft()
                self.outbox.append({
                    "kind": "completion", "rid": req.rid,
                    "prompt_len": meta.prompt_len, "tokens": [],
                    "finish_reason": "incomplete",
                    "bucket": _bucket_for(self.engine, req), "slot": -1,
                    "steps": 0, "prefix_hit": False, "resumed": False,
                    "prefill_chunks": 0, "prefill_chunks_skipped": 0,
                    "redispatched": req.redispatched,
                    "restarts": req.restarts, "tier": "decode"})
                self.completed += 1
                continue
            try:
                self.store.install(req.rid, self.engine, pages)
            except KeyError:
                self._free(pages)
                self.pending.popleft()
                self.outbox.append(
                    {"kind": "handoff_missing", "rid": req.rid})
                continue
            except HostPageCorruptError:
                self._free(pages)
                self.pending.popleft()
                self.corrupt += 1
                self.outbox.append(
                    {"kind": "handoff_corrupt", "rid": req.rid})
                if self.session is not None:
                    self.session.emit(
                        "handoff_corrupt", level="warning", rid=req.rid,
                        tier="decode")
                continue
            self.pending.popleft()
            row = RowPaging(pages=pages, start=0, resumed=True)
            self.sched.admit_prefilled(req, row, meta.first_token)
            self.installed += 1

    def step(self):
        self._try_install()
        if bool(self.sched.queue) or any(
                s is not None for s in self.sched.slots):
            self.sched.step()
        new = self.sched.completions[self._reported:]
        self._reported = len(self.sched.completions)
        if new:
            from deepspeed_tpu.inference.fleet import completion_dict
            for c in new:
                self.completed += 1
                self.outbox.append(dict(completion_dict(c),
                                        kind="completion",
                                        tier="decode"))
        return self.has_work

    def stats(self):
        counts = self.engine.compile_counts() if hasattr(
            self.engine, "compile_counts") else {}
        return {"tier": "decode", "compile_counts": counts,
                "steps": self.sched.step_count,
                "completed": self.completed,
                "installed": self.installed, "corrupt": self.corrupt}


class DisaggCoordinator:
    """Both tiers driven synchronously in one process: round-robin
    dispatch into N prefill workers, least-loaded dispatch of finished
    handoffs into M decode workers, corrupt handoffs recycled as cold
    re-prefills. Deterministic (no threads, no wall-clock scheduling),
    which is exactly what the parity tests, ``audit_disagg`` and the
    bench A/B row need: same request stream in, same tokens out, while
    each tier's ``compile_counts()`` pins one program."""

    def __init__(self, prefill_engines, decode_engines, store=None,
                 session=None):
        if not prefill_engines or not decode_engines:
            raise ValueError("need >= 1 engine per tier")
        self.store = store if store is not None else DeviceHandoffStore()
        self.prefill = [PrefillWorker(e, self.store, session=session)
                        for e in prefill_engines]
        self.decode = [DecodeWorker(e, self.store, session=session)
                       for e in decode_engines]
        self.session = session
        self.completions = []
        self.handoffs = 0
        self.handoff_bytes = 0
        self.reprefills = 0
        self._requests = {}
        self._rr = 0

    def _submit_prefill(self, request):
        self._requests[request.rid] = request
        self.prefill[self._rr % len(self.prefill)].submit(request)
        self._rr += 1

    def _decode_target(self):
        def load(w):
            live = sum(1 for s in w.sched.slots if s is not None)
            free = w.engine.max_batch - live
            return (len(w.pending) - free, len(w.pending))
        return min(self.decode, key=load)

    def _route(self, out):
        kind = out.get("kind")
        if kind == "prefilled":
            self.handoffs += 1
            self.handoff_bytes += out["handoff_bytes"]
            req = self._requests[out["rid"]]
            self._decode_target().submit(
                req, HandoffMeta.from_dict(out["handoff"]))
        elif kind in ("handoff_corrupt", "handoff_missing"):
            # cold re-prefill: never serve from a rotten page
            req = self._requests[out["rid"]]
            req.restarts += 1
            self.reprefills += 1
            self.store.drop(out["rid"])
            self.prefill[self._rr % len(self.prefill)].submit(req)
            self._rr += 1
        elif kind == "handoff_error":
            raise RuntimeError(out["error"])
        else:                       # completion
            self.completions.append(out)
            self.store.drop(out["rid"])

    def run(self, requests, max_rounds=100000):
        """Drain ``requests`` through both tiers; completion dicts in
        finish order (each tagged with the tier that finished it)."""
        for r in requests:
            self._submit_prefill(r)
        for _ in range(max_rounds):
            busy = False
            for w in self.prefill:
                if w.has_work:
                    busy = True
                    w.step()
                for out in w.drain_outputs():
                    self._route(out)
            for w in self.decode:
                if w.has_work:
                    busy = True
                    w.step()
                for out in w.drain_outputs():
                    self._route(out)
            if not busy and not any(w.has_work for w in self.prefill) \
                    and not any(w.has_work for w in self.decode):
                break
        return list(self.completions)

    def tier_stats(self):
        """Per-tier aggregates, compile counts summed across each
        tier's workers — the numbers the 2-program contract pins."""
        def agg(workers):
            counts = {"prefill": 0, "decode": 0}
            stats = [w.stats() for w in workers]
            for s in stats:
                for k, v in s["compile_counts"].items():
                    counts[k] = counts.get(k, 0) + v
            return {"workers": len(workers), "compile_counts": counts,
                    "per_worker": stats}
        out = {"prefill": agg(self.prefill), "decode": agg(self.decode)}
        out["handoffs"] = self.handoffs
        out["handoff_bytes"] = self.handoff_bytes
        out["handoff_bytes_per_session"] = (
            self.handoff_bytes // self.handoffs if self.handoffs else 0)
        out["reprefills"] = self.reprefills
        return out
