"""Continuous batching: the host-side admit/evict/pad loop.

The compiled decode step always runs the full ``[max_batch]`` row
block; this scheduler is everything around it — an open-loop request
queue, slot assignment (the ring: a finished request's row goes
straight to the next arrival), per-request sequence budgets from
``seq_buckets``, and the pad arrays that keep inactive rows
shape-stable. None of it touches a jit boundary, so admission, buckets
and eviction are recompile-free by construction (and the engine's
compile counters prove it).

Buckets: a request's budget is the smallest ``seq_bucket`` that fits
``prompt + max_new_tokens`` (clamped to the largest). The bucket caps
how far the row may fill — a metadata cap, deliberately NOT a compiled
shape — so short requests get admission-control/accounting granularity
without buying per-bucket XLA programs.

Every decode step emits one ``decode_step`` telemetry event (tokens
produced, live batch, occupancy, queue depth, host wall) through the
session, feeding ``ds_tpu_metrics summary``'s serve mode and the
registry's ``decode_*`` metric families.

With a paged engine (``inference.kv_layout = "paged"``) the scheduler
delegates page mapping to `inference/paging.py:PagedCacheManager`:
admission walks the radix prefix cache (shared pages mapped, prefill
resumed mid-prompt), each decode step grows rows' mappings page by
page, and a finished request carrying a ``session_id`` parks its pages
(device first, host RAM under pressure) instead of freeing them. All
of it stays host-side: the compiled decode step just receives the
``[max_batch, pages_per_row]`` tables the manager maintains.
"""

import collections
import dataclasses
import time
from typing import List, Optional

import numpy as np

from deepspeed_tpu.runtime.resilience import fault_injection


@dataclasses.dataclass
class Request:
    """One generation request. ``arrival_step``>0 makes the stream
    open-loop: the scheduler won't admit the request before its decode
    step count reaches it (deterministic synthetic load for benches and
    tests). ``session_id`` (paged engines) parks the request's KV pages
    at completion so a follow-up request on the same session resumes
    without re-prefilling its history.

    Robustness knobs (ISSUE 17): ``deadline_s`` bounds the request's
    TOTAL wall clock from first submit to completion, ``queue_timeout_s``
    bounds its wait for a cache row — either expiry finishes it with the
    typed ``timeout`` reason instead of letting it stall the stream.
    ``redispatched``/``restarts`` are stamped by the fleet router when a
    replica death forces a re-prefill elsewhere; ``submit_t`` is the
    monotonic clock at FIRST submit and survives redispatch, so the
    deadline spans retries (exactly-once completion semantics over
    at-least-once execution)."""
    rid: str
    prompt: List[int]
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    arrival_step: int = 0
    session_id: Optional[str] = None
    deadline_s: Optional[float] = None
    queue_timeout_s: Optional[float] = None
    redispatched: int = 0       # replica-death redispatches (router)
    restarts: int = 0           # total re-executions (router)
    submit_t: Optional[float] = None


@dataclasses.dataclass
class Completion:
    rid: str
    prompt_len: int
    tokens: List[int]           # generated ids (includes eos when hit)
    finish_reason: str          # "max_new_tokens" | "eos" | "length" |
                                # "timeout" | "incomplete"
    bucket: int
    slot: int                   # -1: never held a row (queued timeout)
    steps: int                  # decode steps this request was live for
    prefix_hit: bool = False    # admitted on shared radix pages
    resumed: bool = False       # admitted by resuming a parked session
    prefill_chunks: int = 0     # prefill chunks actually run
    prefill_chunks_skipped: int = 0
    redispatched: int = 0       # times redispatched across replicas
    restarts: int = 0           # times its execution restarted


@dataclasses.dataclass
class _Slot:
    request: Request
    bucket: int
    next_pos: int               # position the pending token feeds at
    pending: int                # last sampled token (next decode input)
    generated: List[int]
    admitted_step: int
    paging: object = None       # RowPaging when the engine is paged


class ContinuousBatchingScheduler:
    def __init__(self, engine, session=None):
        self.engine = engine
        self.session = session if session is not None else engine.session
        self.queue = collections.deque()
        self.slots = [None] * engine.max_batch
        self.step_count = 0
        self.completions = []
        self.paging = None
        if getattr(engine, "kv_layout", "ring") == "paged":
            from deepspeed_tpu.inference.paging import PagedCacheManager
            self.paging = PagedCacheManager(engine, session=self.session)

    # -- request lifecycle --------------------------------------------------

    def submit(self, request):
        if not request.prompt:
            raise ValueError(f"request {request.rid}: empty prompt")
        if len(request.prompt) >= self.engine.max_seq:
            raise ValueError(
                f"request {request.rid}: prompt length "
                f"{len(request.prompt)} does not fit the largest seq "
                f"bucket {self.engine.max_seq}")
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.rid}: max_new_tokens must be >= 1")
        if request.submit_t is None:    # survives redispatch resubmits
            request.submit_t = time.monotonic()
        self.queue.append(request)

    def admit_prefilled(self, request, row, first_token):
        """Seed a slot from ANOTHER tier's finished prefill
        (disaggregated serving, ISSUE 20): the prompt's KV already sits
        in this engine's pool on ``row``'s pages (installed by the KV
        handoff) and ``first_token`` was sampled from the prefill-tier
        logits, so admission here runs NO prefill call — the decode
        tier's prefill program stays at zero jit-cache entries. Returns
        False when no slot is free (the caller keeps the handoff
        queued)."""
        for i in range(len(self.slots)):
            if self.slots[i] is not None:
                continue
            if request.submit_t is None:
                request.submit_t = time.monotonic()
            self.slots[i] = _Slot(
                request=request, bucket=self._bucket_for(request),
                next_pos=len(request.prompt), pending=first_token,
                generated=[first_token],
                admitted_step=self.step_count, paging=row)
            # eos / single-token budgets can finish right here, exactly
            # where the colocated loop's post-admission check fires.
            self._check_finished(i)
            return True
        return False

    def _bucket_for(self, request):
        need = len(request.prompt) + request.max_new_tokens
        for b in self.engine.seq_buckets:
            if need <= b:
                return b
        return self.engine.max_seq      # clamp: generation truncates

    def _finish(self, i, reason):
        s = self.slots[i]
        comp = Completion(
            rid=s.request.rid, prompt_len=len(s.request.prompt),
            tokens=list(s.generated), finish_reason=reason, bucket=s.bucket,
            slot=i, steps=self.step_count - s.admitted_step,
            redispatched=s.request.redispatched,
            restarts=s.request.restarts)
        if s.paging is not None:
            comp.prefix_hit = s.paging.prefix_hit
            comp.resumed = s.paging.resumed
            comp.prefill_chunks = s.paging.prefill_chunks
            comp.prefill_chunks_skipped = s.paging.prefill_chunks_skipped
            # KV on the pages covers the prompt plus every generated
            # token that fed a later decode step (the LAST sampled
            # token was never written — nothing attended past it).
            kv_tokens = list(s.request.prompt) + s.generated[:-1]
            self.paging.release(s.paging, kv_tokens=kv_tokens,
                                session_id=s.request.session_id)
        self.completions.append(comp)
        self.slots[i] = None            # row back on the ring

    def _finish_unstarted(self, request, reason):
        """Record a completion for a request that never held a row
        (queued timeout / max_steps exhaustion)."""
        self.completions.append(Completion(
            rid=request.rid, prompt_len=len(request.prompt), tokens=[],
            finish_reason=reason, bucket=self._bucket_for(request),
            slot=-1, steps=0, redispatched=request.redispatched,
            restarts=request.restarts))

    def _check_finished(self, i):
        s = self.slots[i]
        if s.request.eos_id is not None and \
                s.pending == s.request.eos_id:
            self._finish(i, "eos")
        elif len(s.generated) >= s.request.max_new_tokens:
            self._finish(i, "max_new_tokens")
        elif s.next_pos >= s.bucket:
            # bucket budget exhausted: evict (truncated generation)
            self._finish(i, "length")

    def _expire(self):
        """Typed ``timeout`` finishes: queued requests past their queue
        timeout (or total deadline) drop WITHOUT ever taking a row, and
        live rows past their deadline finish with whatever they
        generated so far."""
        now = time.monotonic()

        def _queued_expired(r):
            waited = now - r.submit_t if r.submit_t is not None else 0.0
            return ((r.queue_timeout_s is not None and
                     waited > r.queue_timeout_s) or
                    (r.deadline_s is not None and waited > r.deadline_s))

        expired = [r for r in self.queue if _queued_expired(r)]
        if expired:
            self.queue = collections.deque(
                r for r in self.queue if not _queued_expired(r))
        for r in expired:
            self._finish_unstarted(r, "timeout")
            if self.session is not None:
                self.session.emit("request_timeout", rid=r.rid,
                                  where="queue", step=self.step_count)
        for i, s in enumerate(self.slots):
            if s is None or s.request.deadline_s is None or \
                    s.request.submit_t is None:
                continue
            if now - s.request.submit_t > s.request.deadline_s:
                self._finish(i, "timeout")
                if self.session is not None:
                    self.session.emit("request_timeout",
                                      rid=s.request.rid, where="decode",
                                      step=self.step_count)

    def _admit(self):
        for i in range(len(self.slots)):
            if self.slots[i] is not None:
                continue
            if not self.queue or \
                    self.queue[0].arrival_step > self.step_count:
                break
            req = self.queue[0]
            row = None
            if self.paging is not None:
                row = self.paging.admit(req.prompt,
                                        session_id=req.session_id)
                if row is None:
                    # pool can't back the prompt right now even after
                    # the eviction ladder — leave the request queued
                    # and let running rows finish and free pages.
                    break
                self.queue.popleft()
                last_logits = self.engine.prefill(
                    i, req.prompt,
                    page_table=row.table(self.paging.pages_per_row),
                    start=row.start)
                self.paging.after_prefill(row, req.prompt)
            else:
                self.queue.popleft()
                last_logits = self.engine.prefill(i, req.prompt)
            first = self.engine.sample_first(last_logits)
            self.slots[i] = _Slot(
                request=req, bucket=self._bucket_for(req),
                next_pos=len(req.prompt), pending=first,
                generated=[first], admitted_step=self.step_count,
                paging=row)
            self._check_finished(i)

    # -- the decode loop ----------------------------------------------------

    def step(self):
        """Admit what the queue allows, then run one compiled decode
        step over the live rows. Returns True while there is (or will
        be) work left. With a speculative engine the "step" is a whole
        draft/verify round and rows advance by a VARIABLE number of
        tokens (their accepted length) — see :meth:`_spec_step`."""
        self._expire()
        self._admit()
        if getattr(self.engine, "speculative", None) is not None:
            return self._spec_step(self.engine.speculative)
        if self.paging is not None:
            # grow each live row's page mapping to cover this step's
            # write BEFORE building the tables; a row the pool can't
            # grow even after the eviction ladder is length-finished
            # (same truncation contract as a bucket edge).
            for i, s in enumerate(self.slots):
                if s is not None and \
                        not self.paging.ensure_position(s.paging,
                                                        s.next_pos):
                    self._finish(i, "length")
        active = [i for i, s in enumerate(self.slots) if s is not None]
        if not active:
            self.step_count += 1        # idle tick (open-loop gap)
            return bool(self.queue)
        mb = self.engine.max_batch
        tokens = np.zeros(mb, np.int32)
        positions = np.zeros(mb, np.int32)
        for i in active:
            tokens[i] = self.slots[i].pending
            positions[i] = self.slots[i].next_pos
        page_tables = None
        if self.paging is not None:
            page_tables = np.zeros((mb, self.paging.pages_per_row),
                                   np.int32)
            for i in active:
                page_tables[i] = self.slots[i].paging.table(
                    self.paging.pages_per_row)
        # fault-injection seams: a hard kill (SIGKILL — the process just
        # dies with admitted sessions' KV un-drained) and the soft
        # decode exception, both no-ops unless a harness armed them.
        fault_injection.maybe_kill("decode_step", self.step_count)
        fault_injection.maybe_fail_decode(self.step_count)
        t0 = time.perf_counter()
        if page_tables is None:
            next_tokens, _ = self.engine.decode(tokens, positions)
        else:
            next_tokens, _ = self.engine.decode(tokens, positions,
                                                page_tables=page_tables)
        wall = time.perf_counter() - t0
        self.step_count += 1
        for i in active:
            s = self.slots[i]
            s.next_pos += 1
            s.pending = int(next_tokens[i])
            s.generated.append(s.pending)
            self._check_finished(i)
        self._emit(len(active), wall)
        return bool(self.queue) or any(s is not None for s in self.slots)

    def _spec_step(self, spec):
        """One speculative round: j chained draft calls + one
        verify-accept call, then a per-row consume walk over the
        variable-length accepted blocks.

        Row discipline: a row must have ``k + 1`` slots of physical
        headroom before the round (the verify chunk writes positions
        ``next_pos..next_pos+k``; past ``max_seq`` the ring write's
        dynamic_update_slice would CLAMP the start and shift the whole
        chunk onto valid history, and a paged table lookup would clamp
        to the last page) — rows inside that margin length-finish now,
        the same truncation contract as a bucket edge, at most k tokens
        early. Paged rows also grow their mapping to cover every
        potentially-ACCEPTED write (``next_pos + j``); pad writes past
        the mapping land on the trash page by the PR 16 discipline."""
        k = spec.k
        j = spec.draft_len()
        for i, s in enumerate(self.slots):
            if s is not None and \
                    s.next_pos + k + 1 > self.engine.max_seq:
                self._finish(i, "length")
        if self.paging is not None:
            for i, s in enumerate(self.slots):
                if s is not None and not self.paging.ensure_span(
                        s.paging, s.next_pos, s.next_pos + j):
                    self._finish(i, "length")
        active = [i for i, s in enumerate(self.slots) if s is not None]
        if not active:
            self.step_count += 1        # idle tick (open-loop gap)
            return bool(self.queue)
        mb = self.engine.max_batch
        tokens = np.zeros(mb, np.int32)
        positions = np.zeros(mb, np.int32)
        for i in active:
            tokens[i] = self.slots[i].pending
            positions[i] = self.slots[i].next_pos
        page_tables = None
        if self.paging is not None:
            page_tables = np.zeros((mb, self.paging.pages_per_row),
                                   np.int32)
            for i in active:
                page_tables[i] = self.slots[i].paging.table(
                    self.paging.pages_per_row)
        fault_injection.maybe_kill("decode_step", self.step_count)
        fault_injection.maybe_fail_decode(self.step_count)
        # draft: j chained truncated-forward calls of ONE compiled
        # program (tokens/positions are data; j itself never reaches a
        # jit boundary)
        chunk = np.zeros((mb, k + 1), np.int32)
        chunk[:, 0] = tokens
        q_dists = None
        cur, cur_pos = tokens, positions.copy()
        t0 = time.perf_counter()
        for t in range(j):
            cur, q = spec.draft(cur, cur_pos, page_tables=page_tables)
            chunk[:, t + 1] = cur
            if q is not None:
                if q_dists is None:
                    q_dists = np.zeros((mb, k, q.shape[-1]), np.float32)
                q_dists[:, t] = q
            cur_pos = cur_pos + 1
        draft_wall = time.perf_counter() - t0
        # verify: one full-depth teacher-forced call over [B, k+1]
        pos_chunk = positions[:, None] + \
            np.arange(k + 1, dtype=np.int32)[None, :]
        draft_len = np.zeros(mb, np.int32)
        draft_len[active] = j
        t1 = time.perf_counter()
        acc, out = spec.verify(chunk, pos_chunk, draft_len,
                               q_dists=q_dists,
                               page_tables=page_tables)
        verify_wall = time.perf_counter() - t1
        self.step_count += 1
        # consume: walk each row's accepted block token by token so
        # eos / token budget / bucket edges bind MID-CHUNK exactly
        # where the non-speculative loop would have stopped
        emitted = accepted = 0
        for i in active:
            s = self.slots[i]
            accepted += int(acc[i])
            for t in range(int(acc[i]) + 1):
                s.next_pos += 1
                s.pending = int(out[i, t])
                s.generated.append(s.pending)
                emitted += 1
                self._check_finished(i)
                if self.slots[i] is None:
                    break
        spec.observe(len(active), len(active) * j, accepted, emitted)
        self._emit(len(active), draft_wall + verify_wall,
                   tokens=emitted,
                   spec_stats={"accepted_tokens": emitted,
                               "accepted_drafts": accepted,
                               "draft_tokens": len(active) * j,
                               "draft_len": j,
                               "draft_wall_s": draft_wall,
                               "verify_wall_s": verify_wall})
        return bool(self.queue) or any(s is not None for s in self.slots)

    def run(self, requests=None, max_steps=100000):
        """Drain ``requests`` (plus anything already queued) through the
        decode loop; returns the completions in finish order.

        Exhausting ``max_steps`` with work still in flight no longer
        returns silently: every live row finishes with the typed
        ``incomplete`` reason (keeping its generated-so-far tokens),
        every still-queued request records an empty ``incomplete``
        completion, and one ``scheduler_incomplete`` warning event makes
        the truncation visible in telemetry."""
        for r in requests or ():
            self.submit(r)
        steps = 0
        while steps < max_steps:
            if not self.step():
                break
            steps += 1
        live = [i for i, s in enumerate(self.slots) if s is not None]
        if live or self.queue:
            for i in live:
                self._finish(i, "incomplete")
            queued = len(self.queue)
            while self.queue:
                self._finish_unstarted(self.queue.popleft(), "incomplete")
            if self.session is not None:
                self.session.emit(
                    "scheduler_incomplete", level="warning",
                    step=self.step_count, max_steps=max_steps,
                    live_rows=len(live), queued=queued)
        return list(self.completions)

    # -- telemetry ----------------------------------------------------------

    def occupancy(self):
        live = sum(1 for s in self.slots if s is not None)
        return live / float(self.engine.max_batch)

    def _emit(self, batch, wall_s, tokens=None, spec_stats=None):
        if self.session is None:
            return
        occ = batch / float(self.engine.max_batch)
        tokens = batch if tokens is None else tokens
        extra = {}
        if spec_stats is not None:
            extra.update(spec_stats)
        if self.paging is not None:
            pg = self.paging
            extra = {"pages_free": pg.allocator.free_pages,
                     "pages_resident": pg.allocator.resident_pages,
                     "prefix_hits": pg.prefix_hits,
                     "prefix_misses": pg.prefix_misses,
                     "sessions_admitted": pg.sessions_admitted,
                     "sessions_parked_host": len(pg.host_store),
                     "cache_bytes": pg.page_bytes() * pg.engine.n_pages}
        self.session.emit(
            "decode_step", step=self.step_count, tokens=tokens,
            batch=batch, occupancy=occ, queue_depth=len(self.queue),
            wall_s=wall_s, **extra)
        reg = self.session.registry
        reg.histogram("decode_step_seconds",
                      help="host wall per compiled decode step").observe(
                          wall_s)
        reg.counter("decode_tokens_total",
                    help="tokens generated by decode steps").inc(tokens)
        if spec_stats is not None:
            reg.histogram(
                "accepted_tokens",
                help="tokens emitted per row per speculative round "
                     "(accepted drafts + correction)").observe(
                         spec_stats["accepted_tokens"] / float(batch))
            drafted = spec_stats["draft_tokens"]
            reg.gauge(
                "draft_efficiency",
                help="fraction of drafted tokens verify accepted").set(
                    spec_stats["accepted_drafts"] / float(drafted)
                    if drafted else 0.0)
        reg.gauge("decode_batch_occupancy",
                  help="live rows / max_batch").set(occ)
        reg.gauge("decode_queue_depth",
                  help="requests waiting for a cache row").set(
                      len(self.queue))
        if self.paging is not None:
            pg = self.paging
            reg.gauge("kv_pages_free",
                      help="unallocated pool pages").set(
                          pg.allocator.free_pages)
            reg.gauge("kv_pages_resident",
                      help="allocated pool pages (live + parked + "
                           "interned)").set(pg.allocator.resident_pages)
            hits = reg.counter("prefix_hits",
                               help="admissions that mapped shared "
                                    "radix pages")
            hits.inc(pg.prefix_hits - hits.value)
            misses = reg.counter("prefix_misses",
                                 help="admissions with no interned "
                                      "prefix")
            misses.inc(pg.prefix_misses - misses.value)
