"""In-program token sampling for the decode step.

With flash decode the attention math stops dominating the step, and
the old host-side sampling round trip (logits → host → argmax → next
token back to device) becomes the cost floor. This module keeps the
whole temperature / top-k / top-p pipeline INSIDE the compiled decode
program: the knobs are static Python values baked into the trace, and
randomness threads a JAX PRNG key through the program (key in, fresh
key out), so the serving loop stays at exactly the same two compiled
programs — sampling adds zero device round trips and zero jit cache
entries.

``temperature == 0.0`` is a static greedy path: plain argmax, bit-for-
bit the pre-sampling behavior, key passed through untouched (so a
greedy serve consumes no randomness and stays reproducible regardless
of seed).
"""

import jax
import jax.numpy as jnp

# Additive knockout for filtered logits: exp() underflows to exactly
# 0.0 in fp32, so a filtered token's probability is exactly zero.
_FILTERED = -1e30


def _apply_top_k(logits, top_k):
    """Keep the ``top_k`` largest logits per row; knock out the rest.
    ``top_k`` static; 0 (or >= vocab) disables the filter."""
    vocab = logits.shape[-1]
    if not top_k or top_k >= vocab:
        return logits
    kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
    return jnp.where(logits >= kth, logits, _FILTERED)


def _apply_top_p(logits, top_p):
    """Nucleus filter: keep the smallest set of tokens whose cumulative
    probability reaches ``top_p`` (static; 1.0 disables). The top token
    always survives (its exclusive cumulative mass is 0 < top_p)."""
    if top_p >= 1.0:
        return logits
    sorted_desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    # exclusive cumulative mass BEFORE each token: the nucleus is every
    # token whose predecessors haven't already covered top_p.
    cum = jnp.cumsum(probs, axis=-1) - probs
    keep = cum < top_p
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits >= cutoff, logits, _FILTERED)


def filtered_logits(logits, temperature, top_k=0, top_p=1.0):
    """The shared temperature → top-k → top-p pipeline as fp32 logits.

    This is the distribution :func:`sample_logits` actually samples
    from, exposed so speculative verify-accept can compute draft (q)
    and verify (p) probabilities under the IDENTICAL filters — the
    rejection-sampling accept rule is only distributionally correct
    when both sides use the same filtered support. ``temperature``
    must be > 0 (greedy has no distribution to filter).
    """
    if temperature <= 0.0:
        raise ValueError(
            f"filtered_logits needs temperature > 0, got {temperature}")
    scaled = logits.astype(jnp.float32) / float(temperature)
    scaled = _apply_top_k(scaled, int(top_k))
    return _apply_top_p(scaled, float(top_p))


def sample_logits(logits, key, temperature=0.0, top_k=0, top_p=1.0):
    """Sample next tokens from ``[..., vocab]`` logits.

    Returns ``(tokens int32 [...], new_key)``. ``temperature`` /
    ``top_k`` / ``top_p`` are STATIC Python numbers (they select the
    traced graph; changing them mid-serve would be a recompile — the
    engine pins them at construction). Filter order is the standard
    temperature → top-k → top-p, sampling via Gumbel trick
    (``jax.random.categorical``) over the filtered logits.
    """
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if temperature == 0.0:
        # static greedy path: no randomness consumed, key untouched
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), key
    key, sub = jax.random.split(key)
    scaled = filtered_logits(logits, temperature, top_k, top_p)
    tokens = jax.random.categorical(sub, scaled, axis=-1)
    return tokens.astype(jnp.int32), key
