"""Admission router for the serving fleet: one global queue, N replicas.

The router owns every request from submit to completion. Replicas
(`inference/fleet.py` — subprocess workers or in-process threads) are
pure executors: the router assigns each admitted request to the
least-loaded healthy replica, keeps its OWN authoritative copy of every
in-flight request, and health-checks replicas with the training
supervisor's classifier (`runtime/supervisor/supervisor.py:
classify_exit`/``heartbeat_verdict`` over the PR 12 ``hb-p<idx>.json``
files) plus a decode-step liveness deadline.

When a replica dies — crash, hang, or preemption — its in-flight
requests drain straight back to the router queue and redispatch to
healthy replicas as re-prefills after an exponential backoff. Greedy
decode is request-local deterministic (per-row KV, fixed compiled
shapes, replicas share seeded params), so a redispatched request's
tokens are identical to an uninterrupted run: callers observe
exactly-once COMPLETION on top of at-least-once EXECUTION, with the
retry count recorded on the completion (``redispatched``/``restarts``).

Bounds, so nothing grows or retries forever:

- ``max_redispatch`` — a request drained more times than this finishes
  with the ``aborted`` reason (and :class:`RequestAbortedError` when
  ``raise_on_abort``), emitted as a durable ``request_aborted`` event.
- ``max_queue_depth`` — per-replica in-flight bound; when every healthy
  replica is at it the router DEFERS dispatch (``fleet_defer``).
- ``max_pending`` — global admission bound; a submit past it is SHED
  with the ``shed`` reason (``fleet_shed``) instead of queueing
  unboundedly.
- ``deadline_s``/``queue_timeout_s`` (per request) — enforced on the
  router queue here and inside each replica's scheduler; either way the
  request finishes with the typed ``timeout`` reason.
"""

import collections
import dataclasses
import time
from typing import Dict, List, Optional


class RequestAbortedError(RuntimeError):
    """A request exhausted its redispatch budget: every attempt landed
    on a replica that died before completing it."""

    def __init__(self, rid, redispatched):
        self.rid = rid
        self.redispatched = redispatched
        super().__init__(
            f"request {rid!r} aborted after {redispatched} "
            f"redispatches (replica died every time)")


@dataclasses.dataclass
class _Queued:
    request: object                 # scheduler.Request
    not_before: float = 0.0         # redispatch backoff gate
    meta: object = None             # HandoffMeta dict (decode tier)


@dataclasses.dataclass
class FleetResult:
    completions: List[dict]         # finish order, one per request
    ok: bool
    replicas: int
    replicas_dead: int
    redispatched_total: int
    aborted: int
    shed: int
    defers: int
    timeouts: int
    stats: List[dict]               # surviving replicas' final stats
    latency_s: Dict[str, Optional[float]]   # p50/p95/p99/max

    def by_rid(self):
        return {c["rid"]: c for c in self.completions}


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


class FleetRouter:
    """See the module docstring. ``replicas`` are started handles from
    `inference/fleet.py` (anything with submit/poll/check/stop/kill)."""

    def __init__(self, replicas, session=None,
                 max_redispatch=2,
                 max_queue_depth=8,
                 max_pending=None,
                 backoff_base_s=0.05,
                 backoff_cap_s=2.0,
                 poll_interval_s=0.002,
                 raise_on_abort=False):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas = list(replicas)
        self.session = session
        self.max_redispatch = int(max_redispatch)
        self.max_queue_depth = int(max_queue_depth)
        self.max_pending = (int(max_pending) if max_pending is not None
                            else None)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.poll_interval_s = float(poll_interval_s)
        self.raise_on_abort = bool(raise_on_abort)

        self.queue = collections.deque()        # _Queued
        self.assigned = {r.index: {} for r in self.replicas}
        self.dead = {}                          # index -> cause
        self.completions = []
        self.completed_rids = set()             # exactly-once gate
        self.redispatched_total = 0
        self.aborted = 0
        self.shed = 0
        self.defers = 0
        self.timeouts = 0
        self._deferring = False
        self._recovering = {}   # index -> (t_detect, {rids not yet out})
        self._submit_t = {}     # rid -> wall-clock submit (latency)

    # -- telemetry -----------------------------------------------------

    def _emit(self, event, **fields):
        if self.session is not None:
            try:
                self.session.emit(event, **fields)
            except Exception:       # telemetry never kills the fleet
                pass

    # -- submission ----------------------------------------------------

    def _outstanding(self):
        return len(self.queue) + sum(
            len(v) for v in self.assigned.values())

    def submit(self, request):
        """Admit one request, or shed it at the global pending bound."""
        if request.rid in self._submit_t:
            raise ValueError(f"duplicate rid {request.rid!r}")
        self._submit_t[request.rid] = time.monotonic()
        if request.submit_t is None:
            request.submit_t = self._submit_t[request.rid]
        if self.max_pending is not None and \
                self._outstanding() >= self.max_pending:
            self.shed += 1
            self._record(request, tokens=[], finish_reason="shed",
                         replica=None)
            self._emit("fleet_shed", rid=request.rid,
                       outstanding=self._outstanding(),
                       max_pending=self.max_pending)
            return False
        self.queue.append(_Queued(request))
        return True

    # -- completion plumbing -------------------------------------------

    def _record(self, request, tokens, finish_reason, replica,
                extra=None):
        """One exactly-once completion record for ``request``."""
        if request.rid in self.completed_rids:
            return
        self.completed_rids.add(request.rid)
        now = time.monotonic()
        comp = {
            "rid": request.rid, "prompt_len": len(request.prompt),
            "tokens": list(tokens), "finish_reason": finish_reason,
            "bucket": 0, "slot": -1, "steps": 0,
            "prefix_hit": False, "resumed": False,
            "prefill_chunks": 0, "prefill_chunks_skipped": 0,
            "redispatched": request.redispatched,
            "restarts": request.restarts,
            "replica": replica,
            "latency_s": now - self._submit_t[request.rid],
        }
        if extra:
            comp.update(extra)
        self.completions.append(comp)
        self._emit("request_complete", rid=comp["rid"], replica=replica,
                   finish_reason=finish_reason, tokens=len(comp["tokens"]),
                   latency_s=round(comp["latency_s"], 6),
                   redispatched=comp["redispatched"],
                   restarts=comp["restarts"],
                   # disaggregated runs tag completions with the tier
                   # and the ttft/queue-wait split for the metrics CLI
                   **{k: comp[k] for k in
                      ("tier", "ttft_s", "decode_queue_wait_s")
                      if k in comp})

    def _collect(self):
        """Drain every live replica's finished completions."""
        for rep in self.replicas:
            if rep.index in self.dead:
                continue
            for c in rep.poll():
                req = self.assigned[rep.index].pop(c["rid"], None)
                if req is None or c["rid"] in self.completed_rids:
                    continue    # duplicate / already completed elsewhere
                self._record(
                    req, tokens=c["tokens"],
                    finish_reason=c["finish_reason"], replica=rep.index,
                    extra={k: c[k] for k in
                           ("bucket", "slot", "steps", "prefix_hit",
                            "resumed", "prefill_chunks",
                            "prefill_chunks_skipped") if k in c})

    # -- health / drain / redispatch -----------------------------------

    def _healthy(self):
        return [r for r in self.replicas if r.index not in self.dead]

    def _check_health(self, now):
        for rep in self.replicas:
            if rep.index in self.dead:
                continue
            cause = rep.check(now)
            if cause is None:
                continue
            self.dead[rep.index] = cause
            in_flight = self.assigned[rep.index]
            self._emit("replica_dead", replica=rep.index, cause=cause,
                       in_flight=len(in_flight))
            rep.reap()
            self._drain(rep.index, now)

    def _drain(self, index, now):
        """Requeue a dead replica's in-flight requests (bounded retry
        with exponential backoff), aborting the over-budget ones."""
        drained = self.assigned[index]
        self.assigned[index] = {}
        recovering = set()
        for rid, req in drained.items():
            req.redispatched += 1
            req.restarts += 1
            if req.redispatched > self.max_redispatch or \
                    not self._healthy():
                self.aborted += 1
                self._record(req, tokens=[], finish_reason="aborted",
                             replica=index)
                self._emit("request_aborted", rid=rid,
                           redispatched=req.redispatched,
                           last_replica=index)
                if self.raise_on_abort:
                    raise RequestAbortedError(rid, req.redispatched)
                continue
            backoff = min(self.backoff_cap_s, self.backoff_base_s *
                          (2 ** (req.redispatched - 1)))
            req.arrival_step = 0    # re-prefill immediately on arrival
            self.queue.append(_Queued(req, not_before=now + backoff))
            recovering.add(rid)
            self.redispatched_total += 1
            self._emit("fleet_redispatch", rid=rid, from_replica=index,
                       redispatched=req.redispatched,
                       backoff_s=round(backoff, 4))
        if recovering:
            self._recovering[index] = (now, recovering)
        else:
            self._emit("replica_recovered", replica=index,
                       time_to_recover_s=0.0, redispatched=0)

    def _note_dispatched(self, rid, now):
        """Close a replica's recovery window once its last drained
        request is back on a healthy replica."""
        for index, (t_detect, rids) in list(self._recovering.items()):
            rids.discard(rid)
            if not rids:
                del self._recovering[index]
                self._emit("replica_recovered", replica=index,
                           time_to_recover_s=round(now - t_detect, 6),
                           redispatched=self.redispatched_total)

    # -- deadlines on the router queue ---------------------------------

    def _expire(self, now):
        if not self.queue:
            return
        keep = collections.deque()
        for item in self.queue:
            req = item.request
            waited = now - req.submit_t if req.submit_t is not None \
                else 0.0
            expired = ((req.queue_timeout_s is not None and
                        waited > req.queue_timeout_s) or
                       (req.deadline_s is not None and
                        waited > req.deadline_s))
            if expired:
                self.timeouts += 1
                self._record(req, tokens=[], finish_reason="timeout",
                             replica=None)
                self._emit("request_timeout", rid=req.rid,
                           where="router_queue",
                           waited_s=round(waited, 6))
            else:
                keep.append(item)
        self.queue = keep

    # -- dispatch ------------------------------------------------------

    def _dispatch(self, now):
        ready = [q for q in self.queue if q.not_before <= now]
        if not ready:
            return
        dispatched = []
        for item in ready:
            candidates = [r for r in self._healthy()
                          if len(self.assigned[r.index])
                          < self.max_queue_depth]
            if not candidates:
                if not self._deferring:
                    self.defers += 1
                    self._deferring = True
                    self._emit("fleet_defer", queued=len(self.queue),
                               max_queue_depth=self.max_queue_depth)
                break
            self._deferring = False
            rep = min(candidates,
                      key=lambda r: (len(self.assigned[r.index]),
                                     r.index))
            req = item.request
            self.assigned[rep.index][req.rid] = req
            rep.submit(req)
            dispatched.append(item)
            self._emit("fleet_dispatch", rid=req.rid, replica=rep.index,
                       redispatched=req.redispatched,
                       queue_depth=len(self.assigned[rep.index]))
            self._note_dispatched(req.rid, now)
        if dispatched:
            gone = set(id(d) for d in dispatched)
            self.queue = collections.deque(
                q for q in self.queue if id(q) not in gone)

    # -- the drive loop ------------------------------------------------

    def run(self, requests=(), timeout_s=120.0):
        """Drive every request (plus anything already submitted) to a
        completion, draining and redispatching around replica deaths.
        Returns a :class:`FleetResult`; ``ok`` means every submitted
        request completed with a generative reason (no aborts, sheds,
        timeouts, or fleet-level truncation)."""
        for r in requests:
            self.submit(r)
        t0 = time.monotonic()
        while self.queue or any(self.assigned[r.index]
                                for r in self._healthy()):
            now = time.monotonic()
            self._collect()
            self._check_health(now)
            self._expire(now)
            self._dispatch(now)
            if not self._healthy() and (
                    self.queue or any(self.assigned.values())):
                # every replica is dead: drain whatever is left into
                # aborted completions rather than spinning forever
                for rep in self.replicas:
                    if self.assigned[rep.index]:
                        self._drain(rep.index, now)
                while self.queue:
                    req = self.queue.popleft().request
                    self.aborted += 1
                    self._record(req, tokens=[],
                                 finish_reason="aborted", replica=None)
                    self._emit("request_aborted", rid=req.rid,
                               redispatched=req.redispatched,
                               last_replica=None)
                break
            if time.monotonic() - t0 > timeout_s:
                for rep in self._healthy():
                    for rid, req in list(
                            self.assigned[rep.index].items()):
                        self._record(req, tokens=[],
                                     finish_reason="incomplete",
                                     replica=rep.index)
                    self.assigned[rep.index] = {}
                while self.queue:
                    self._record(self.queue.popleft().request,
                                 tokens=[], finish_reason="incomplete",
                                 replica=None)
                self._emit("scheduler_incomplete", level="warning",
                           where="fleet", timeout_s=timeout_s)
                break
            time.sleep(self.poll_interval_s)
        self._collect()
        return self._finish()

    def _finish(self):
        stats = []
        for rep in self._healthy():
            st = rep.stop()
            if st is not None:
                st = dict(st, replica=rep.index)
                stats.append(st)
                self._emit("replica_stats", **st)
        lat = sorted(c["latency_s"] for c in self.completions
                     if c.get("latency_s") is not None)
        latency = {"p50": _percentile(lat, 0.50),
                   "p95": _percentile(lat, 0.95),
                   "p99": _percentile(lat, 0.99),
                   "max": lat[-1] if lat else None}
        generative = ("max_new_tokens", "eos", "length")
        ok = (len(self.completions) == len(self._submit_t) and
              all(c["finish_reason"] in generative
                  for c in self.completions))
        result = FleetResult(
            completions=list(self.completions), ok=ok,
            replicas=len(self.replicas), replicas_dead=len(self.dead),
            redispatched_total=self.redispatched_total,
            aborted=self.aborted, shed=self.shed, defers=self.defers,
            timeouts=self.timeouts, stats=stats, latency_s=latency)
        self._emit("fleet_done", ok=ok,
                   requests=len(self._submit_t),
                   completions=len(self.completions),
                   replicas=len(self.replicas),
                   replicas_dead=len(self.dead),
                   dead_causes=dict(self.dead),
                   redispatched_total=self.redispatched_total,
                   aborted=self.aborted, shed=self.shed,
                   defers=self.defers, timeouts=self.timeouts,
                   latency_p99_s=latency["p99"])
        return result


@dataclasses.dataclass
class DisaggResult:
    """Outcome of a disaggregated run: the fleet-level fields plus the
    handoff ledger and per-tier stats/latency splits."""
    completions: List[dict]
    ok: bool
    prefill_replicas: int
    decode_replicas: int
    replicas_dead: int
    dead_by_tier: Dict[str, int]
    redispatched_total: int
    aborted: int
    shed: int
    defers: int
    timeouts: int
    handoffs: int
    handoff_bytes: int
    handoff_corrupt: int
    resumed_from_park: int
    stats: List[dict]               # surviving replicas, tier-tagged
    latency_s: Dict[str, Optional[float]]
    ttft_s: Dict[str, Optional[float]]

    def by_rid(self):
        return {c["rid"]: c for c in self.completions}


class DisaggRouter(FleetRouter):
    """Tiered admission router for disaggregated serving (ISSUE 20).

    New requests dispatch to the PREFILL tier; a worker's ``prefilled``
    output moves the request (now pure admission metadata — the KV
    pages travel through the handoff store) onto the DECODE tier's
    queue, and only the decode tier produces its completion. Both
    tiers reuse the fleet machinery unchanged: least-loaded dispatch
    under ``max_queue_depth``, supervisor-classified health checks,
    exponential-backoff redispatch bounded by ``max_redispatch``,
    exactly-once completion records.

    Tier-aware recovery is the one new rule: a dead PREFILL worker's
    in-flight requests simply re-prefill elsewhere (nothing durable was
    lost), while a dead DECODE worker's requests re-prefill ONLY when
    their pages weren't parked — a durable handoff (``store.parked``)
    re-enters the decode queue and resumes from the parked snapshot. A
    CRC-rotted handoff (``handoff_corrupt``) always cold re-prefills:
    never serve from a rotten page.
    """

    def __init__(self, prefill_replicas, decode_replicas, store,
                 session=None, **kwargs):
        if not prefill_replicas or not decode_replicas:
            raise ValueError("disaggregation needs >= 1 replica per tier")
        super().__init__(list(prefill_replicas) + list(decode_replicas),
                         session=session, **kwargs)
        self.store = store
        self.tier_of = {}
        for r in prefill_replicas:
            self.tier_of[r.index] = "prefill"
        for r in decode_replicas:
            if r.index in self.tier_of:
                raise ValueError(
                    f"replica index {r.index} appears in both tiers")
            self.tier_of[r.index] = "decode"
        self.prefill_replicas = list(prefill_replicas)
        self.decode_replicas = list(decode_replicas)
        self.decode_queue = collections.deque()     # _Queued with meta
        self.handoffs = 0
        self.handoff_bytes = 0
        self.handoff_corrupt = 0
        self.resumed_from_park = 0
        self._metas = {}            # rid -> handoff meta dict (decode leg)
        self._extras = {}           # rid -> prefill-side completion fields
        self._prefilled_t = {}      # rid -> monotonic handoff time
        self._dispatch_t = {}       # rid -> monotonic prefill dispatch
        self.ttft = {}              # rid -> seconds to first token

    # -- tier plumbing -------------------------------------------------

    def _tier_healthy(self, tier):
        return [r for r in self.replicas
                if r.index not in self.dead and
                self.tier_of[r.index] == tier]

    def _outstanding(self):
        return (len(self.queue) + len(self.decode_queue) +
                sum(len(v) for v in self.assigned.values()))

    def _requeue_prefill(self, req, now, why):
        """Route a request back to the prefill tier (cold re-prefill),
        bounded exactly like a fleet redispatch."""
        req.restarts += 1
        if req.restarts > self.max_redispatch + 1 or \
                not self._tier_healthy("prefill"):
            self.aborted += 1
            self._record(req, tokens=[], finish_reason="aborted",
                         replica=None)
            self._emit("request_aborted", rid=req.rid,
                       redispatched=req.redispatched, why=why)
            if self.raise_on_abort:
                raise RequestAbortedError(req.rid, req.redispatched)
            return
        self._metas.pop(req.rid, None)
        self.queue.append(_Queued(req, not_before=now))
        self._emit("disagg_reprefill", rid=req.rid, why=why,
                   restarts=req.restarts)

    # -- collection ----------------------------------------------------

    def _collect(self):
        now = time.monotonic()
        for rep in self.replicas:
            if rep.index in self.dead:
                continue
            tier = self.tier_of[rep.index]
            for c in rep.poll():
                kind = c.get("kind", "completion")
                rid = c["rid"]
                req = self.assigned[rep.index].pop(rid, None)
                if req is None or rid in self.completed_rids:
                    if kind != "completion":
                        self.store.drop(rid)
                    continue
                if kind == "prefilled":
                    self.handoffs += 1
                    self.handoff_bytes += c.get("handoff_bytes", 0)
                    self._metas[rid] = c["handoff"]
                    self._extras[rid] = {
                        k: c[k] for k in
                        ("prefix_hit", "prefill_chunks",
                         "prefill_chunks_skipped", "handoff_bytes")
                        if k in c}
                    self._extras[rid]["prefill_replica"] = rep.index
                    self._prefilled_t[rid] = now
                    ttft = now - req.submit_t \
                        if req.submit_t is not None else None
                    self.ttft[rid] = ttft
                    qwait = None
                    if rid in self._dispatch_t and \
                            req.submit_t is not None:
                        qwait = self._dispatch_t[rid] - req.submit_t
                    self._emit(
                        "request_prefilled", rid=rid, replica=rep.index,
                        tier="prefill",
                        ttft_s=round(ttft, 6) if ttft is not None
                        else None,
                        queue_wait_s=round(qwait, 6)
                        if qwait is not None else None,
                        handoff_bytes=c.get("handoff_bytes", 0),
                        parked=bool(c["handoff"].get("parked")))
                    self.decode_queue.append(
                        _Queued(req, meta=c["handoff"]))
                elif kind in ("handoff_corrupt", "handoff_missing"):
                    self.handoff_corrupt += 1
                    self.store.drop(rid)
                    self._emit("handoff_corrupt", level="warning",
                               rid=rid, replica=rep.index, kind=kind)
                    self._requeue_prefill(req, now, why=kind)
                elif kind == "handoff_error":
                    self.aborted += 1
                    self.store.drop(rid)
                    self._record(req, tokens=[],
                                 finish_reason="handoff_error",
                                 replica=rep.index,
                                 extra={"error": c.get("error")})
                else:
                    extra = {k: c[k] for k in
                             ("bucket", "slot", "steps", "prefix_hit",
                              "resumed", "prefill_chunks",
                              "prefill_chunks_skipped") if k in c}
                    extra.update(self._extras.pop(rid, {}))
                    extra["tier"] = tier
                    if rid in self.ttft and self.ttft[rid] is not None:
                        extra["ttft_s"] = self.ttft[rid]
                    if rid in self._prefilled_t:
                        extra.setdefault("decode_queue_wait_s", None)
                    self._record(req, tokens=c["tokens"],
                                 finish_reason=c["finish_reason"],
                                 replica=rep.index, extra=extra)
                    self.store.drop(rid)

    # -- tier-aware drain ----------------------------------------------

    def _drain(self, index, now):
        tier = self.tier_of.get(index, "prefill")
        if tier == "prefill":
            super()._drain(index, now)
            return
        drained = self.assigned[index]
        self.assigned[index] = {}
        recovering = set()
        for rid, req in drained.items():
            req.redispatched += 1
            req.restarts += 1
            if req.redispatched > self.max_redispatch:
                self.aborted += 1
                self._record(req, tokens=[], finish_reason="aborted",
                             replica=index)
                self._emit("request_aborted", rid=rid,
                           redispatched=req.redispatched,
                           last_replica=index)
                if self.raise_on_abort:
                    raise RequestAbortedError(rid, req.redispatched)
                continue
            backoff = min(self.backoff_cap_s, self.backoff_base_s *
                          (2 ** (req.redispatched - 1)))
            self.redispatched_total += 1
            recovering.add(rid)
            if self.store.parked(rid) and rid in self._metas:
                # durable handoff: the parked snapshot survives the
                # worker, so the request resumes on another decode
                # worker without re-running prefill.
                self.resumed_from_park += 1
                self.decode_queue.append(_Queued(
                    req, not_before=now + backoff,
                    meta=self._metas[rid]))
                self._emit("fleet_redispatch", rid=rid,
                           from_replica=index, tier="decode",
                           resumed_from_park=True,
                           redispatched=req.redispatched,
                           backoff_s=round(backoff, 4))
            else:
                # in-process handoff was consumed with the worker (or
                # the snapshot is gone): only the prompt survives, so
                # the request re-prefills from scratch.
                self.store.drop(rid)
                self._metas.pop(rid, None)
                req.arrival_step = 0
                self.queue.append(_Queued(req, not_before=now + backoff))
                self._emit("fleet_redispatch", rid=rid,
                           from_replica=index, tier="decode",
                           resumed_from_park=False,
                           redispatched=req.redispatched,
                           backoff_s=round(backoff, 4))
        if recovering:
            self._recovering[index] = (now, recovering)
        else:
            self._emit("replica_recovered", replica=index,
                       time_to_recover_s=0.0, redispatched=0)

    # -- tiered dispatch -----------------------------------------------

    def _dispatch_tier(self, queue, tier, now):
        ready = [q for q in queue if q.not_before <= now]
        if not ready:
            return queue
        dispatched = []
        for item in ready:
            candidates = [r for r in self._tier_healthy(tier)
                          if len(self.assigned[r.index])
                          < self.max_queue_depth]
            if not candidates:
                if not self._deferring:
                    self.defers += 1
                    self._deferring = True
                    self._emit("fleet_defer", tier=tier,
                               queued=len(queue),
                               max_queue_depth=self.max_queue_depth)
                break
            self._deferring = False
            rep = min(candidates,
                      key=lambda r: (len(self.assigned[r.index]),
                                     r.index))
            req = item.request
            self.assigned[rep.index][req.rid] = req
            if tier == "decode":
                rep.submit(req, item.meta)
                if req.rid in self._prefilled_t:
                    # `now` predates _collect's stamp when the handoff
                    # and the dispatch land in the same loop tick
                    wait = max(0.0, now - self._prefilled_t[req.rid])
                    self._extras.setdefault(req.rid, {})[
                        "decode_queue_wait_s"] = wait
            else:
                self._dispatch_t[req.rid] = now
                rep.submit(req)
            dispatched.append(item)
            self._emit("fleet_dispatch", rid=req.rid, tier=tier,
                       replica=rep.index,
                       redispatched=req.redispatched,
                       queue_depth=len(self.assigned[rep.index]))
            self._note_dispatched(req.rid, now)
        if dispatched:
            gone = set(id(d) for d in dispatched)
            return collections.deque(
                q for q in queue if id(q) not in gone)
        return queue

    def _dispatch(self, now):
        self.queue = self._dispatch_tier(self.queue, "prefill", now)
        self.decode_queue = self._dispatch_tier(
            self.decode_queue, "decode", now)

    def _abort_queue(self, queue, why):
        n = 0
        while queue:
            req = queue.popleft().request
            if req.rid in self.completed_rids:
                continue
            self.aborted += 1
            self._record(req, tokens=[], finish_reason="aborted",
                         replica=None)
            self._emit("request_aborted", rid=req.rid,
                       redispatched=req.redispatched, why=why)
            n += 1
        return n

    # -- the drive loop ------------------------------------------------

    def run(self, requests=(), timeout_s=120.0):
        for r in requests:
            self.submit(r)
        t0 = time.monotonic()
        while self.queue or self.decode_queue or any(
                self.assigned[r.index] for r in self._healthy()):
            now = time.monotonic()
            self._collect()
            self._check_health(now)
            self._expire(now)
            self._dispatch(now)
            if not self._tier_healthy("prefill") and self.queue:
                self._abort_queue(self.queue, "prefill_tier_dead")
            if not self._tier_healthy("decode") and self.decode_queue:
                self._abort_queue(self.decode_queue, "decode_tier_dead")
            if not self._healthy():
                for rep in self.replicas:
                    if self.assigned[rep.index]:
                        self._drain(rep.index, now)
                self._abort_queue(self.queue, "fleet_dead")
                self._abort_queue(self.decode_queue, "fleet_dead")
                break
            if time.monotonic() - t0 > timeout_s:
                for rep in self._healthy():
                    for rid, req in list(
                            self.assigned[rep.index].items()):
                        self._record(req, tokens=[],
                                     finish_reason="incomplete",
                                     replica=rep.index)
                    self.assigned[rep.index] = {}
                for queue in (self.queue, self.decode_queue):
                    while queue:
                        self._record(queue.popleft().request,
                                     tokens=[],
                                     finish_reason="incomplete",
                                     replica=None)
                self._emit("scheduler_incomplete", level="warning",
                           where="disagg_fleet", timeout_s=timeout_s)
                break
            time.sleep(self.poll_interval_s)
        self._collect()
        return self._finish()

    def _finish(self):
        stats = []
        for rep in self._healthy():
            st = rep.stop()
            if st is not None:
                st = dict(st, replica=rep.index,
                          tier=self.tier_of[rep.index])
                stats.append(st)
                self._emit("replica_stats", **st)
        lat = sorted(c["latency_s"] for c in self.completions
                     if c.get("latency_s") is not None)
        latency = {"p50": _percentile(lat, 0.50),
                   "p95": _percentile(lat, 0.95),
                   "p99": _percentile(lat, 0.99),
                   "max": lat[-1] if lat else None}
        tt = sorted(v for v in self.ttft.values() if v is not None)
        ttft = {"p50": _percentile(tt, 0.50),
                "p95": _percentile(tt, 0.95),
                "p99": _percentile(tt, 0.99),
                "max": tt[-1] if tt else None}
        dead_by_tier = {"prefill": 0, "decode": 0}
        for idx in self.dead:
            dead_by_tier[self.tier_of[idx]] += 1
        generative = ("max_new_tokens", "eos", "length")
        ok = (len(self.completions) == len(self._submit_t) and
              all(c["finish_reason"] in generative
                  for c in self.completions))
        result = DisaggResult(
            completions=list(self.completions), ok=ok,
            prefill_replicas=len(self.prefill_replicas),
            decode_replicas=len(self.decode_replicas),
            replicas_dead=len(self.dead), dead_by_tier=dead_by_tier,
            redispatched_total=self.redispatched_total,
            aborted=self.aborted, shed=self.shed, defers=self.defers,
            timeouts=self.timeouts, handoffs=self.handoffs,
            handoff_bytes=self.handoff_bytes,
            handoff_corrupt=self.handoff_corrupt,
            resumed_from_park=self.resumed_from_park,
            stats=stats, latency_s=latency, ttft_s=ttft)
        self._emit("disagg_done", ok=ok,
                   requests=len(self._submit_t),
                   completions=len(self.completions),
                   prefill_replicas=len(self.prefill_replicas),
                   decode_replicas=len(self.decode_replicas),
                   replicas_dead=len(self.dead),
                   dead_by_tier=dead_by_tier,
                   dead_causes=dict(self.dead),
                   redispatched_total=self.redispatched_total,
                   handoffs=self.handoffs,
                   handoff_bytes=self.handoff_bytes,
                   handoff_corrupt=self.handoff_corrupt,
                   resumed_from_park=self.resumed_from_park,
                   aborted=self.aborted, shed=self.shed,
                   defers=self.defers, timeouts=self.timeouts,
                   latency_p99_s=latency["p99"],
                   ttft_p99_s=ttft["p99"])
        return result
