"""Serving fleet replicas: the executors behind the admission router.

Two interchangeable backends behind one duck-typed handle contract
(``index``/``start``/``submit``/``poll``/``check``/``stop``/``kill``/
``reap``), so `inference/router.py:FleetRouter` never knows which it is
driving:

- :class:`ProcessReplica` — a real subprocess running
  `inference/fleet_worker.py` under the ``ds_tpu_run`` supervisor's env
  contract (``DS_TPU_RUN_PROCESS_INDEX`` / ``DS_TPU_RUN_RESTART_COUNT``
  / done markers), speaking JSONL over stdin/stdout and writing the
  PR 12 ``hb-p<idx>.json`` heartbeat files. Death classification is the
  supervisor's own: ``classify_exit`` on the exit code + done marker,
  ``heartbeat_verdict`` on the heartbeat file. This is the backend the
  SIGKILL soak and the CI fleet smoke run — the process genuinely dies.
- :class:`ThreadReplica` — an in-process thread around any engine the
  ``engine_factory`` returns (including the no-jax ``StubEngine`` the
  unit tests use), with the same lifecycle semantics simulated:
  ``kill()`` stops the loop mid-flight without reporting (a crash),
  ``preempt()`` finishes the current decode step and exits cleanly
  without its done flag (a preemption), an unhandled scheduler
  exception (e.g. the injected decode fault) is a crash, and a stalled
  loop past ``step_timeout_s`` reads as a hang. Fast enough for tier-1.

Both report completions as plain dicts (:func:`completion_dict`) so the
router's bookkeeping is backend-agnostic.
"""

import collections
import json
import os
import subprocess
import sys
import threading
import time

from deepspeed_tpu.runtime.supervisor.state import CAUSE_HANG
from deepspeed_tpu.runtime.supervisor.supervisor import (
    classify_exit,
    done_path,
    heartbeat_verdict,
)
from deepspeed_tpu.telemetry.watchdog import heartbeat_path

COMPLETION_FIELDS = (
    "rid", "prompt_len", "tokens", "finish_reason", "bucket", "slot",
    "steps", "prefix_hit", "resumed", "prefill_chunks",
    "prefill_chunks_skipped", "redispatched", "restarts")

REQUEST_FIELDS = (
    "rid", "prompt", "max_new_tokens", "eos_id", "arrival_step",
    "session_id", "deadline_s", "queue_timeout_s", "redispatched",
    "restarts")


def completion_dict(c):
    """A scheduler ``Completion`` as the wire/router dict."""
    return {k: getattr(c, k) for k in COMPLETION_FIELDS}


def request_dict(r):
    """A scheduler ``Request`` as the wire dict. ``submit_t`` stays
    home: monotonic clocks don't travel between processes — the router
    enforces the global deadline, the worker re-clocks its own."""
    return {k: getattr(r, k) for k in REQUEST_FIELDS}


class ThreadReplica:
    """In-process replica: one scheduler loop on a daemon thread."""

    def __init__(self, index, engine_factory, step_timeout_s=None):
        self.index = int(index)
        self.engine_factory = engine_factory
        self.step_timeout_s = step_timeout_s
        self._inbox = collections.deque()
        self._outbox = collections.deque()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._kill = threading.Event()
        self._preempt = threading.Event()
        self._done_flag = False         # the done-marker analogue
        self._preempted = False
        self._error = None
        self._stats = None
        self._last_progress = time.monotonic()
        self._busy = False
        self._thread = None
        self._reported = 0

    # -- lifecycle -----------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name=f"fleet-replica-{self.index}",
            daemon=True)
        self._thread.start()
        return self

    def _run(self):
        try:
            from deepspeed_tpu.inference.scheduler import (
                ContinuousBatchingScheduler)
            engine = self.engine_factory()
            sched = ContinuousBatchingScheduler(engine)
            while True:
                if self._kill.is_set():
                    return          # SIGKILL analogue: vanish mid-flight
                with self._lock:
                    while self._inbox:
                        sched.submit(self._inbox.popleft())
                has_work = bool(sched.queue) or any(
                    s is not None for s in sched.slots)
                if has_work:
                    self._busy = True
                    sched.step()    # fault probes live inside
                    self._last_progress = time.monotonic()
                    self._busy = False
                with self._lock:
                    new = sched.completions[self._reported:]
                    self._reported = len(sched.completions)
                    for c in new:
                        self._outbox.append(completion_dict(c))
                if self._preempt.is_set():
                    # SIGTERM analogue: current step finished above;
                    # report completed-so-far and exit WITHOUT the done
                    # flag, so the router classifies a preemption.
                    self._preempted = True
                    return
                if not has_work:
                    if self._stop.is_set():
                        counts = engine.compile_counts() if hasattr(
                            engine, "compile_counts") else {}
                        self._stats = {
                            "compile_counts": counts,
                            "steps": sched.step_count,
                            "completed": len(sched.completions),
                        }
                        self._done_flag = True
                        return
                    time.sleep(0.0005)
        except BaseException as e:      # noqa: BLE001 - crash envelope
            self._error = e

    # -- router-facing handle ------------------------------------------

    def submit(self, request):
        with self._lock:
            self._inbox.append(request)

    def poll(self):
        with self._lock:
            out = list(self._outbox)
            self._outbox.clear()
        return out

    def check(self, now=None):
        """Failure cause, or None while healthy — mirroring the
        supervisor's classifier over thread state: a dead thread's
        "exit code" is its error/done flag, a stalled busy loop past
        ``step_timeout_s`` is a hang."""
        now = time.monotonic() if now is None else now
        if self._thread is not None and not self._thread.is_alive():
            rc = 1 if (self._error is not None or
                       self._kill.is_set()) else 0
            return classify_exit(rc, self._done_flag)
        if self.step_timeout_s is not None and self._busy and \
                now - self._last_progress > self.step_timeout_s:
            return CAUSE_HANG
        return None

    def stop(self, timeout=30.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        return self._stats

    def kill(self):
        self._kill.set()

    def preempt(self):
        self._preempt.set()

    def reap(self):
        """Post-death cleanup (pipes for processes; nothing here)."""


class TierThreadReplica(ThreadReplica):
    """In-process TIER replica for disaggregated serving: the factory
    returns a `inference/disagg.py` ``PrefillWorker``/``DecodeWorker``
    instead of an engine, and the loop drives its submit/step/
    drain_outputs surface. Lifecycle semantics are ThreadReplica's
    exactly — kill vanishes mid-flight, preempt finishes the step and
    exits without the done flag, an unhandled exception is a crash —
    so the DisaggRouter health-checks both backends identically."""

    def submit(self, request, meta=None):
        with self._lock:
            self._inbox.append((request, meta))

    def _run(self):
        try:
            worker = self.engine_factory()
            while True:
                if self._kill.is_set():
                    return          # SIGKILL analogue: vanish mid-flight
                with self._lock:
                    while self._inbox:
                        req, meta = self._inbox.popleft()
                        worker.submit(req, meta)
                has_work = worker.has_work
                if has_work:
                    self._busy = True
                    worker.step()   # fault probes live inside
                    self._last_progress = time.monotonic()
                    self._busy = False
                with self._lock:
                    self._outbox.extend(worker.drain_outputs())
                if self._preempt.is_set():
                    self._preempted = True
                    return
                if not has_work:
                    if self._stop.is_set():
                        self._stats = worker.stats()
                        self._done_flag = True
                        return
                    time.sleep(0.0005)
        except BaseException as e:      # noqa: BLE001 - crash envelope
            self._error = e


class ProcessReplica:
    """Subprocess replica: `fleet_worker.py` over JSONL pipes.

    ``spec`` is the worker's build recipe (inference config, params
    seed, optional per-replica telemetry jsonl) passed through the
    ``DS_TPU_SERVE_SPEC`` env var; ``inject`` (optional) becomes this
    replica's ``DS_TPU_SERVE_INJECT`` so a harness can arm faults in
    exactly one replica of the fleet.
    """

    def __init__(self, index, spec, workdir, num_replicas=1,
                 inject=None, env=None,
                 hang_timeout_s=None, heartbeat_stale_s=None,
                 restart_count=0):
        self.index = int(index)
        self.spec = dict(spec)
        self.workdir = os.path.abspath(workdir)
        self.num_replicas = int(num_replicas)
        self.inject = inject
        self.base_env = dict(env) if env is not None \
            else dict(os.environ)
        self.hang_timeout_s = hang_timeout_s
        self.heartbeat_stale_s = heartbeat_stale_s
        self.restart_count = int(restart_count)
        self.proc = None
        self._outbox = collections.deque()
        self._lock = threading.Lock()
        self._reader = None
        self._stats = None
        self.ready = threading.Event()

    # -- lifecycle -----------------------------------------------------

    def start(self):
        os.makedirs(self.workdir, exist_ok=True)
        env = dict(self.base_env)
        # The worker runs with cwd=workdir, so the repo root must be on
        # PYTHONPATH explicitly (the parent usually has it via its cwd).
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH")) if p)
        env.update({
            "DS_TPU_RUN_PROCESS_INDEX": str(self.index),
            "DS_TPU_RUN_NUM_WORKERS": str(self.num_replicas),
            "DS_TPU_RUN_RESTART_COUNT": str(self.restart_count),
            "DS_TPU_RUN_ATTEMPT": "1",
            "DS_TPU_RUN_WORKDIR": self.workdir,
            "DS_TPU_SERVE_SPEC": json.dumps(self.spec),
        })
        if self.inject is not None:
            env["DS_TPU_SERVE_INJECT"] = json.dumps(self.inject)
        log_dir = os.path.join(self.workdir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_f = open(os.path.join(log_dir,
                                  f"replica{self.index}.log"), "ab")
        try:
            self.proc = subprocess.Popen(
                [sys.executable, "-m",
                 "deepspeed_tpu.inference.fleet_worker"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=log_f, cwd=self.workdir, env=env, text=True,
                bufsize=1)
        finally:
            log_f.close()               # the child holds its own fd
        self._reader = threading.Thread(
            target=self._read_loop,
            name=f"fleet-reader-{self.index}", daemon=True)
        self._reader.start()
        return self

    def _read_loop(self):
        try:
            for line in self.proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue        # stray print from the worker
                kind = msg.get("type")
                if kind == "completion":
                    with self._lock:
                        self._outbox.append(msg["completion"])
                elif kind in ("prefilled", "handoff_corrupt",
                              "handoff_missing", "handoff_error"):
                    # disaggregated tier outputs (ISSUE 20): the payload
                    # travels as-is, tagged with its kind so the
                    # DisaggRouter can route it.
                    with self._lock:
                        self._outbox.append(
                            dict(msg["payload"], kind=kind))
                elif kind == "ready":
                    self.ready.set()
                elif kind in ("stats", "preempted"):
                    self._stats = msg
        except (OSError, ValueError):
            pass                    # pipe died with the worker

    def wait_ready(self, timeout=120.0):
        """Block until the worker reports its engine is built (compile
        warmup happens on first prefill, not here)."""
        if not self.ready.wait(timeout):
            raise TimeoutError(
                f"replica {self.index} never reported ready "
                f"(see {self.workdir}/logs/replica{self.index}.log)")
        return self

    # -- router-facing handle ------------------------------------------

    def _send(self, msg):
        try:
            self.proc.stdin.write(json.dumps(msg) + "\n")
            self.proc.stdin.flush()
        except (OSError, ValueError):
            pass        # dead replica: the health check will notice

    def submit(self, request):
        self._send({"cmd": "submit", "request": request_dict(request)})

    def poll(self):
        with self._lock:
            out = list(self._outbox)
            self._outbox.clear()
        return out

    def check(self, now=None):
        rc = self.proc.poll()
        cause = classify_exit(
            rc, os.path.exists(done_path(self.workdir, self.index)))
        if cause is not None or rc is not None:
            return cause
        hb = self._read_heartbeat()
        return heartbeat_verdict(
            hb, time.time(), hang_timeout_s=self.hang_timeout_s,
            heartbeat_stale_s=self.heartbeat_stale_s)

    def _read_heartbeat(self):
        try:
            with open(heartbeat_path(self.workdir, self.index)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def stop(self, timeout=60.0):
        self._send({"cmd": "stop"})
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
        if self._reader is not None:
            self._reader.join(timeout=5.0)
        return self._stats

    def kill(self):
        """Hard SIGKILL — the soak path when the harness kills from
        outside rather than via an armed ``inject_kill``."""
        try:
            self.proc.kill()
        except OSError:
            pass

    def terminate(self):
        """SIGTERM: the worker's PreemptionHandler finishes the step,
        reports completed-so-far, and exits 0 without its done marker."""
        try:
            self.proc.terminate()
        except OSError:
            pass

    def reap(self):
        """Close pipes after death so fds don't leak across a long
        fleet run."""
        try:
            self.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            self.proc.kill()
        for stream in (self.proc.stdin, self.proc.stdout):
            try:
                if stream is not None:
                    stream.close()
            except OSError:
                pass


class TierProcessReplica(ProcessReplica):
    """Subprocess TIER replica: the worker boots with ``spec["tier"]``
    set to ``"prefill"``/``"decode"`` and a shared ``handoff_dir``,
    builds the matching tier engine + worker, and speaks the same JSONL
    protocol plus the handoff kinds (``prefilled``/``handoff_corrupt``/
    ``handoff_missing``/``handoff_error``). A decode-tier submit
    carries the :class:`~deepspeed_tpu.inference.disagg.HandoffMeta`
    dict alongside the request."""

    def submit(self, request, meta=None):
        msg = {"cmd": "submit", "request": request_dict(request)}
        if meta is not None:
            msg["handoff"] = dict(meta)
        self._send(msg)


def build_process_fleet(n, spec, workdir, inject=None, inject_replica=0,
                        env=None, hang_timeout_s=None,
                        heartbeat_stale_s=None):
    """Spawn and ready-wait ``n`` :class:`ProcessReplica` workers in
    ``workdir`` (shared heartbeat/done-marker dir, per-replica logs).
    ``inject`` arms the fault spec in ``inject_replica`` only."""
    replicas = []
    for i in range(n):
        replicas.append(ProcessReplica(
            i, spec, workdir, num_replicas=n,
            inject=inject if i == inject_replica else None,
            env=env, hang_timeout_s=hang_timeout_s,
            heartbeat_stale_s=heartbeat_stale_s).start())
    for r in replicas:
        r.wait_ready()
    return replicas
