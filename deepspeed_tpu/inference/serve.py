"""`ds_tpu_serve`: drive the serving engine against a request stream.

    ds_tpu_serve --synthetic 8                # scripted open-loop stream
    ds_tpu_serve --requests stream.jsonl      # one request per line
    ds_tpu_serve --config ds_config.json      # inference block from config
    ds_tpu_serve --scan-layers --kv-cache-dtype int8
    ds_tpu_serve --expect-compiles 2 --json
    ds_tpu_serve --synthetic 8 --kv-layout paged --shared-prefix 12 \
                 --expect-prefix-hits 1   # radix prefix-cache smoke
    ds_tpu_serve --synthetic 8 --replicas 2 \
                 --kill-replica 0 --kill-at-step 3 \
                 --expect-redispatch 1    # fleet resilience smoke
    ds_tpu_serve --synthetic 8 --speculative --spec-k 4 \
                 --draft-layers 1 --block-scale 0.1 \
                 --expect-compiles 3 --expect-min-accepted 1.0
    ds_tpu_serve --synthetic 4 --checkpoint /ckpts/run1 --n-head 4

The model is the test-size GPT-2 with seeded random params — this CLI
exists to exercise and measure the serving engine (CI smoke, bench
rows, audits), not to ship checkpoints. A request line is
``{"rid": "r0", "prompt": [1, 2, 3], "max_new_tokens": 8,
"eos_id": null, "arrival_step": 0}`` (only ``prompt`` required; also
``deadline_s``/``queue_timeout_s`` per ISSUE 17).

``--expect-compiles N`` makes the exit code enforce the recompile
contract: after the stream drains, prefill + decode (+ draft + verify
with ``--speculative``) jit-cache entries must total exactly N (2 for
any single-engine serve — one prefill, one decode — and exactly 3
speculative: prefill, draft, verify, with the plain decode program
never entered). With ``--replicas`` the gate applies PER SURVIVING
REPLICA.
``--jsonl`` writes telemetry events for ``ds_tpu_metrics summary``
serve mode (``decode_step`` single-engine; fleet events with
``--replicas``).

``--replicas N`` (N >= 2) serves through the fleet router
(`inference/fleet.py` + `router.py`): N replica workers behind one
admission queue with drain/redispatch on replica death.
``--kill-replica I --kill-at-step S`` arms a real SIGKILL inside
replica I's decode loop (``DS_TPU_SERVE_INJECT``), and
``--expect-redispatch N`` gates the exit code on the fleet actually
recovering.

Exit codes: 0 ok, 1 contract violation or unfinished requests,
2 usage errors.
"""

import argparse
import json
import sys

import numpy as np


def _build_requests(args, vocab_size, max_seq):
    from deepspeed_tpu.inference.scheduler import Request
    if args.requests:
        reqs = []
        with open(args.requests) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                reqs.append(Request(
                    rid=str(d.get("rid", f"r{i}")),
                    prompt=[int(t) for t in d["prompt"]],
                    max_new_tokens=int(
                        d.get("max_new_tokens", args.max_new)),
                    eos_id=d.get("eos_id"),
                    arrival_step=int(d.get("arrival_step", 0)),
                    session_id=d.get("session_id"),
                    deadline_s=d.get("deadline_s", args.deadline_s),
                    queue_timeout_s=d.get("queue_timeout_s",
                                          args.queue_timeout_s)))
        return reqs
    # synthetic open-loop stream: varied prompt lengths spanning the
    # buckets, staggered arrivals, deterministic under --seed. With
    # --shared-prefix N every prompt opens with the same N tokens (a
    # common system prompt) so a paged engine's radix cache gets hits.
    rng = np.random.default_rng(args.seed)
    shared = rng.integers(
        0, vocab_size, args.shared_prefix).tolist() \
        if args.shared_prefix else []
    reqs = []
    for i in range(args.synthetic):
        plen = int(rng.integers(2, max(3, args.synthetic_max_prompt)))
        tail = rng.integers(0, vocab_size, plen).tolist()
        prompt = (shared + tail)[:max_seq - 1]
        reqs.append(Request(
            rid=f"s{i}",
            prompt=prompt,
            max_new_tokens=args.max_new,
            arrival_step=int(i * args.arrival_every),
            deadline_s=args.deadline_s,
            queue_timeout_s=args.queue_timeout_s))
    return reqs


# gpt2_tiny's fixed test vocab — the synthetic stream only needs the
# token range, so fleet mode doesn't build a model in the parent
_TINY_VOCAB = 256


def _scale_blocks(params, scale):
    """Damp every block's residual-branch output projections
    (attn/mlp ``c_proj`` kernels) by ``scale``.

    Seeded-random weights give each block a ~unit-RMS output riding on
    a 0.02-RMS embedding stream, so a truncated-depth draft diverges
    from the full model immediately and speculative acceptance sits at
    chance (~1/vocab). Trained transformers converge through depth;
    ``--block-scale 0.1`` emulates that residual-stream convergence so
    the CI mean-accepted gate measures the accept machinery, not the
    entropy of random init."""
    def walk(tree, path):
        if hasattr(tree, "items"):
            return {k: walk(v, path + (str(k),))
                    for k, v in tree.items()}
        if "c_proj" in path and path[-1] == "kernel":
            return tree * scale
        return tree

    return walk(params, ())


def _load_checkpoint_model(args, jax, jnp):
    """Serve a real trained checkpoint: resolve + load a
    `runtime/resilience/checkpoint.py` manifest, take its fp32 master
    params, infer the GPT-2 geometry from leaf shapes, and convert the
    layer layout (the elastic ``param_layout`` metadata: ``stacked``
    scan_layers vs ``per_layer`` unrolled) to the requested serving
    variant — training→serving handoff in one command. Checkpoints
    saved under a different tensor-parallel topology need a
    ``ds_tpu_reshard`` relayout first (single-host serving reads
    replicated host leaves)."""
    import re

    from deepspeed_tpu.models.gpt2 import (
        GPT2Config,
        GPT2LMHead,
        stack_gpt2_layer_params,
        unstack_gpt2_layer_params,
    )
    from deepspeed_tpu.runtime.resilience.checkpoint import (
        CheckpointManager)

    mgr = CheckpointManager()
    tag = mgr.resolve_tag(args.checkpoint, args.ckpt_tag)
    if tag is None:
        raise SystemExit(
            f"ds_tpu_serve: no valid checkpoint under {args.checkpoint}")
    state, meta, path = mgr.load(args.checkpoint, tag)
    if "params" not in state:
        raise SystemExit(
            f"ds_tpu_serve: checkpoint {path} carries no 'params' tree")
    params = state["params"]
    topo = (meta or {}).get("topology") or {}
    saved_tp = int((topo.get("mesh_shape") or {}).get("model", 1) or 1)
    if saved_tp > 1:
        print(f"note: checkpoint {tag} was saved on a model-parallel "
              f"mesh (model axis {saved_tp}); if its leaves were "
              f"persisted sharded, relayout with ds_tpu_reshard before "
              f"serving", file=sys.stderr)
    # layer-layout conversion: the round trip is bit-exact, so a
    # per-layer training checkpoint serves as scan_layers and back
    if args.scan_layers and "h" not in params:
        params = stack_gpt2_layer_params(params)
    elif not args.scan_layers and "h" in params:
        params = unstack_gpt2_layer_params(params)
    wte, wpe = params["wte"], params["wpe"]
    if "h" in params:
        n_layer = int(jax.tree_util.tree_leaves(params["h"])[0].shape[0])
    else:
        n_layer = len([k for k in params
                       if re.match(r"^h_\d+$", str(k))])
    n_embd = int(wte.shape[1])
    if n_embd % args.n_head:
        raise SystemExit(
            f"ds_tpu_serve: --n-head {args.n_head} does not divide the "
            f"checkpoint's n_embd {n_embd}")
    cfg = GPT2Config(
        vocab_size=int(wte.shape[0]), n_positions=int(wpe.shape[0]),
        n_embd=n_embd, n_layer=n_layer, n_head=args.n_head,
        dropout=0.0, dtype=jnp.float32, param_dtype=jnp.float32,
        scan_layers=args.scan_layers)
    params = jax.tree_util.tree_map(jnp.asarray, params)
    return GPT2LMHead(cfg), params, {"tag": tag, "path": path,
                                     "n_layer": n_layer,
                                     "n_embd": n_embd,
                                     "vocab_size": cfg.vocab_size,
                                     "param_layout": topo.get(
                                         "param_layout")}


def _run_fleet(args, inf_cfg, session):
    """Serve through the N-replica fleet router (ISSUE 17)."""
    import os
    import tempfile

    from deepspeed_tpu.inference import fleet as fleet_mod
    from deepspeed_tpu.inference.router import FleetRouter

    workdir = os.path.abspath(
        args.workdir or tempfile.mkdtemp(prefix="ds-tpu-fleet-"))
    max_seq = max(inf_cfg.get("seq_buckets", (16, 32)))
    requests = _build_requests(args, _TINY_VOCAB, max_seq)

    inject = None
    if args.kill_replica is not None:
        inject = {"kill": {"op": "decode_step",
                           "at_step": args.kill_at_step}}
    spec = {"inf_cfg": {k: (list(v) if isinstance(v, tuple) else v)
                        for k, v in inf_cfg.items()},
            "seed": args.seed, "scan_layers": args.scan_layers}

    if args.replica_backend == "process":
        replicas = []
        for i in range(args.replicas):
            rspec = dict(spec, jsonl=os.path.join(
                workdir, f"replica{i}.jsonl"))
            replicas.append(fleet_mod.ProcessReplica(
                i, rspec, workdir, num_replicas=args.replicas,
                inject=inject if i == args.kill_replica else None,
                hang_timeout_s=args.hang_timeout_s,
                heartbeat_stale_s=args.heartbeat_stale_s).start())
        for r in replicas:
            r.wait_ready()
    else:
        import jax
        import jax.numpy as jnp
        from deepspeed_tpu.inference.engine import InferenceEngine
        from deepspeed_tpu.models.gpt2 import GPT2LMHead, gpt2_tiny

        def factory():
            cfg = gpt2_tiny(n_embd=32, dtype=jnp.float32,
                            scan_layers=args.scan_layers)
            model = GPT2LMHead(cfg)
            params = model.init(jax.random.PRNGKey(args.seed),
                                jnp.zeros((1, 8), jnp.int32))["params"]
            return InferenceEngine(model, params, config=inf_cfg)

        replicas = [fleet_mod.ThreadReplica(i, factory).start()
                    for i in range(args.replicas)]

    router = FleetRouter(
        replicas, session=session,
        max_redispatch=(args.max_redispatch if args.max_redispatch
                        is not None
                        else int(inf_cfg.get("max_redispatch", 2))),
        max_queue_depth=(args.max_queue_depth if args.max_queue_depth
                         is not None
                         else int(inf_cfg.get("max_queue_depth", 8))),
        max_pending=args.max_pending)
    fr = router.run(requests, timeout_s=args.fleet_timeout)

    ok = fr.ok
    compiles_bad = []
    if args.expect_compiles is not None:
        for st in fr.stats:
            total = sum(n for n in st["compile_counts"].values()
                        if n is not None)
            if total != args.expect_compiles:
                compiles_bad.append((st["replica"], total))
        ok = ok and not compiles_bad
    redisp_ok = True
    if args.expect_redispatch is not None:
        redisp_ok = fr.redispatched_total >= args.expect_redispatch
        ok = ok and redisp_ok

    result = {
        "requests": len(requests),
        "completions": fr.completions,
        "fleet": {
            "replicas": fr.replicas,
            "backend": args.replica_backend,
            "replicas_dead": fr.replicas_dead,
            "dead_causes": dict(router.dead),
            "redispatched_total": fr.redispatched_total,
            "aborted": fr.aborted, "shed": fr.shed,
            "defers": fr.defers, "timeouts": fr.timeouts,
            "latency_s": fr.latency_s,
            "stats": fr.stats,
            "workdir": workdir,
        },
        "ok": ok,
    }
    if args.expect_compiles is not None:
        result["expect_compiles"] = args.expect_compiles
    if args.expect_redispatch is not None:
        result["expect_redispatch"] = args.expect_redispatch

    if args.as_json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        for c in fr.completions:
            extra = ""
            if c["redispatched"]:
                extra = f", redispatched x{c['redispatched']}"
            print(f"{c['rid']}: prompt {c['prompt_len']} tokens -> "
                  f"{len(c['tokens'])} generated "
                  f"({c['finish_reason']}, replica {c['replica']}"
                  f"{extra})")
        fl = result["fleet"]
        print(f"{len(fr.completions)}/{len(requests)} requests "
              f"completed on {fl['replicas']} replica(s) "
              f"({fl['replicas_dead']} died: {fl['dead_causes']}); "
              f"redispatched={fl['redispatched_total']} "
              f"aborted={fl['aborted']} shed={fl['shed']} "
              f"timeouts={fl['timeouts']}")
        for st in fr.stats:
            cc = st["compile_counts"]
            print(f"replica {st['replica']}: {st['completed']} "
                  f"completed in {st['steps']} step(s); compiles: "
                  f"prefill={cc.get('prefill')} "
                  f"decode={cc.get('decode')}")
        if not ok:
            if compiles_bad:
                why = (f"replica compile counts {compiles_bad} != "
                       f"expected {args.expect_compiles}")
            elif not redisp_ok:
                why = (f"redispatched {fr.redispatched_total} < "
                       f"expected {args.expect_redispatch}")
            else:
                why = ("unfinished/aborted/shed/timed-out requests "
                       "in the fleet result")
            print(f"FAIL: {why}", file=sys.stderr)
    return 0 if ok else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_tpu_serve",
        description="run the jitted serving engine over a request "
                    "stream (continuous batching, bucketed KV cache)")
    parser.add_argument("--config", default=None,
                        help="DeepSpeed-style JSON config; its "
                             "'inference' block configures the engine")
    parser.add_argument("--scan-layers", action="store_true",
                        help="serve the scan_layers model variant")
    parser.add_argument("--kv-cache-dtype", default=None,
                        help="override cache storage: bf16, f32, or a "
                             "codec name (int8, f8e4m3fn, f8e5m2)")
    parser.add_argument("--max-batch", type=int, default=None,
                        help="override inference.max_batch")
    parser.add_argument("--seq-buckets", default=None,
                        help="override inference.seq_buckets, e.g. 16,32")
    parser.add_argument("--prefill-chunk", type=int, default=None,
                        help="override inference.prefill_chunk")
    parser.add_argument("--attention", default=None,
                        choices=("dense", "flash"),
                        help="decode attention impl: dense softmax or "
                             "the Pallas flash-decode kernel")
    parser.add_argument("--block-k", type=int, default=None,
                        help="flash-decode KV block size (must divide "
                             "max(seq_buckets))")
    parser.add_argument("--kv-layout", default=None,
                        choices=("ring", "paged"),
                        help="KV cache layout: per-row ring buffers or "
                             "the paged pool with radix prefix sharing")
    parser.add_argument("--page-size", type=int, default=None,
                        help="paged layout: tokens per KV page (0 = "
                             "auto; must be a multiple of "
                             "prefill_chunk and divide max seq bucket)")
    parser.add_argument("--n-pages", type=int, default=None,
                        help="paged layout: physical pool pages "
                             "(0 = auto; page 0 is the trash page)")
    parser.add_argument("--prefix-cache", dest="prefix_cache",
                        action="store_true", default=None,
                        help="paged layout: intern finished prompts in "
                             "the radix prefix cache (default on)")
    parser.add_argument("--no-prefix-cache", dest="prefix_cache",
                        action="store_false",
                        help="paged layout: disable prefix sharing")
    parser.add_argument("--park-threshold", type=float, default=None,
                        help="paged layout: evacuate parked sessions "
                             "to host RAM when the free-page fraction "
                             "drops below this (0 disables)")
    parser.add_argument("--shared-prefix", type=int, default=0,
                        help="synthetic stream: open every prompt with "
                             "the same N tokens (a shared system "
                             "prompt) to exercise the prefix cache")
    parser.add_argument("--expect-prefix-hits", type=int, default=None,
                        help="exit 1 unless the paged prefix cache "
                             "recorded at least this many hits")
    parser.add_argument("--temperature", type=float, default=None,
                        help="sampling temperature (0 = greedy argmax, "
                             "the default)")
    parser.add_argument("--top-k", type=int, default=None,
                        help="keep only the k most likely tokens "
                             "(0 = disabled)")
    parser.add_argument("--top-p", type=float, default=None,
                        help="nucleus sampling mass (1.0 = disabled)")
    # -- speculative decoding (ISSUE 18) --------------------------------
    parser.add_argument("--speculative", action="store_true",
                        help="self-speculative decoding: draft k "
                             "tokens through the first draft_layers "
                             "blocks, verify all of them in one "
                             "full-depth forward")
    parser.add_argument("--spec-k", type=int, default=4,
                        help="draft window: tokens drafted per verify "
                             "round (>= 1)")
    parser.add_argument("--draft-layers", type=int, default=0,
                        help="transformer blocks the draft pass runs "
                             "(0 = auto n_layer // 2)")
    parser.add_argument("--min-accept-to-grow", type=float, default=0.0,
                        help="adaptive draft length: grow the window "
                             "when mean accepted drafts/round clears "
                             "this, shrink when it doesn't (0 = fixed "
                             "window)")
    parser.add_argument("--block-scale", type=float, default=None,
                        help="damp every block's c_proj kernels by "
                             "this factor; emulates trained residual "
                             "convergence so seeded-random weights "
                             "give measurable draft acceptance")
    parser.add_argument("--expect-min-accepted", type=float,
                        default=None,
                        help="exit 1 unless mean accepted tokens per "
                             "speculative round clears this")
    # -- checkpoint serving (ISSUE 18) ----------------------------------
    parser.add_argument("--checkpoint", default=None,
                        help="serve params from this training "
                             "checkpoint dir (runtime/resilience "
                             "manifest layout) instead of seeded "
                             "random weights")
    parser.add_argument("--ckpt-tag", default=None,
                        help="checkpoint tag to load (default: the "
                             "newest valid one)")
    parser.add_argument("--n-head", type=int, default=4,
                        help="attention heads for --checkpoint serving "
                             "(not recoverable from param shapes)")
    parser.add_argument("--requests", default=None,
                        help="JSONL request stream (one request/line)")
    parser.add_argument("--synthetic", type=int, default=0,
                        help="generate N synthetic open-loop requests "
                             "instead of --requests")
    parser.add_argument("--synthetic-max-prompt", type=int, default=24,
                        help="synthetic prompt length upper bound")
    parser.add_argument("--arrival-every", type=float, default=1.0,
                        help="synthetic arrival spacing in decode steps")
    parser.add_argument("--max-new", type=int, default=8,
                        help="default max_new_tokens per request")
    parser.add_argument("--seed", type=int, default=0,
                        help="params + synthetic stream seed")
    parser.add_argument("--expect-compiles", type=int, default=None,
                        help="exit 1 unless total jit cache entries "
                             "(prefill + decode) equal exactly this")
    parser.add_argument("--jsonl", default=None,
                        help="write decode_step telemetry events here "
                             "(ds_tpu_metrics summary serve mode)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="print the result dict as JSON")
    # -- fleet mode (ISSUE 17) ------------------------------------------
    parser.add_argument("--replicas", type=int, default=1,
                        help="serve through an N-replica fleet behind "
                             "the admission router (N >= 2)")
    parser.add_argument("--replica-backend", default="process",
                        choices=("process", "thread"),
                        help="fleet replicas: real subprocess workers "
                             "(SIGKILL-able) or in-process threads")
    parser.add_argument("--workdir", default=None,
                        help="fleet workdir (heartbeats, done markers, "
                             "replica logs); default: a temp dir")
    parser.add_argument("--deadline-s", type=float, default=None,
                        help="per-request total wall-clock deadline "
                             "(typed 'timeout' finish reason)")
    parser.add_argument("--queue-timeout-s", type=float, default=None,
                        help="per-request bound on queue wait before "
                             "admission (typed 'timeout')")
    parser.add_argument("--max-redispatch", type=int, default=None,
                        help="redispatches before a request aborts "
                             "(typed RequestAbortedError path)")
    parser.add_argument("--max-queue-depth", type=int, default=None,
                        help="per-replica in-flight bound (router "
                             "defers past it, emitting fleet_defer)")
    parser.add_argument("--max-pending", type=int, default=None,
                        help="global admission bound (router sheds "
                             "past it, emitting fleet_shed)")
    parser.add_argument("--fleet-timeout", type=float, default=300.0,
                        help="whole-fleet drive-loop wall bound")
    parser.add_argument("--hang-timeout-s", type=float, default=None,
                        help="replica heartbeat stuck-in-step bound")
    parser.add_argument("--heartbeat-stale-s", type=float, default=None,
                        help="replica heartbeat staleness bound")
    parser.add_argument("--kill-replica", type=int, default=None,
                        help="arm a SIGKILL fault in this replica index")
    parser.add_argument("--kill-at-step", type=int, default=3,
                        help="decode step the armed kill fires at")
    parser.add_argument("--expect-redispatch", type=int, default=None,
                        help="exit 1 unless the fleet redispatched at "
                             "least this many requests")
    args = parser.parse_args(argv)

    if not args.requests and not args.synthetic:
        parser.error("one of --requests or --synthetic N is required")
    if args.requests and args.synthetic:
        parser.error("--requests and --synthetic are mutually exclusive")
    if args.replicas < 1:
        parser.error("--replicas must be >= 1")
    if args.replicas == 1 and (args.kill_replica is not None or
                               args.expect_redispatch is not None):
        parser.error("--kill-replica/--expect-redispatch require "
                     "--replicas >= 2")
    if args.kill_replica is not None and \
            not 0 <= args.kill_replica < args.replicas:
        parser.error(f"--kill-replica {args.kill_replica} outside "
                     f"0..{args.replicas - 1}")
    if args.kill_replica is not None and \
            args.replica_backend != "process":
        parser.error("--kill-replica needs --replica-backend process "
                     "(a thread cannot be SIGKILLed in isolation)")
    if args.speculative and args.replicas > 1:
        parser.error("--speculative is single-replica only (the fleet "
                     "router has no variable-tokens-per-step protocol "
                     "yet)")
    if args.expect_min_accepted is not None and not args.speculative:
        parser.error("--expect-min-accepted requires --speculative")
    if args.checkpoint and args.replicas > 1:
        parser.error("--checkpoint serving is single-replica only")
    if args.spec_k < 1:
        parser.error("--spec-k must be >= 1")
    if args.draft_layers < 0:
        parser.error("--draft-layers must be >= 0 (0 = auto)")
    if args.n_head < 1:
        parser.error("--n-head must be >= 1")

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.scheduler import (
        ContinuousBatchingScheduler)
    from deepspeed_tpu.models.gpt2 import GPT2LMHead, gpt2_tiny
    from deepspeed_tpu.telemetry.session import TelemetrySession

    inf_cfg = {"max_batch": 2, "seq_buckets": (16, 32),
               "prefill_chunk": 4}
    if args.config:
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        with open(args.config) as f:
            raw = json.load(f)
        # a serving config needn't carry training batch sizes; give the
        # validator trivial ones (world_size pinned to 1 — serving does
        # no data parallelism) so only the inference block matters
        raw.setdefault("train_batch_size", 1)
        raw.setdefault("train_micro_batch_size_per_gpu", 1)
        ds = DeepSpeedConfig(raw, world_size=1)
        inf = ds.inference
        inf_cfg = {"max_batch": inf.max_batch,
                   "seq_buckets": inf.seq_buckets,
                   "prefill_chunk": inf.prefill_chunk,
                   "kv_cache_dtype": inf.kv_cache_dtype,
                   "max_new_tokens": inf.max_new_tokens,
                   "attention_impl": inf.attention_impl,
                   "attention_block_k": inf.attention_block_k,
                   "temperature": inf.temperature,
                   "top_k": inf.top_k,
                   "top_p": inf.top_p,
                   "sampling_seed": inf.sampling_seed,
                   "kv_layout": inf.kv_layout,
                   "page_size": inf.page_size,
                   "n_pages": inf.n_pages,
                   "prefix_cache": inf.prefix_cache,
                   "host_park_threshold": inf.host_park_threshold,
                   "replicas": inf.replicas,
                   "max_redispatch": inf.max_redispatch,
                   "max_queue_depth": inf.max_queue_depth,
                   "deadline_s": inf.deadline_s,
                   "queue_timeout_s": inf.queue_timeout_s,
                   "speculative": inf.speculative}
    if args.max_batch is not None:
        inf_cfg["max_batch"] = args.max_batch
    if args.seq_buckets is not None:
        inf_cfg["seq_buckets"] = tuple(
            int(b) for b in args.seq_buckets.split(",") if b.strip())
    if args.prefill_chunk is not None:
        inf_cfg["prefill_chunk"] = args.prefill_chunk
    if args.kv_cache_dtype is not None:
        inf_cfg["kv_cache_dtype"] = args.kv_cache_dtype
    if args.attention is not None:
        inf_cfg["attention_impl"] = args.attention
    if args.block_k is not None:
        inf_cfg["attention_block_k"] = args.block_k
    if args.temperature is not None:
        inf_cfg["temperature"] = args.temperature
    if args.top_k is not None:
        inf_cfg["top_k"] = args.top_k
    if args.top_p is not None:
        inf_cfg["top_p"] = args.top_p
    if args.kv_layout is not None:
        inf_cfg["kv_layout"] = args.kv_layout
    if args.page_size is not None:
        inf_cfg["page_size"] = args.page_size
    if args.n_pages is not None:
        inf_cfg["n_pages"] = args.n_pages
    if args.prefix_cache is not None:
        inf_cfg["prefix_cache"] = args.prefix_cache
    if args.park_threshold is not None:
        inf_cfg["host_park_threshold"] = args.park_threshold
    if args.speculative:
        inf_cfg["speculative"] = {
            "enabled": True, "k": args.spec_k,
            "draft_layers": args.draft_layers,
            "min_accept_to_grow": args.min_accept_to_grow}
    if args.expect_prefix_hits is not None and \
            inf_cfg.get("kv_layout", "ring") != "paged":
        parser.error("--expect-prefix-hits requires --kv-layout paged")
    # --seed doubles as the sampling seed: one knob pins params, the
    # synthetic stream, AND the in-program sampler, so a serve is
    # reproducible end to end (a non-default --seed beats the config).
    if args.seed != 0 or "sampling_seed" not in inf_cfg:
        inf_cfg["sampling_seed"] = args.seed

    session = None
    if args.jsonl:
        from deepspeed_tpu.telemetry.exporters import JsonlExporter
        session = TelemetrySession(exporters=[JsonlExporter(args.jsonl)])

    # config-file fleet/deadline knobs apply when the flags stay at
    # their defaults (0 in the config block means disabled)
    args.replicas = max(args.replicas, int(inf_cfg.get("replicas", 1)
                                           or 1))
    if args.deadline_s is None:
        args.deadline_s = inf_cfg.get("deadline_s") or None
    if args.queue_timeout_s is None:
        args.queue_timeout_s = inf_cfg.get("queue_timeout_s") or None
    if args.replicas > 1:
        if inf_cfg.get("speculative"):
            parser.error("config enables speculative decoding but the "
                         "serve is fleet-mode; run single-replica")
        return _run_fleet(args, inf_cfg, session)

    ckpt_info = None
    if args.checkpoint:
        model, params, ckpt_info = _load_checkpoint_model(args, jax, jnp)
        cfg = model.config
    else:
        cfg = gpt2_tiny(n_embd=32, dtype=jnp.float32,
                        scan_layers=args.scan_layers)
        model = GPT2LMHead(cfg)
        toks = jnp.zeros((1, 8), jnp.int32)
        params = model.init(jax.random.PRNGKey(args.seed),
                            toks)["params"]
    if args.block_scale is not None:
        params = _scale_blocks(params, args.block_scale)
    engine = InferenceEngine(model, params, config=inf_cfg,
                             session=session)
    sched = ContinuousBatchingScheduler(engine)

    requests = _build_requests(args, cfg.vocab_size, engine.max_seq)
    completions = sched.run(requests)

    counts = engine.compile_counts()
    total_compiles = sum(n for n in counts.values() if n is not None)
    result = {
        "requests": len(requests),
        "completions": [
            {"rid": c.rid, "prompt_len": c.prompt_len,
             "tokens": c.tokens, "finish_reason": c.finish_reason,
             "bucket": c.bucket, "slot": c.slot, "steps": c.steps,
             "prefix_hit": c.prefix_hit, "resumed": c.resumed,
             "prefill_chunks": c.prefill_chunks,
             "prefill_chunks_skipped": c.prefill_chunks_skipped}
            for c in completions],
        "decode_steps": sched.step_count,
        "compile_counts": counts,
        "cache": engine.cache_facts(),
        "attention": {"impl": engine.attention_impl,
                      "block_k": engine.attention_block_k},
        "sampling": {"temperature": engine.temperature,
                     "top_k": engine.top_k, "top_p": engine.top_p,
                     "seed": engine.sampling_seed},
    }
    if sched.paging is not None:
        result["paging"] = sched.paging.facts()
    if engine.speculative is not None:
        result["speculative"] = engine.speculative.facts()
    if ckpt_info is not None:
        result["checkpoint"] = ckpt_info
    ok = len(completions) == len(requests)
    if args.expect_compiles is not None:
        result["expect_compiles"] = args.expect_compiles
        ok = ok and total_compiles == args.expect_compiles
    prefix_hits_ok = True
    if args.expect_prefix_hits is not None:
        hits = result["paging"]["prefix_hits"]
        result["expect_prefix_hits"] = args.expect_prefix_hits
        prefix_hits_ok = hits >= args.expect_prefix_hits
        ok = ok and prefix_hits_ok
    accepted_ok = True
    if args.expect_min_accepted is not None:
        mean_acc = result["speculative"]["mean_accepted"]
        result["expect_min_accepted"] = args.expect_min_accepted
        accepted_ok = mean_acc >= args.expect_min_accepted
        ok = ok and accepted_ok
    result["ok"] = ok

    if args.as_json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        for c in completions:
            extra = ""
            if c.prefix_hit or c.resumed:
                kind = "resumed" if c.resumed else "prefix hit"
                extra = (f", {kind}: skipped "
                         f"{c.prefill_chunks_skipped} prefill chunk(s)")
            print(f"{c.rid}: prompt {c.prompt_len} tokens -> "
                  f"{len(c.tokens)} generated ({c.finish_reason}, "
                  f"bucket {c.bucket}, slot {c.slot}{extra})")
        compiles = (f"prefill={counts['prefill']} "
                    f"decode={counts['decode']}")
        if engine.speculative is not None:
            compiles += (f" draft={counts['draft']} "
                         f"verify={counts['verify']}")
        print(f"{len(completions)}/{len(requests)} requests completed "
              f"in {sched.step_count} decode step(s); compiles: "
              f"{compiles}")
        if ckpt_info is not None:
            print(f"checkpoint: tag {ckpt_info['tag']} "
                  f"({ckpt_info['n_layer']}L/{ckpt_info['n_embd']}d, "
                  f"vocab {ckpt_info['vocab_size']}, saved layout "
                  f"{ckpt_info['param_layout']})")
        if engine.speculative is not None:
            sp = result["speculative"]
            print(f"speculative: k={sp['k']} "
                  f"draft_layers={sp['draft_layers']}/{sp['n_layer']}, "
                  f"mean accepted {sp['mean_accepted']:.3f} "
                  f"tokens/round over {sp['row_rounds']} row-round(s), "
                  f"draft efficiency {sp['draft_efficiency']:.3f}")
        if sched.paging is not None:
            pg = result["paging"]
            print(f"paged KV: {pg['pages_resident']}/{pg['n_pages']} "
                  f"pages resident, prefix hits {pg['prefix_hits']}/"
                  f"misses {pg['prefix_misses']}, host-parked "
                  f"{pg['sessions_parked_host']} session(s)")
        if not ok:
            if len(completions) != len(requests):
                why = "unfinished requests"
            elif not prefix_hits_ok:
                why = (f"prefix hits "
                       f"{result['paging']['prefix_hits']} < expected "
                       f"{args.expect_prefix_hits}")
            elif not accepted_ok:
                why = (f"mean accepted "
                       f"{result['speculative']['mean_accepted']:.3f} "
                       f"< expected {args.expect_min_accepted}")
            else:
                why = (f"compile count {total_compiles} != expected "
                       f"{args.expect_compiles}")
            print(f"FAIL: {why}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
