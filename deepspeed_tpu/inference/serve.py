"""`ds_tpu_serve`: drive the serving engine against a request stream.

    ds_tpu_serve --synthetic 8                # scripted open-loop stream
    ds_tpu_serve --requests stream.jsonl      # one request per line
    ds_tpu_serve --config ds_config.json      # inference block from config
    ds_tpu_serve --scan-layers --kv-cache-dtype int8
    ds_tpu_serve --expect-compiles 2 --json
    ds_tpu_serve --synthetic 8 --kv-layout paged --shared-prefix 12 \
                 --expect-prefix-hits 1   # radix prefix-cache smoke
    ds_tpu_serve --synthetic 8 --replicas 2 \
                 --kill-replica 0 --kill-at-step 3 \
                 --expect-redispatch 1    # fleet resilience smoke
    ds_tpu_serve --synthetic 8 --kv-layout paged --disaggregate \
                 --prefill-workers 1 --decode-workers 1 \
                 --expect-compiles 2      # tiered prefill/decode smoke
    ds_tpu_serve --synthetic 8 --speculative --spec-k 4 \
                 --draft-layers 1 --block-scale 0.1 \
                 --expect-compiles 3 --expect-min-accepted 1.0
    ds_tpu_serve --synthetic 4 --checkpoint /ckpts/run1 --n-head 4

The model is the test-size GPT-2 with seeded random params — this CLI
exists to exercise and measure the serving engine (CI smoke, bench
rows, audits), not to ship checkpoints. A request line is
``{"rid": "r0", "prompt": [1, 2, 3], "max_new_tokens": 8,
"eos_id": null, "arrival_step": 0}`` (only ``prompt`` required; also
``deadline_s``/``queue_timeout_s`` per ISSUE 17).

``--expect-compiles N`` makes the exit code enforce the recompile
contract: after the stream drains, prefill + decode (+ draft + verify
with ``--speculative``) jit-cache entries must total exactly N (2 for
any single-engine serve — one prefill, one decode — and exactly 3
speculative: prefill, draft, verify, with the plain decode program
never entered). With ``--replicas`` the gate applies PER SURVIVING
REPLICA. With ``--disaggregate`` it counts DISTINCT compiled programs
across the whole fleet (2: the prefill tier's one program plus the
decode tier's), not per-worker jit entries — each worker holds its own
cache entry for its tier's single program, so entries scale with
worker count while the program count must not.
``--jsonl`` writes telemetry events for ``ds_tpu_metrics summary``
serve mode (``decode_step`` single-engine; fleet events with
``--replicas``).

``--replicas N`` (N >= 2) serves through the fleet router
(`inference/fleet.py` + `router.py`): N replica workers behind one
admission queue with drain/redispatch on replica death.
``--kill-replica I --kill-at-step S`` arms a real SIGKILL inside
replica I's decode loop (``DS_TPU_SERVE_INJECT``), and
``--expect-redispatch N`` gates the exit code on the fleet actually
recovering.

Exit codes: 0 ok, 1 contract violation or unfinished requests,
2 usage errors.
"""

import argparse
import json
import sys

import numpy as np


def _build_requests(args, vocab_size, max_seq):
    from deepspeed_tpu.inference.scheduler import Request
    if args.requests:
        reqs = []
        with open(args.requests) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                reqs.append(Request(
                    rid=str(d.get("rid", f"r{i}")),
                    prompt=[int(t) for t in d["prompt"]],
                    max_new_tokens=int(
                        d.get("max_new_tokens", args.max_new)),
                    eos_id=d.get("eos_id"),
                    arrival_step=int(d.get("arrival_step", 0)),
                    session_id=d.get("session_id"),
                    deadline_s=d.get("deadline_s", args.deadline_s),
                    queue_timeout_s=d.get("queue_timeout_s",
                                          args.queue_timeout_s)))
        return reqs
    # synthetic open-loop stream: varied prompt lengths spanning the
    # buckets, staggered arrivals, deterministic under --seed. With
    # --shared-prefix N every prompt opens with the same N tokens (a
    # common system prompt) so a paged engine's radix cache gets hits.
    rng = np.random.default_rng(args.seed)
    shared = rng.integers(
        0, vocab_size, args.shared_prefix).tolist() \
        if args.shared_prefix else []
    reqs = []
    for i in range(args.synthetic):
        plen = int(rng.integers(2, max(3, args.synthetic_max_prompt)))
        tail = rng.integers(0, vocab_size, plen).tolist()
        prompt = (shared + tail)[:max_seq - 1]
        reqs.append(Request(
            rid=f"s{i}",
            prompt=prompt,
            max_new_tokens=args.max_new,
            arrival_step=int(i * args.arrival_every),
            deadline_s=args.deadline_s,
            queue_timeout_s=args.queue_timeout_s))
    return reqs


# gpt2_tiny's fixed test vocab — the synthetic stream only needs the
# token range, so fleet mode doesn't build a model in the parent
_TINY_VOCAB = 256


def _scale_blocks(params, scale):
    """Damp every block's residual-branch output projections
    (attn/mlp ``c_proj`` kernels) by ``scale``.

    Seeded-random weights give each block a ~unit-RMS output riding on
    a 0.02-RMS embedding stream, so a truncated-depth draft diverges
    from the full model immediately and speculative acceptance sits at
    chance (~1/vocab). Trained transformers converge through depth;
    ``--block-scale 0.1`` emulates that residual-stream convergence so
    the CI mean-accepted gate measures the accept machinery, not the
    entropy of random init."""
    def walk(tree, path):
        if hasattr(tree, "items"):
            return {k: walk(v, path + (str(k),))
                    for k, v in tree.items()}
        if "c_proj" in path and path[-1] == "kernel":
            return tree * scale
        return tree

    return walk(params, ())


def _load_checkpoint_model(args, jax, jnp):
    """Serve a real trained checkpoint: resolve + load a
    `runtime/resilience/checkpoint.py` manifest, take its fp32 master
    params, infer the GPT-2 geometry from leaf shapes, and convert the
    layer layout (the elastic ``param_layout`` metadata: ``stacked``
    scan_layers vs ``per_layer`` unrolled) to the requested serving
    variant — training→serving handoff in one command. Checkpoints
    saved under a different tensor-parallel topology need a
    ``ds_tpu_reshard`` relayout first (single-host serving reads
    replicated host leaves)."""
    import re

    from deepspeed_tpu.models.gpt2 import (
        GPT2Config,
        GPT2LMHead,
        stack_gpt2_layer_params,
        unstack_gpt2_layer_params,
    )
    from deepspeed_tpu.runtime.resilience.checkpoint import (
        CheckpointManager)

    mgr = CheckpointManager()
    tag = mgr.resolve_tag(args.checkpoint, args.ckpt_tag)
    if tag is None:
        raise SystemExit(
            f"ds_tpu_serve: no valid checkpoint under {args.checkpoint}")
    state, meta, path = mgr.load(args.checkpoint, tag)
    if "params" not in state:
        raise SystemExit(
            f"ds_tpu_serve: checkpoint {path} carries no 'params' tree")
    params = state["params"]
    topo = (meta or {}).get("topology") or {}
    saved_tp = int((topo.get("mesh_shape") or {}).get("model", 1) or 1)
    if saved_tp > 1:
        print(f"note: checkpoint {tag} was saved on a model-parallel "
              f"mesh (model axis {saved_tp}); if its leaves were "
              f"persisted sharded, relayout with ds_tpu_reshard before "
              f"serving", file=sys.stderr)
    # layer-layout conversion: the round trip is bit-exact, so a
    # per-layer training checkpoint serves as scan_layers and back
    if args.scan_layers and "h" not in params:
        params = stack_gpt2_layer_params(params)
    elif not args.scan_layers and "h" in params:
        params = unstack_gpt2_layer_params(params)
    wte, wpe = params["wte"], params["wpe"]
    if "h" in params:
        n_layer = int(jax.tree_util.tree_leaves(params["h"])[0].shape[0])
    else:
        n_layer = len([k for k in params
                       if re.match(r"^h_\d+$", str(k))])
    n_embd = int(wte.shape[1])
    if n_embd % args.n_head:
        raise SystemExit(
            f"ds_tpu_serve: --n-head {args.n_head} does not divide the "
            f"checkpoint's n_embd {n_embd}")
    cfg = GPT2Config(
        vocab_size=int(wte.shape[0]), n_positions=int(wpe.shape[0]),
        n_embd=n_embd, n_layer=n_layer, n_head=args.n_head,
        dropout=0.0, dtype=jnp.float32, param_dtype=jnp.float32,
        scan_layers=args.scan_layers)
    params = jax.tree_util.tree_map(jnp.asarray, params)
    return GPT2LMHead(cfg), params, {"tag": tag, "path": path,
                                     "n_layer": n_layer,
                                     "n_embd": n_embd,
                                     "vocab_size": cfg.vocab_size,
                                     "param_layout": topo.get(
                                         "param_layout")}


def _run_fleet(args, inf_cfg, session):
    """Serve through the N-replica fleet router (ISSUE 17)."""
    import os
    import tempfile

    from deepspeed_tpu.inference import fleet as fleet_mod
    from deepspeed_tpu.inference.router import FleetRouter

    workdir = os.path.abspath(
        args.workdir or tempfile.mkdtemp(prefix="ds-tpu-fleet-"))
    max_seq = max(inf_cfg.get("seq_buckets", (16, 32)))
    requests = _build_requests(args, _TINY_VOCAB, max_seq)

    inject = None
    if args.kill_replica is not None:
        inject = {"kill": {"op": "decode_step",
                           "at_step": args.kill_at_step}}
    spec = {"inf_cfg": {k: (list(v) if isinstance(v, tuple) else v)
                        for k, v in inf_cfg.items()},
            "seed": args.seed, "scan_layers": args.scan_layers}

    if args.replica_backend == "process":
        replicas = []
        for i in range(args.replicas):
            rspec = dict(spec, jsonl=os.path.join(
                workdir, f"replica{i}.jsonl"))
            replicas.append(fleet_mod.ProcessReplica(
                i, rspec, workdir, num_replicas=args.replicas,
                inject=inject if i == args.kill_replica else None,
                hang_timeout_s=args.hang_timeout_s,
                heartbeat_stale_s=args.heartbeat_stale_s).start())
        for r in replicas:
            r.wait_ready()
    else:
        import jax
        import jax.numpy as jnp
        from deepspeed_tpu.inference.engine import InferenceEngine
        from deepspeed_tpu.models.gpt2 import GPT2LMHead, gpt2_tiny

        def factory():
            cfg = gpt2_tiny(n_embd=32, dtype=jnp.float32,
                            scan_layers=args.scan_layers)
            model = GPT2LMHead(cfg)
            params = model.init(jax.random.PRNGKey(args.seed),
                                jnp.zeros((1, 8), jnp.int32))["params"]
            return InferenceEngine(model, params, config=inf_cfg)

        replicas = [fleet_mod.ThreadReplica(i, factory).start()
                    for i in range(args.replicas)]

    router = FleetRouter(
        replicas, session=session,
        max_redispatch=(args.max_redispatch if args.max_redispatch
                        is not None
                        else int(inf_cfg.get("max_redispatch", 2))),
        max_queue_depth=(args.max_queue_depth if args.max_queue_depth
                         is not None
                         else int(inf_cfg.get("max_queue_depth", 8))),
        max_pending=args.max_pending)
    fr = router.run(requests, timeout_s=args.fleet_timeout)

    ok = fr.ok
    compiles_bad = []
    if args.expect_compiles is not None:
        for st in fr.stats:
            total = sum(n for n in st["compile_counts"].values()
                        if n is not None)
            if total != args.expect_compiles:
                compiles_bad.append((st["replica"], total))
        ok = ok and not compiles_bad
    redisp_ok = True
    if args.expect_redispatch is not None:
        redisp_ok = fr.redispatched_total >= args.expect_redispatch
        ok = ok and redisp_ok

    result = {
        "requests": len(requests),
        "completions": fr.completions,
        "fleet": {
            "replicas": fr.replicas,
            "backend": args.replica_backend,
            "replicas_dead": fr.replicas_dead,
            "dead_causes": dict(router.dead),
            "redispatched_total": fr.redispatched_total,
            "aborted": fr.aborted, "shed": fr.shed,
            "defers": fr.defers, "timeouts": fr.timeouts,
            "latency_s": fr.latency_s,
            "stats": fr.stats,
            "workdir": workdir,
        },
        "ok": ok,
    }
    if args.expect_compiles is not None:
        result["expect_compiles"] = args.expect_compiles
    if args.expect_redispatch is not None:
        result["expect_redispatch"] = args.expect_redispatch

    if args.as_json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        for c in fr.completions:
            extra = ""
            if c["redispatched"]:
                extra = f", redispatched x{c['redispatched']}"
            print(f"{c['rid']}: prompt {c['prompt_len']} tokens -> "
                  f"{len(c['tokens'])} generated "
                  f"({c['finish_reason']}, replica {c['replica']}"
                  f"{extra})")
        fl = result["fleet"]
        print(f"{len(fr.completions)}/{len(requests)} requests "
              f"completed on {fl['replicas']} replica(s) "
              f"({fl['replicas_dead']} died: {fl['dead_causes']}); "
              f"redispatched={fl['redispatched_total']} "
              f"aborted={fl['aborted']} shed={fl['shed']} "
              f"timeouts={fl['timeouts']}")
        for st in fr.stats:
            cc = st["compile_counts"]
            print(f"replica {st['replica']}: {st['completed']} "
                  f"completed in {st['steps']} step(s); compiles: "
                  f"prefill={cc.get('prefill')} "
                  f"decode={cc.get('decode')}")
        if not ok:
            if compiles_bad:
                why = (f"replica compile counts {compiles_bad} != "
                       f"expected {args.expect_compiles}")
            elif not redisp_ok:
                why = (f"redispatched {fr.redispatched_total} < "
                       f"expected {args.expect_redispatch}")
            else:
                why = ("unfinished/aborted/shed/timed-out requests "
                       "in the fleet result")
            print(f"FAIL: {why}", file=sys.stderr)
    return 0 if ok else 1


def _run_disagg(args, inf_cfg, session):
    """Serve through disaggregated prefill/decode tiers (ISSUE 20).

    Each tier pins exactly ONE compiled program warmup-to-drain — the
    prefill tier never enters the decode jit and vice versa — so the
    fleet-wide compile total is 2 regardless of worker counts. The
    process backend hands KV off through a durable
    ``FileHandoffStore`` under ``workdir/handoff`` (CRC-verified, park/
    resume survives a dead decode worker); the thread backend uses the
    consume-once device-to-device ``DeviceHandoffStore``."""
    import os
    import tempfile

    from deepspeed_tpu.inference import fleet as fleet_mod
    from deepspeed_tpu.inference.router import DisaggRouter

    workdir = os.path.abspath(
        args.workdir or tempfile.mkdtemp(prefix="ds-tpu-disagg-"))
    max_seq = max(inf_cfg.get("seq_buckets", (16, 32)))
    requests = _build_requests(args, _TINY_VOCAB, max_seq)

    n_pre, n_dec = args.prefill_workers, args.decode_workers
    total = n_pre + n_dec

    def tier_inf(tier):
        # per-tier engine config: tiers scale max_batch independently
        # (0 / unset falls back to the shared max_batch)
        cfg = {k: v for k, v in inf_cfg.items()
               if k not in ("disaggregated", "prefill_workers",
                            "decode_workers", "prefill_max_batch",
                            "decode_max_batch")}
        mb = (args.prefill_max_batch if tier == "prefill"
              else args.decode_max_batch)
        if mb is None:
            mb = int(inf_cfg.get(f"{tier}_max_batch", 0) or 0)
        if mb:
            cfg["max_batch"] = mb
        return cfg

    inject = None
    if args.kill_prefill_worker is not None:
        inject = {"kill": {"op": "prefill_chunk",
                           "at_step": args.kill_at_step}}

    if args.replica_backend == "process":
        from deepspeed_tpu.inference.disagg import FileHandoffStore
        handoff_dir = os.path.join(workdir, "handoff")
        # the router shares the workers' durable store: parked()/drop()
        # are plain file probes, so tier-aware recovery works from the
        # parent without touching any device state
        store = FileHandoffStore(handoff_dir)

        def spawn(i, tier, tag, inj):
            cfg = {k: (list(v) if isinstance(v, tuple) else v)
                   for k, v in tier_inf(tier).items()}
            rspec = {"inf_cfg": cfg, "seed": args.seed,
                     "scan_layers": args.scan_layers, "tier": tier,
                     "handoff_dir": handoff_dir,
                     "jsonl": os.path.join(workdir, f"{tag}.jsonl")}
            return fleet_mod.TierProcessReplica(
                i, rspec, workdir, num_replicas=total, inject=inj,
                hang_timeout_s=args.hang_timeout_s,
                heartbeat_stale_s=args.heartbeat_stale_s).start()

        # globally-unique indices across tiers: prefill 0..N-1,
        # decode N..N+M-1 (heartbeats/done markers share the workdir)
        prefill = [spawn(i, "prefill", f"prefill{i}",
                         inject if i == args.kill_prefill_worker
                         else None)
                   for i in range(n_pre)]
        decode = [spawn(n_pre + j, "decode", f"decode{j}", None)
                  for j in range(n_dec)]
        for r in prefill + decode:
            r.wait_ready()
    else:
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.inference.disagg import (
            DecodeWorker, DeviceHandoffStore, PrefillWorker)
        from deepspeed_tpu.inference.engine import InferenceEngine
        from deepspeed_tpu.models.gpt2 import GPT2LMHead, gpt2_tiny

        store = DeviceHandoffStore()

        def make_factory(tier):
            cfg_t = dict(tier_inf(tier), tier=tier)

            def factory():
                cfg = gpt2_tiny(n_embd=32, dtype=jnp.float32,
                                scan_layers=args.scan_layers)
                model = GPT2LMHead(cfg)
                params = model.init(
                    jax.random.PRNGKey(args.seed),
                    jnp.zeros((1, 8), jnp.int32))["params"]
                engine = InferenceEngine(model, params, config=cfg_t)
                cls = (PrefillWorker if tier == "prefill"
                       else DecodeWorker)
                return cls(engine, store)
            return factory

        prefill = [fleet_mod.TierThreadReplica(
            i, make_factory("prefill")).start() for i in range(n_pre)]
        decode = [fleet_mod.TierThreadReplica(
            n_pre + j, make_factory("decode")).start()
            for j in range(n_dec)]

    router = DisaggRouter(
        prefill, decode, store, session=session,
        max_redispatch=(args.max_redispatch if args.max_redispatch
                        is not None
                        else int(inf_cfg.get("max_redispatch", 2))),
        max_queue_depth=(args.max_queue_depth if args.max_queue_depth
                         is not None
                         else int(inf_cfg.get("max_queue_depth", 8))),
        max_pending=args.max_pending)
    fr = router.run(requests, timeout_s=args.fleet_timeout)

    # the one-program-per-tier pin is intrinsic to disaggregation:
    # every surviving worker must hold exactly its own tier's program
    # and never have entered the other one
    pins = {"prefill": {"prefill": 1, "decode": 0},
            "decode": {"prefill": 0, "decode": 1}}
    tier_bad = []
    programs = set()
    for st in fr.stats:
        cc = st.get("compile_counts") or {}
        got = {"prefill": cc.get("prefill") or 0,
               "decode": cc.get("decode") or 0}
        programs.update(k for k, v in got.items() if v)
        if got != pins[st["tier"]]:
            tier_bad.append((st["replica"], st["tier"], got))
    # the fleet census counts DISTINCT programs, not jit entries:
    # every worker necessarily holds its own cache entry for its
    # tier's one program, so entries scale with worker count while the
    # program count stays 2 — and the pin check above already fails
    # any worker holding more than its single program
    total_compiles = len(programs)
    ok = fr.ok and not tier_bad
    compiles_ok = True
    if args.expect_compiles is not None:
        compiles_ok = total_compiles == args.expect_compiles
        ok = ok and compiles_ok
    redisp_ok = True
    if args.expect_redispatch is not None:
        redisp_ok = fr.redispatched_total >= args.expect_redispatch
        ok = ok and redisp_ok

    result = {
        "requests": len(requests),
        "completions": fr.completions,
        "disagg": {
            "backend": args.replica_backend,
            "prefill_workers": fr.prefill_replicas,
            "decode_workers": fr.decode_replicas,
            "replicas_dead": fr.replicas_dead,
            "dead_by_tier": fr.dead_by_tier,
            "dead_causes": dict(router.dead),
            "redispatched_total": fr.redispatched_total,
            "aborted": fr.aborted, "shed": fr.shed,
            "defers": fr.defers, "timeouts": fr.timeouts,
            "handoffs": fr.handoffs,
            "handoff_bytes": fr.handoff_bytes,
            "handoff_bytes_per_session": (
                fr.handoff_bytes / fr.handoffs if fr.handoffs else 0.0),
            "handoff_corrupt": fr.handoff_corrupt,
            "resumed_from_park": fr.resumed_from_park,
            "latency_s": fr.latency_s,
            "ttft_s": fr.ttft_s,
            "total_compiles": total_compiles,
            "stats": fr.stats,
            "workdir": workdir,
        },
        "ok": ok,
    }
    if args.expect_compiles is not None:
        result["expect_compiles"] = args.expect_compiles
    if args.expect_redispatch is not None:
        result["expect_redispatch"] = args.expect_redispatch

    if args.as_json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        for c in fr.completions:
            extra = ""
            if c.get("redispatched"):
                extra += f", redispatched x{c['redispatched']}"
            if c.get("restarts"):
                extra += f", re-prefilled x{c['restarts']}"
            print(f"{c['rid']}: prompt {c['prompt_len']} tokens -> "
                  f"{len(c['tokens'])} generated "
                  f"({c['finish_reason']}, replica {c['replica']}"
                  f"{extra})")
        dg = result["disagg"]
        print(f"{len(fr.completions)}/{len(requests)} requests "
              f"completed across {dg['prefill_workers']}+"
              f"{dg['decode_workers']} tiered worker(s) "
              f"({dg['replicas_dead']} died: {dg['dead_causes']}); "
              f"redispatched={dg['redispatched_total']} "
              f"aborted={dg['aborted']} timeouts={dg['timeouts']}")
        for tier in ("prefill", "decode"):
            sts = [s for s in fr.stats if s["tier"] == tier]
            # distinct programs the tier's workers hold (1 each when
            # the pins are honored, whatever the worker count)
            tp = {"prefill": 0, "decode": 0}
            for s in sts:
                for k, v in (s.get("compile_counts") or {}).items():
                    if v:
                        tp[k] = 1
            done = sum(int(s.get("completed", 0)) for s in sts)
            print(f"{tier} tier: {len(sts)} surviving worker(s), "
                  f"{done} completion(s); compiles: "
                  f"prefill={tp['prefill']} decode={tp['decode']}")
        def _ms(v):
            return "n/a" if v is None else f"{v * 1e3:.1f}ms"
        tt, lat = fr.ttft_s, fr.latency_s
        print(f"handoff: {dg['handoffs']} session(s), "
              f"{dg['handoff_bytes']} byte(s) "
              f"({dg['handoff_bytes_per_session']:.0f}/session), "
              f"corrupt={dg['handoff_corrupt']} "
              f"resumed_from_park={dg['resumed_from_park']}; "
              f"ttft p50={_ms(tt['p50'])} p95={_ms(tt['p95'])} "
              f"p99={_ms(tt['p99'])}; latency p99={_ms(lat['p99'])}")
        if not ok:
            if tier_bad:
                why = (f"per-tier compile pins violated: {tier_bad} "
                       f"(each worker must hold exactly one program, "
                       f"its own tier's)")
            elif not compiles_ok:
                why = (f"fleet compile total {total_compiles} != "
                       f"expected {args.expect_compiles}")
            elif not redisp_ok:
                why = (f"redispatched {fr.redispatched_total} < "
                       f"expected {args.expect_redispatch}")
            else:
                why = ("unfinished/aborted/shed/timed-out requests "
                       "in the disaggregated result")
            print(f"FAIL: {why}", file=sys.stderr)
    return 0 if ok else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_tpu_serve",
        description="run the jitted serving engine over a request "
                    "stream (continuous batching, bucketed KV cache)")
    parser.add_argument("--config", default=None,
                        help="DeepSpeed-style JSON config; its "
                             "'inference' block configures the engine")
    parser.add_argument("--scan-layers", action="store_true",
                        help="serve the scan_layers model variant")
    parser.add_argument("--kv-cache-dtype", default=None,
                        help="override cache storage: bf16, f32, or a "
                             "codec name (int8, f8e4m3fn, f8e5m2)")
    parser.add_argument("--max-batch", type=int, default=None,
                        help="override inference.max_batch")
    parser.add_argument("--seq-buckets", default=None,
                        help="override inference.seq_buckets, e.g. 16,32")
    parser.add_argument("--prefill-chunk", type=int, default=None,
                        help="override inference.prefill_chunk")
    parser.add_argument("--attention", default=None,
                        choices=("dense", "flash"),
                        help="decode attention impl: dense softmax or "
                             "the Pallas flash-decode kernel")
    parser.add_argument("--block-k", type=int, default=None,
                        help="flash-decode KV block size (must divide "
                             "max(seq_buckets))")
    parser.add_argument("--kv-layout", default=None,
                        choices=("ring", "paged"),
                        help="KV cache layout: per-row ring buffers or "
                             "the paged pool with radix prefix sharing")
    parser.add_argument("--page-size", type=int, default=None,
                        help="paged layout: tokens per KV page (0 = "
                             "auto; must be a multiple of "
                             "prefill_chunk and divide max seq bucket)")
    parser.add_argument("--n-pages", type=int, default=None,
                        help="paged layout: physical pool pages "
                             "(0 = auto; page 0 is the trash page)")
    parser.add_argument("--prefix-cache", dest="prefix_cache",
                        action="store_true", default=None,
                        help="paged layout: intern finished prompts in "
                             "the radix prefix cache (default on)")
    parser.add_argument("--no-prefix-cache", dest="prefix_cache",
                        action="store_false",
                        help="paged layout: disable prefix sharing")
    parser.add_argument("--park-threshold", type=float, default=None,
                        help="paged layout: evacuate parked sessions "
                             "to host RAM when the free-page fraction "
                             "drops below this (0 disables)")
    parser.add_argument("--shared-prefix", type=int, default=0,
                        help="synthetic stream: open every prompt with "
                             "the same N tokens (a shared system "
                             "prompt) to exercise the prefix cache")
    parser.add_argument("--expect-prefix-hits", type=int, default=None,
                        help="exit 1 unless the paged prefix cache "
                             "recorded at least this many hits")
    parser.add_argument("--temperature", type=float, default=None,
                        help="sampling temperature (0 = greedy argmax, "
                             "the default)")
    parser.add_argument("--top-k", type=int, default=None,
                        help="keep only the k most likely tokens "
                             "(0 = disabled)")
    parser.add_argument("--top-p", type=float, default=None,
                        help="nucleus sampling mass (1.0 = disabled)")
    # -- speculative decoding (ISSUE 18) --------------------------------
    parser.add_argument("--speculative", action="store_true",
                        help="self-speculative decoding: draft k "
                             "tokens through the first draft_layers "
                             "blocks, verify all of them in one "
                             "full-depth forward")
    parser.add_argument("--spec-k", type=int, default=4,
                        help="draft window: tokens drafted per verify "
                             "round (>= 1)")
    parser.add_argument("--draft-layers", type=int, default=0,
                        help="transformer blocks the draft pass runs "
                             "(0 = auto n_layer // 2)")
    parser.add_argument("--min-accept-to-grow", type=float, default=0.0,
                        help="adaptive draft length: grow the window "
                             "when mean accepted drafts/round clears "
                             "this, shrink when it doesn't (0 = fixed "
                             "window)")
    parser.add_argument("--block-scale", type=float, default=None,
                        help="damp every block's c_proj kernels by "
                             "this factor; emulates trained residual "
                             "convergence so seeded-random weights "
                             "give measurable draft acceptance")
    parser.add_argument("--expect-min-accepted", type=float,
                        default=None,
                        help="exit 1 unless mean accepted tokens per "
                             "speculative round clears this")
    # -- checkpoint serving (ISSUE 18) ----------------------------------
    parser.add_argument("--checkpoint", default=None,
                        help="serve params from this training "
                             "checkpoint dir (runtime/resilience "
                             "manifest layout) instead of seeded "
                             "random weights")
    parser.add_argument("--ckpt-tag", default=None,
                        help="checkpoint tag to load (default: the "
                             "newest valid one)")
    parser.add_argument("--n-head", type=int, default=4,
                        help="attention heads for --checkpoint serving "
                             "(not recoverable from param shapes)")
    parser.add_argument("--requests", default=None,
                        help="JSONL request stream (one request/line)")
    parser.add_argument("--synthetic", type=int, default=0,
                        help="generate N synthetic open-loop requests "
                             "instead of --requests")
    parser.add_argument("--synthetic-max-prompt", type=int, default=24,
                        help="synthetic prompt length upper bound")
    parser.add_argument("--arrival-every", type=float, default=1.0,
                        help="synthetic arrival spacing in decode steps")
    parser.add_argument("--max-new", type=int, default=8,
                        help="default max_new_tokens per request")
    parser.add_argument("--seed", type=int, default=0,
                        help="params + synthetic stream seed")
    parser.add_argument("--expect-compiles", type=int, default=None,
                        help="exit 1 unless total jit cache entries "
                             "(prefill + decode) equal exactly this")
    parser.add_argument("--jsonl", default=None,
                        help="write decode_step telemetry events here "
                             "(ds_tpu_metrics summary serve mode)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="print the result dict as JSON")
    # -- fleet mode (ISSUE 17) ------------------------------------------
    parser.add_argument("--replicas", type=int, default=1,
                        help="serve through an N-replica fleet behind "
                             "the admission router (N >= 2)")
    parser.add_argument("--replica-backend", default="process",
                        choices=("process", "thread"),
                        help="fleet replicas: real subprocess workers "
                             "(SIGKILL-able) or in-process threads")
    parser.add_argument("--workdir", default=None,
                        help="fleet workdir (heartbeats, done markers, "
                             "replica logs); default: a temp dir")
    parser.add_argument("--deadline-s", type=float, default=None,
                        help="per-request total wall-clock deadline "
                             "(typed 'timeout' finish reason)")
    parser.add_argument("--queue-timeout-s", type=float, default=None,
                        help="per-request bound on queue wait before "
                             "admission (typed 'timeout')")
    parser.add_argument("--max-redispatch", type=int, default=None,
                        help="redispatches before a request aborts "
                             "(typed RequestAbortedError path)")
    parser.add_argument("--max-queue-depth", type=int, default=None,
                        help="per-replica in-flight bound (router "
                             "defers past it, emitting fleet_defer)")
    parser.add_argument("--max-pending", type=int, default=None,
                        help="global admission bound (router sheds "
                             "past it, emitting fleet_shed)")
    parser.add_argument("--fleet-timeout", type=float, default=300.0,
                        help="whole-fleet drive-loop wall bound")
    parser.add_argument("--hang-timeout-s", type=float, default=None,
                        help="replica heartbeat stuck-in-step bound")
    parser.add_argument("--heartbeat-stale-s", type=float, default=None,
                        help="replica heartbeat staleness bound")
    parser.add_argument("--kill-replica", type=int, default=None,
                        help="arm a SIGKILL fault in this replica index")
    parser.add_argument("--kill-at-step", type=int, default=3,
                        help="decode step the armed kill fires at")
    parser.add_argument("--expect-redispatch", type=int, default=None,
                        help="exit 1 unless the fleet redispatched at "
                             "least this many requests")
    # -- disaggregated prefill/decode tiers (ISSUE 20) -------------------
    parser.add_argument("--disaggregate", action="store_true",
                        help="split serving into a prefill tier and a "
                             "decode tier (one compiled program each; "
                             "KV pages hand off through the paged "
                             "store between tiers)")
    parser.add_argument("--prefill-workers", type=int, default=None,
                        help="disaggregated: prefill-tier worker count "
                             "(default from config, else 1)")
    parser.add_argument("--decode-workers", type=int, default=None,
                        help="disaggregated: decode-tier worker count "
                             "(default from config, else 1)")
    parser.add_argument("--prefill-max-batch", type=int, default=None,
                        help="disaggregated: prefill-tier max_batch "
                             "override (0/unset = shared max_batch)")
    parser.add_argument("--decode-max-batch", type=int, default=None,
                        help="disaggregated: decode-tier max_batch "
                             "override (0/unset = shared max_batch)")
    parser.add_argument("--kill-prefill-worker", type=int, default=None,
                        help="arm a SIGKILL mid-prefill-chunk in this "
                             "prefill-tier worker index (process "
                             "backend)")
    args = parser.parse_args(argv)

    if not args.requests and not args.synthetic:
        parser.error("one of --requests or --synthetic N is required")
    if args.requests and args.synthetic:
        parser.error("--requests and --synthetic are mutually exclusive")
    if args.replicas < 1:
        parser.error("--replicas must be >= 1")
    if args.replicas == 1 and args.kill_replica is not None:
        parser.error("--kill-replica requires --replicas >= 2 (use "
                     "--kill-prefill-worker with --disaggregate)")
    if args.replicas == 1 and args.expect_redispatch is not None \
            and not args.disaggregate:
        parser.error("--expect-redispatch requires --replicas >= 2 "
                     "or --disaggregate")
    if args.disaggregate:
        if args.speculative:
            parser.error("--disaggregate excludes --speculative (the "
                         "draft/verify pair would break the one-"
                         "program-per-tier contract)")
        if args.replicas > 1:
            parser.error("--disaggregate and --replicas are mutually "
                         "exclusive; tiers scale via "
                         "--prefill-workers/--decode-workers")
        if args.checkpoint:
            parser.error("--disaggregate serves the seeded test model "
                         "only (no --checkpoint)")
    if args.kill_prefill_worker is not None:
        if not args.disaggregate:
            parser.error("--kill-prefill-worker requires --disaggregate")
        if args.replica_backend != "process":
            parser.error("--kill-prefill-worker needs --replica-backend "
                         "process (a thread cannot be SIGKILLed in "
                         "isolation)")
    for name in ("prefill_workers", "decode_workers"):
        v = getattr(args, name)
        if v is not None and v < 1:
            parser.error(f"--{name.replace('_', '-')} must be >= 1")
    if args.kill_replica is not None and \
            not 0 <= args.kill_replica < args.replicas:
        parser.error(f"--kill-replica {args.kill_replica} outside "
                     f"0..{args.replicas - 1}")
    if args.kill_replica is not None and \
            args.replica_backend != "process":
        parser.error("--kill-replica needs --replica-backend process "
                     "(a thread cannot be SIGKILLed in isolation)")
    if args.speculative and args.replicas > 1:
        parser.error("--speculative is single-replica only (the fleet "
                     "router has no variable-tokens-per-step protocol "
                     "yet)")
    if args.expect_min_accepted is not None and not args.speculative:
        parser.error("--expect-min-accepted requires --speculative")
    if args.checkpoint and args.replicas > 1:
        parser.error("--checkpoint serving is single-replica only")
    if args.spec_k < 1:
        parser.error("--spec-k must be >= 1")
    if args.draft_layers < 0:
        parser.error("--draft-layers must be >= 0 (0 = auto)")
    if args.n_head < 1:
        parser.error("--n-head must be >= 1")

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.scheduler import (
        ContinuousBatchingScheduler)
    from deepspeed_tpu.models.gpt2 import GPT2LMHead, gpt2_tiny
    from deepspeed_tpu.telemetry.session import TelemetrySession

    inf_cfg = {"max_batch": 2, "seq_buckets": (16, 32),
               "prefill_chunk": 4}
    if args.config:
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        with open(args.config) as f:
            raw = json.load(f)
        # a serving config needn't carry training batch sizes; give the
        # validator trivial ones (world_size pinned to 1 — serving does
        # no data parallelism) so only the inference block matters
        raw.setdefault("train_batch_size", 1)
        raw.setdefault("train_micro_batch_size_per_gpu", 1)
        ds = DeepSpeedConfig(raw, world_size=1)
        inf = ds.inference
        inf_cfg = {"max_batch": inf.max_batch,
                   "seq_buckets": inf.seq_buckets,
                   "prefill_chunk": inf.prefill_chunk,
                   "kv_cache_dtype": inf.kv_cache_dtype,
                   "max_new_tokens": inf.max_new_tokens,
                   "attention_impl": inf.attention_impl,
                   "attention_block_k": inf.attention_block_k,
                   "temperature": inf.temperature,
                   "top_k": inf.top_k,
                   "top_p": inf.top_p,
                   "sampling_seed": inf.sampling_seed,
                   "kv_layout": inf.kv_layout,
                   "page_size": inf.page_size,
                   "n_pages": inf.n_pages,
                   "prefix_cache": inf.prefix_cache,
                   "host_park_threshold": inf.host_park_threshold,
                   "replicas": inf.replicas,
                   "max_redispatch": inf.max_redispatch,
                   "max_queue_depth": inf.max_queue_depth,
                   "deadline_s": inf.deadline_s,
                   "queue_timeout_s": inf.queue_timeout_s,
                   "speculative": inf.speculative,
                   "disaggregated": inf.disaggregated,
                   "prefill_workers": inf.prefill_workers,
                   "decode_workers": inf.decode_workers,
                   "prefill_max_batch": inf.prefill_max_batch,
                   "decode_max_batch": inf.decode_max_batch}
    if args.max_batch is not None:
        inf_cfg["max_batch"] = args.max_batch
    if args.seq_buckets is not None:
        inf_cfg["seq_buckets"] = tuple(
            int(b) for b in args.seq_buckets.split(",") if b.strip())
    if args.prefill_chunk is not None:
        inf_cfg["prefill_chunk"] = args.prefill_chunk
    if args.kv_cache_dtype is not None:
        inf_cfg["kv_cache_dtype"] = args.kv_cache_dtype
    if args.attention is not None:
        inf_cfg["attention_impl"] = args.attention
    if args.block_k is not None:
        inf_cfg["attention_block_k"] = args.block_k
    if args.temperature is not None:
        inf_cfg["temperature"] = args.temperature
    if args.top_k is not None:
        inf_cfg["top_k"] = args.top_k
    if args.top_p is not None:
        inf_cfg["top_p"] = args.top_p
    if args.kv_layout is not None:
        inf_cfg["kv_layout"] = args.kv_layout
    if args.page_size is not None:
        inf_cfg["page_size"] = args.page_size
    if args.n_pages is not None:
        inf_cfg["n_pages"] = args.n_pages
    if args.prefix_cache is not None:
        inf_cfg["prefix_cache"] = args.prefix_cache
    if args.park_threshold is not None:
        inf_cfg["host_park_threshold"] = args.park_threshold
    if args.speculative:
        inf_cfg["speculative"] = {
            "enabled": True, "k": args.spec_k,
            "draft_layers": args.draft_layers,
            "min_accept_to_grow": args.min_accept_to_grow}
    if args.expect_prefix_hits is not None and \
            inf_cfg.get("kv_layout", "ring") != "paged":
        parser.error("--expect-prefix-hits requires --kv-layout paged")
    # --seed doubles as the sampling seed: one knob pins params, the
    # synthetic stream, AND the in-program sampler, so a serve is
    # reproducible end to end (a non-default --seed beats the config).
    if args.seed != 0 or "sampling_seed" not in inf_cfg:
        inf_cfg["sampling_seed"] = args.seed

    session = None
    if args.jsonl:
        from deepspeed_tpu.telemetry.exporters import JsonlExporter
        session = TelemetrySession(exporters=[JsonlExporter(args.jsonl)])

    # config-file fleet/deadline knobs apply when the flags stay at
    # their defaults (0 in the config block means disabled)
    args.replicas = max(args.replicas, int(inf_cfg.get("replicas", 1)
                                           or 1))
    if args.deadline_s is None:
        args.deadline_s = inf_cfg.get("deadline_s") or None
    if args.queue_timeout_s is None:
        args.queue_timeout_s = inf_cfg.get("queue_timeout_s") or None
    args.disaggregate = args.disaggregate or bool(
        inf_cfg.get("disaggregated"))
    if args.disaggregate:
        if inf_cfg.get("kv_layout", "ring") != "paged":
            parser.error("--disaggregate requires --kv-layout paged "
                         "(the prefill->decode handoff is a KV page "
                         "copy)")
        if inf_cfg.get("speculative"):
            parser.error("config enables speculative decoding but the "
                         "serve is disaggregated; the tiers pin one "
                         "program each")
        if args.prefill_workers is None:
            args.prefill_workers = int(
                inf_cfg.get("prefill_workers", 1) or 1)
        if args.decode_workers is None:
            args.decode_workers = int(
                inf_cfg.get("decode_workers", 1) or 1)
        if args.kill_prefill_worker is not None and not \
                0 <= args.kill_prefill_worker < args.prefill_workers:
            parser.error(f"--kill-prefill-worker "
                         f"{args.kill_prefill_worker} outside "
                         f"0..{args.prefill_workers - 1}")
        return _run_disagg(args, inf_cfg, session)
    if args.replicas > 1:
        if inf_cfg.get("speculative"):
            parser.error("config enables speculative decoding but the "
                         "serve is fleet-mode; run single-replica")
        return _run_fleet(args, inf_cfg, session)

    ckpt_info = None
    if args.checkpoint:
        model, params, ckpt_info = _load_checkpoint_model(args, jax, jnp)
        cfg = model.config
    else:
        cfg = gpt2_tiny(n_embd=32, dtype=jnp.float32,
                        scan_layers=args.scan_layers)
        model = GPT2LMHead(cfg)
        toks = jnp.zeros((1, 8), jnp.int32)
        params = model.init(jax.random.PRNGKey(args.seed),
                            toks)["params"]
    if args.block_scale is not None:
        params = _scale_blocks(params, args.block_scale)
    engine = InferenceEngine(model, params, config=inf_cfg,
                             session=session)
    sched = ContinuousBatchingScheduler(engine)

    requests = _build_requests(args, cfg.vocab_size, engine.max_seq)
    completions = sched.run(requests)

    counts = engine.compile_counts()
    total_compiles = sum(n for n in counts.values() if n is not None)
    result = {
        "requests": len(requests),
        "completions": [
            {"rid": c.rid, "prompt_len": c.prompt_len,
             "tokens": c.tokens, "finish_reason": c.finish_reason,
             "bucket": c.bucket, "slot": c.slot, "steps": c.steps,
             "prefix_hit": c.prefix_hit, "resumed": c.resumed,
             "prefill_chunks": c.prefill_chunks,
             "prefill_chunks_skipped": c.prefill_chunks_skipped}
            for c in completions],
        "decode_steps": sched.step_count,
        "compile_counts": counts,
        "cache": engine.cache_facts(),
        "attention": {"impl": engine.attention_impl,
                      "block_k": engine.attention_block_k},
        "sampling": {"temperature": engine.temperature,
                     "top_k": engine.top_k, "top_p": engine.top_p,
                     "seed": engine.sampling_seed},
    }
    if sched.paging is not None:
        result["paging"] = sched.paging.facts()
    if engine.speculative is not None:
        result["speculative"] = engine.speculative.facts()
    if ckpt_info is not None:
        result["checkpoint"] = ckpt_info
    ok = len(completions) == len(requests)
    if args.expect_compiles is not None:
        result["expect_compiles"] = args.expect_compiles
        ok = ok and total_compiles == args.expect_compiles
    prefix_hits_ok = True
    if args.expect_prefix_hits is not None:
        hits = result["paging"]["prefix_hits"]
        result["expect_prefix_hits"] = args.expect_prefix_hits
        prefix_hits_ok = hits >= args.expect_prefix_hits
        ok = ok and prefix_hits_ok
    accepted_ok = True
    if args.expect_min_accepted is not None:
        mean_acc = result["speculative"]["mean_accepted"]
        result["expect_min_accepted"] = args.expect_min_accepted
        accepted_ok = mean_acc >= args.expect_min_accepted
        ok = ok and accepted_ok
    result["ok"] = ok

    if args.as_json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        for c in completions:
            extra = ""
            if c.prefix_hit or c.resumed:
                kind = "resumed" if c.resumed else "prefix hit"
                extra = (f", {kind}: skipped "
                         f"{c.prefill_chunks_skipped} prefill chunk(s)")
            print(f"{c.rid}: prompt {c.prompt_len} tokens -> "
                  f"{len(c.tokens)} generated ({c.finish_reason}, "
                  f"bucket {c.bucket}, slot {c.slot}{extra})")
        compiles = (f"prefill={counts['prefill']} "
                    f"decode={counts['decode']}")
        if engine.speculative is not None:
            compiles += (f" draft={counts['draft']} "
                         f"verify={counts['verify']}")
        print(f"{len(completions)}/{len(requests)} requests completed "
              f"in {sched.step_count} decode step(s); compiles: "
              f"{compiles}")
        if ckpt_info is not None:
            print(f"checkpoint: tag {ckpt_info['tag']} "
                  f"({ckpt_info['n_layer']}L/{ckpt_info['n_embd']}d, "
                  f"vocab {ckpt_info['vocab_size']}, saved layout "
                  f"{ckpt_info['param_layout']})")
        if engine.speculative is not None:
            sp = result["speculative"]
            print(f"speculative: k={sp['k']} "
                  f"draft_layers={sp['draft_layers']}/{sp['n_layer']}, "
                  f"mean accepted {sp['mean_accepted']:.3f} "
                  f"tokens/round over {sp['row_rounds']} row-round(s), "
                  f"draft efficiency {sp['draft_efficiency']:.3f}")
        if sched.paging is not None:
            pg = result["paging"]
            print(f"paged KV: {pg['pages_resident']}/{pg['n_pages']} "
                  f"pages resident, prefix hits {pg['prefix_hits']}/"
                  f"misses {pg['prefix_misses']}, host-parked "
                  f"{pg['sessions_parked_host']} session(s)")
        if not ok:
            if len(completions) != len(requests):
                why = "unfinished requests"
            elif not prefix_hits_ok:
                why = (f"prefix hits "
                       f"{result['paging']['prefix_hits']} < expected "
                       f"{args.expect_prefix_hits}")
            elif not accepted_ok:
                why = (f"mean accepted "
                       f"{result['speculative']['mean_accepted']:.3f} "
                       f"< expected {args.expect_min_accepted}")
            else:
                why = (f"compile count {total_compiles} != expected "
                       f"{args.expect_compiles}")
            print(f"FAIL: {why}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
