"""One serving-fleet replica worker: `python -m ...fleet_worker`.

Spawned by `inference/fleet.py:ProcessReplica` under the ``ds_tpu_run``
supervisor env contract. Protocol, all JSONL:

- stdin (router → worker): ``{"cmd": "submit", "request": {...}}`` and
  ``{"cmd": "stop"}``.
- stdout (worker → router): ``{"type": "ready", "pid": ...}`` once the
  engine is built, one ``{"type": "completion", "completion": {...}}``
  per finished request as it finishes (streamed — the router must see
  progress before the replica drains, or a mid-stream death would lose
  completed work), and a final ``{"type": "stats", ...}`` /
  ``{"type": "preempted", ...}``.
- ``hb-p<idx>.json`` heartbeat file in the workdir every loop tick
  (same schema as the hang watchdog's), suppressed while an armed
  ``heartbeat_stall`` fault is in effect.

Lifecycle contract (mirrors training workers):

- ``DS_TPU_SERVE_SPEC`` (env) carries the engine recipe: the inference
  config block, the params seed, ``scan_layers``, optional ``jsonl``
  telemetry path.
- Faults arm from ``DS_TPU_SERVE_INJECT`` only when
  ``DS_TPU_RUN_RESTART_COUNT`` is 0 (first attempt).
- Clean stop: drain, report stats, write ``done-p<idx:05d>``, exit 0.
- SIGTERM (``PreemptionHandler``): finish the CURRENT decode step, emit
  a durable ``preemption`` telemetry event, flush completed-so-far
  completions, exit 0 WITHOUT the done marker — which is exactly what
  ``classify_exit`` reads as a preemption.
- SIGKILL / injected decode faults: the process dies mid-stream; the
  router's health check classifies and redispatches.
"""

import collections
import json
import os
import socket
import sys
import threading
import time


def _out(msg):
    sys.stdout.write(json.dumps(msg) + "\n")
    sys.stdout.flush()


def _write_heartbeat(workdir, index, step, busy):
    from deepspeed_tpu.telemetry.watchdog import heartbeat_path
    hb = {
        "t": time.time(),
        "hostname": socket.gethostname(),
        "process_index": index,
        "pid": os.getpid(),
        "step": step,
        "phase": "serve",
        "in_step": busy,
        "step_elapsed_s": 0.0,
    }
    path = heartbeat_path(workdir, index)
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(hb, f)
        os.replace(tmp, path)
    except OSError:
        pass


class _CommandReader:
    """Blocking stdin reader on a daemon thread. select() over a
    buffered sys.stdin is a trap — readline() can pull several lines
    into Python's buffer while select() sees an empty fd, stranding
    commands — so a thread does blocking readline() and the serve loop
    drains the deque non-blockingly."""

    def __init__(self):
        self._lines = collections.deque()
        self._t = threading.Thread(target=self._loop, daemon=True,
                                   name="fleet-worker-stdin")
        self._t.start()

    def _loop(self):
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            try:
                self._lines.append(json.loads(line))
            except ValueError:
                pass
        self._lines.append({"cmd": "stop"})     # EOF: router is gone

    def drain(self):
        cmds = []
        while self._lines:
            try:
                cmds.append(self._lines.popleft())
            except IndexError:
                break
        return cmds


def _request_from(d):
    from deepspeed_tpu.inference.scheduler import Request
    return Request(
        rid=str(d["rid"]),
        prompt=[int(t) for t in d["prompt"]],
        max_new_tokens=int(d.get("max_new_tokens", 16)),
        eos_id=d.get("eos_id"),
        arrival_step=int(d.get("arrival_step", 0)),
        session_id=d.get("session_id"),
        deadline_s=d.get("deadline_s"),
        queue_timeout_s=d.get("queue_timeout_s"),
        redispatched=int(d.get("redispatched", 0)),
        restarts=int(d.get("restarts", 0)))


def _tier_loop(worker, engine, handler, reader, session, workdir,
               index):
    """Serve loop for a disaggregated TIER worker (``spec["tier"]``):
    same lifecycle contract as the colocated loop below — streamed
    outputs, heartbeats, preemption/stop semantics — but driving a
    `inference/disagg.py` PrefillWorker/DecodeWorker instead of the
    colocated scheduler. Handoff outputs travel as their own JSONL
    kinds (``prefilled``/``handoff_corrupt``/...)."""
    from deepspeed_tpu.runtime.resilience import fault_injection

    def _steps():
        return worker.sched.step_count if hasattr(worker, "sched") \
            else worker.steps

    reported = 0
    stalled_until = 0.0
    stopping = False
    while True:
        if not worker.has_work and not stopping:
            time.sleep(0.002)
        for cmd in reader.drain():
            if cmd.get("cmd") == "submit":
                worker.submit(_request_from(cmd["request"]),
                              cmd.get("handoff"))
            elif cmd.get("cmd") == "stop":
                stopping = True

        has_work = worker.has_work
        if has_work:
            worker.step()       # kill/decode fault probes fire inside

        for out in worker.drain_outputs():
            kind = out.pop("kind", "completion")
            if kind == "completion":
                reported += 1
                _out({"type": "completion", "completion": out})
            else:
                _out({"type": kind, "payload": out})

        now = time.time()
        stall = fault_injection.heartbeat_stall_seconds(_steps())
        if stall:
            stalled_until = now + stall
        if now >= stalled_until:
            _write_heartbeat(workdir, index, _steps(), has_work)

        if handler.preempted:
            if session is not None:
                session.emit("preemption", step=_steps(),
                             completed=reported, replica=index,
                             tier=worker.tier)
                session.close()
            _out({"type": "preempted", "completed": reported,
                  "steps": _steps(), "tier": worker.tier})
            return 0            # exit 0, NO done marker -> preemption

        if stopping and not has_work:
            break

    _out(dict(worker.stats(), type="stats", replica=index))
    if session is not None:
        session.close()
    from deepspeed_tpu.runtime.supervisor.supervisor import done_path
    with open(done_path(workdir, index), "w") as f:
        f.write("done\n")
    return 0


def main():
    index = int(os.environ.get("DS_TPU_RUN_PROCESS_INDEX", "0"))
    workdir = os.environ.get("DS_TPU_RUN_WORKDIR", os.getcwd())
    restart_count = int(os.environ.get("DS_TPU_RUN_RESTART_COUNT", "0"))
    spec = json.loads(os.environ["DS_TPU_SERVE_SPEC"])

    from deepspeed_tpu.runtime.resilience import fault_injection
    if restart_count == 0:
        fault_injection.arm_from_env()

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.fleet import completion_dict
    from deepspeed_tpu.inference.scheduler import (
        ContinuousBatchingScheduler)
    from deepspeed_tpu.models.gpt2 import GPT2LMHead, gpt2_tiny
    from deepspeed_tpu.runtime.resilience.preemption import (
        PreemptionHandler)

    session = None
    if spec.get("jsonl"):
        from deepspeed_tpu.telemetry.exporters import JsonlExporter
        from deepspeed_tpu.telemetry.session import TelemetrySession
        session = TelemetrySession(
            exporters=[JsonlExporter(spec["jsonl"])])

    seed = int(spec.get("seed", 0))
    cfg = gpt2_tiny(n_embd=32, dtype=jnp.float32,
                    scan_layers=bool(spec.get("scan_layers", False)))
    model = GPT2LMHead(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), toks)["params"]
    tier = spec.get("tier")
    inf_cfg = dict(spec.get("inf_cfg") or {})
    if tier:
        inf_cfg["tier"] = tier
    engine = InferenceEngine(model, params, config=inf_cfg,
                             session=session)

    if tier:
        from deepspeed_tpu.inference.disagg import (
            DecodeWorker, FileHandoffStore, PrefillWorker)
        store = FileHandoffStore(spec["handoff_dir"])
        worker = (PrefillWorker if tier == "prefill"
                  else DecodeWorker)(engine, store, session=session)
        handler = PreemptionHandler().install()
        reader = _CommandReader()
        _out({"type": "ready", "pid": os.getpid(), "replica": index,
              "tier": tier})
        return _tier_loop(worker, engine, handler, reader, session,
                          workdir, index)

    sched = ContinuousBatchingScheduler(engine)

    handler = PreemptionHandler().install()
    reader = _CommandReader()
    _out({"type": "ready", "pid": os.getpid(), "replica": index})

    reported = 0
    stalled_until = 0.0
    stopping = False
    while True:
        idle = not (sched.queue or any(
            s is not None for s in sched.slots))
        if idle and not stopping:
            time.sleep(0.002)
        for cmd in reader.drain():
            if cmd.get("cmd") == "submit":
                sched.submit(_request_from(cmd["request"]))
            elif cmd.get("cmd") == "stop":
                stopping = True

        has_work = bool(sched.queue) or any(
            s is not None for s in sched.slots)
        if has_work:
            sched.step()        # kill/decode fault probes fire inside

        for c in sched.completions[reported:]:
            _out({"type": "completion", "completion": completion_dict(c)})
        reported = len(sched.completions)

        now = time.time()
        stall = fault_injection.heartbeat_stall_seconds(sched.step_count)
        if stall:
            stalled_until = now + stall
        if now >= stalled_until:
            _write_heartbeat(workdir, index, sched.step_count, has_work)

        if handler.preempted:
            # SIGTERM: the current decode step already finished above.
            if session is not None:
                session.emit("preemption", step=sched.step_count,
                             completed=reported, replica=index)
                session.close()
            _out({"type": "preempted", "completed": reported,
                  "steps": sched.step_count})
            return 0            # exit 0, NO done marker -> preemption

        if stopping and not has_work:
            break

    counts = engine.compile_counts()
    _out({"type": "stats", "compile_counts": counts,
          "steps": sched.step_count, "completed": reported,
          "replica": index})
    if session is not None:
        session.close()
    from deepspeed_tpu.runtime.supervisor.supervisor import done_path
    with open(done_path(workdir, index), "w") as f:
        f.write("done\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
