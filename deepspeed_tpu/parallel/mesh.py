"""Device-mesh bootstrap: the TPU-native replacement for process groups.

The reference organizes parallelism with NCCL process groups
(`runtime/engine.py:130`, `runtime/pipe/topology.py:252-364`). On TPU the
equivalent structure is a named ``jax.sharding.Mesh``: the ``data`` axis
replaces the dp group, ``model`` the mp/slice groups, ``pipe`` the pipeline
stage pairs, ``seq`` sequence/context parallelism, and ``expert`` MoE expert
parallelism. XLA collectives over these axes ride ICI within a slice and DCN
across slices.
"""

from typing import Optional, Dict

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis order: collectives on inner (fastest-varying) axes stay on
# ICI neighbors; `data` is outermost so cross-slice DCN traffic (if any) is
# the infrequent gradient reduction.
MESH_AXES = ("data", "pipe", "expert", "seq", "model")

_initialized = False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None):
    """Multi-host rendezvous: analog of ``dist.init_process_group`` at
    `runtime/engine.py:135`, via ``jax.distributed.initialize``.

    Defaults come from the ``DS_TPU_COORDINATOR`` /
    ``DS_TPU_NUM_PROCESSES`` / ``DS_TPU_PROCESS_ID`` env the launcher sets
    per host (`launcher/launch.py:build_env` — the MASTER_ADDR/RANK
    equivalent). Single-process (one host, or tests) is a no-op: JAX
    already sees all local devices.
    """
    global _initialized
    if _initialized:
        return
    import os

    explicit_coordinator = coordinator_address is not None
    if coordinator_address is None:
        coordinator_address = os.environ.get("DS_TPU_COORDINATOR")
    if num_processes is None and "DS_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DS_TPU_NUM_PROCESSES"])
    if process_id is None and "DS_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DS_TPU_PROCESS_ID"])
    # An explicit coordinator always initializes (num_processes/process_id
    # auto-detect on TPU pods); env-driven initialization requires the
    # process count so a partial env fails loudly rather than silently
    # staying single-host.
    if explicit_coordinator or num_processes not in (None, 1):
        if not explicit_coordinator and coordinator_address is None:
            raise RuntimeError(
                "DS_TPU_NUM_PROCESSES is set but DS_TPU_COORDINATOR is "
                "missing — partial launcher env")
        # NB: can't ask jax.default_backend() here — that would initialize
        # the backend, and jax.distributed.initialize must run first. Use
        # pre-init signals only, and only a *positive* off-TPU signal: a
        # platform env set without "tpu", or no libtpu importable (TPU VMs
        # always ship it). An unset env on a TPU pod must keep working —
        # process_id auto-detects there.
        platforms = (os.environ.get("JAX_PLATFORMS")
                     or os.environ.get("JAX_PLATFORM_NAME") or "")
        if platforms:
            off_tpu = "tpu" not in platforms.lower()
        else:
            import importlib.util
            off_tpu = importlib.util.find_spec("libtpu") is None
        if not explicit_coordinator and process_id is None and off_tpu:
            # process_id=None only auto-detects on TPU pods; off-TPU it
            # dies deep inside the backend with an obscure error — fail
            # with the same loud partial-env message instead.
            raise RuntimeError(
                "DS_TPU_NUM_PROCESSES is set but DS_TPU_PROCESS_ID is "
                "missing — partial launcher env (process_id only "
                "auto-detects on TPU pods)")
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _initialized = True


def normalize_mesh_shape(mesh_config: Optional[Dict[str, Optional[int]]],
                         n_devices: Optional[int] = None) -> Dict[str, int]:
    """Resolve a user mesh dict into a full {axis: size} over all devices.

    Unspecified axes default to 1; a ``data`` axis of None (or omitted)
    absorbs the remaining devices.
    """
    if n_devices is None:
        n_devices = jax.device_count()
    cfg = dict(mesh_config or {})
    shape = {}
    denom = 1
    for axis in MESH_AXES:
        if axis == "data":
            continue
        size = cfg.get(axis) or 1
        shape[axis] = int(size)
        denom *= int(size)
    if n_devices % denom != 0:
        raise ValueError(
            f"mesh axes {cfg} (product {denom}) do not divide "
            f"device count {n_devices}")
    data = cfg.get("data")
    if data is None:
        data = n_devices // denom
    if data * denom != n_devices:
        raise ValueError(
            f"mesh {cfg} with data={data} does not cover {n_devices} devices")
    shape["data"] = int(data)
    return shape


def build_mesh(mesh_config: Optional[Dict[str, Optional[int]]] = None,
               devices=None) -> Mesh:
    """Create the named device mesh.

    Uses ``jax.experimental.mesh_utils.create_device_mesh`` when possible so
    the logical axes map onto the physical ICI torus; falls back to a plain
    reshape (CPU test meshes).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    shape = normalize_mesh_shape(mesh_config, n)
    dims = tuple(shape[a] for a in MESH_AXES)
    try:
        from jax.experimental import mesh_utils
        device_array = mesh_utils.create_device_mesh(dims, devices=devices)
    except Exception:
        device_array = np.asarray(devices).reshape(dims)
    return Mesh(device_array, MESH_AXES)


def single_device_mesh() -> Mesh:
    """1-device mesh with all named axes size 1 (single-chip runs)."""
    return build_mesh({})


def data_sharding(mesh: Mesh, *, batch_axes=("data",)) -> NamedSharding:
    """Sharding for a [batch, ...] array split over the data axis."""
    return NamedSharding(mesh, PartitionSpec(batch_axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def mesh_shape_dict(mesh: Mesh) -> Dict[str, int]:
    """{axis: size} in canonical axis order — the schema checkpoint
    manifests record (`runtime/elastic/topology.py`), so a saved and a
    live topology compare key-by-key."""
    return {axis: int(mesh.shape[axis]) for axis in mesh.axis_names}
