"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has **no** sequence-dim sharding (SURVEY.md §5.7 — its
long-context story is block-sparse attention only). These are the
TPU-idiomatic long-context mechanisms this framework adds on top of parity:

- **Ring attention** (Liu et al., arXiv:2310.01889): q stays put, k/v chunks
  rotate around the ``seq`` mesh axis via ``ppermute`` (ICI-neighbor
  traffic), with online-softmax accumulation so each device only ever holds
  one remote chunk. Memory per device: O(T/n); comm: n-1 neighbor hops that
  XLA overlaps with the chunk matmuls.
- **Ulysses** (DeepSpeed-Ulysses, arXiv:2309.14509): two ``all_to_all``
  collectives re-shard [seq-sharded, all heads] ⟷ [all seq, head-sharded]
  so any full-sequence attention kernel (flash, block-sparse) runs
  unchanged on H/n heads.

Both come as a ``*_local`` form for use inside an existing ``shard_map``
(how the engine composes them) and a standalone wrapper that builds the
``shard_map`` over a mesh.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.compat import shard_map

from deepspeed_tpu.ops.pallas.flash_attention import (
    DEFAULT_MASK_VALUE,
    dropout_multiplier,
    flash_attention,
    fold_in_seed,
)
from deepspeed_tpu.parallel.collectives import (all_to_all_overlap,
                                                barrier_after,
                                                overlap_plan)


def _check_dropout_args(dropout_rate, dropout_seed):
    if dropout_rate:
        if not 0.0 <= dropout_rate < 1.0:
            raise ValueError(f"dropout_rate {dropout_rate} not in [0, 1)")
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")


def ring_attention_local(q, k, v, axis_name, causal=True, sm_scale=None,
                         dropout_rate=0.0, dropout_seed=None,
                         data_axis_name=None):
    """Ring attention over ``axis_name``; call inside ``shard_map``.

    q, k, v: [B, T_local, H, D] — this device's sequence shard. Returns the
    local [B, T_local, H, D] attention output, exactly equal to the
    corresponding slice of full attention over the global sequence.

    ``dropout_rate``/``dropout_seed``: in-kernel attention-prob dropout
    with the shared counter-based mask at GLOBAL sequence coordinates —
    every seq rank derives the same bits for the same (b, h, q, k)
    element, so the sharded result equals dense-with-the-same-mask. The
    batch coordinate is the shard-local row index; pass
    ``data_axis_name`` when a data axis is also bound so each data shard
    mixes its rank into the seed (otherwise all data shards would reuse
    one mask pattern across the batch).
    """
    _check_dropout_args(dropout_rate, dropout_seed)
    if dropout_rate and data_axis_name is not None:
        dropout_seed = fold_in_seed(dropout_seed,
                                    jax.lax.axis_index(data_axis_name))
    B, Tloc, H, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)

    qf = q.astype(jnp.float32) * sm_scale
    q_pos = idx * Tloc + jnp.arange(Tloc)            # global q positions
    perm = [(j, (j + 1) % n) for j in range(n)]
    bh_idx = jnp.arange(B)[:, None] * H + jnp.arange(H)[None, :]  # [B, H]

    def compute_chunk(acc, m, l, kc, vc, src):
        k_pos = src * Tloc + jnp.arange(Tloc)
        s = jnp.einsum("bthd,bshd->bhts", qf, kc.astype(jnp.float32))
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]  # [Tloc, Tloc] global
            s = jnp.where(mask[None, None], s, DEFAULT_MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        pd = p
        if dropout_rate > 0.0:
            pd = p * dropout_multiplier(
                dropout_seed, bh_idx[:, :, None, None],
                q_pos[None, None, :, None],
                k_pos[None, None, None, :], dropout_rate)
        acc = acc * corr[..., None] + \
            jnp.einsum("bhts,bshd->bhtd", pd, vc.astype(jnp.float32))
        return acc, m_new, l_new

    def step(carry, t):
        acc, m, l, kc, vc = carry
        # rotation first: t=0 (own chunk) is handled outside the scan, so
        # only n-1 ppermutes ever ship data
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        # after t rotations this device holds the chunk of owner (idx - t)
        src = jnp.mod(idx - t, n)
        if causal:
            # chunks entirely in the future are all-masked: skip their
            # matmuls (predicate varies per device; branch is local math)
            acc, m, l = jax.lax.cond(
                src <= idx,
                lambda a, mm, ll: compute_chunk(a, mm, ll, kc, vc, src),
                lambda a, mm, ll: (a, mm, ll),
                acc, m, l)
        else:
            acc, m, l = compute_chunk(acc, m, l, kc, vc, src)
        return (acc, m, l, kc, vc), None

    acc0 = jnp.zeros((B, H, Tloc, D), jnp.float32)
    m0 = jnp.full((B, H, Tloc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tloc), jnp.float32)
    acc, m, l = compute_chunk(acc0, m0, l0, k, v, idx)   # own (diagonal) chunk
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc, m, l, k, v), jnp.arange(1, n))
    # causal rows always see the diagonal chunk (t=0), so l > 0 everywhere
    out = acc / l[..., None]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name, causal=True, sm_scale=None,
                            attn_fn=None, dropout_rate=0.0,
                            dropout_seed=None, data_axis_name=None):
    """Ulysses sequence parallelism; call inside ``shard_map``.

    q, k, v: [B, T_local, H, D] seq shards with H divisible by the axis
    size. all_to_all → [B, T, H/n, D], run ``attn_fn`` (default
    :func:`flash_attention`) on the full sequence, all_to_all back.

    Dropout is delegated to ``attn_fn`` with this rank's axis index MIXED
    into the seed (full avalanche, :func:`fold_in_seed` — a linear stride
    would alias the hash's coordinate multipliers): each rank attends a
    DIFFERENT head group but sees the same local head indices, so an
    unfolded seed would repeat the identical mask pattern across head
    groups (correlated dropout). ``data_axis_name``: as in
    :func:`ring_attention_local`.

    Under an active ``ulysses`` overlap plan the heads are split into
    chunk groups: group *j+1*'s decomposed ``all_to_all`` (shift
    ``ppermute``s, :func:`all_to_all_overlap`) can overlap group *j*'s
    attention. The un-chunked result is identical (the inverse
    ``all_to_all`` restores the original head order) except under
    dropout, where each group additionally folds its index into the seed
    (decorrelated but not bit-matching the monolithic mask).
    """
    _check_dropout_args(dropout_rate, dropout_seed)
    n = jax.lax.psum(1, axis_name)
    H = q.shape[2]
    assert H % n == 0, f"heads {H} must divide seq-parallel degree {n}"
    if attn_fn is None:
        attn_fn = flash_attention   # "auto": Pallas on TPU, XLA elsewhere
    kwargs = {}
    if dropout_rate > 0.0:
        seed = fold_in_seed(dropout_seed, jax.lax.axis_index(axis_name))
        if data_axis_name is not None:
            seed = fold_in_seed(seed, jax.lax.axis_index(data_axis_name))
        kwargs = {"dropout_rate": dropout_rate, "dropout_seed": seed}

    plan = overlap_plan("ulysses")
    c = 0
    if plan is not None and plan.chunks > 1 and n > 1:
        # groups must keep the per-group head dim divisible by n:
        # largest divisor of H/n that is <= plan.chunks
        h_loc = H // n
        c = min(plan.chunks, h_loc)
        while c > 1 and h_loc % c:
            c -= 1
    if c > 1:
        h_grp = H // c
        outs = []
        dep = None   # serialize the decomposed exchanges (barrier_after)
        for j in range(c):
            gkw = dict(kwargs)
            if gkw:
                gkw["dropout_seed"] = fold_in_seed(gkw["dropout_seed"], j)
            start = j * h_grp
            grp = []
            for t in (q, k, v):
                t = jax.lax.slice_in_dim(t, start, start + h_grp, axis=2)
                t = all_to_all_overlap(barrier_after(t, dep), axis_name,
                                       2, 1, chunks=c)
                dep = t
                grp.append(t)
            og = attn_fn(*grp, causal=causal, sm_scale=sm_scale, **gkw)
            back = all_to_all_overlap(og, axis_name, 1, 2, chunks=c)
            dep = back
            outs.append(back)
        return jnp.concatenate(outs, axis=2)

    def scatter_heads(x):   # [B, Tloc, H, D] → [B, T, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    out = attn_fn(scatter_heads(q), scatter_heads(k), scatter_heads(v),
                  causal=causal, sm_scale=sm_scale, **kwargs)
    # [B, T, H/n, D] → [B, Tloc, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def _seq_sharded_call(local_fn, mesh, q, k, v, seq_axis, data_axis):
    specs = P(data_axis, seq_axis, None, None)
    fn = shard_map(local_fn, mesh=mesh, in_specs=(specs, specs, specs),
                       out_specs=specs, check_vma=False)
    return fn(q, k, v)


def ring_attention(q, k, v, mesh, causal=True, sm_scale=None,
                   seq_axis="seq", data_axis="data",
                   dropout_rate=0.0, dropout_seed=None):
    """Standalone ring attention: q,k,v [B, T, H, D] global arrays sharded
    [data, seq] over ``mesh``."""
    # fold the data rank into the seed only when there IS data sharding —
    # at data=1 the fold would be a pure (parity-breaking) seed rewrite
    dax = data_axis if mesh.shape[data_axis] > 1 else None
    local = functools.partial(ring_attention_local, axis_name=seq_axis,
                              causal=causal, sm_scale=sm_scale,
                              dropout_rate=dropout_rate,
                              dropout_seed=dropout_seed,
                              data_axis_name=dax)
    return _seq_sharded_call(local, mesh, q, k, v, seq_axis, data_axis)


def ulysses_attention(q, k, v, mesh, causal=True, sm_scale=None,
                      seq_axis="seq", data_axis="data", attn_fn=None,
                      dropout_rate=0.0, dropout_seed=None):
    """Standalone Ulysses attention: q,k,v [B, T, H, D] sharded [data, seq]."""
    dax = data_axis if mesh.shape[data_axis] > 1 else None
    local = functools.partial(ulysses_attention_local, axis_name=seq_axis,
                              causal=causal, sm_scale=sm_scale,
                              attn_fn=attn_fn, dropout_rate=dropout_rate,
                              dropout_seed=dropout_seed,
                              data_axis_name=dax)
    return _seq_sharded_call(local, mesh, q, k, v, seq_axis, data_axis)
