"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has **no** sequence-dim sharding (SURVEY.md §5.7 — its
long-context story is block-sparse attention only). These are the
TPU-idiomatic long-context mechanisms this framework adds on top of parity:

- **Ring attention** (Liu et al., arXiv:2310.01889): q stays put, k/v chunks
  rotate around the ``seq`` mesh axis via ``ppermute`` (ICI-neighbor
  traffic), with online-softmax accumulation so each device only ever holds
  one remote chunk. Memory per device: O(T/n); comm: n-1 neighbor hops that
  XLA overlaps with the chunk matmuls.
- **Ulysses** (DeepSpeed-Ulysses, arXiv:2309.14509): two ``all_to_all``
  collectives re-shard [seq-sharded, all heads] ⟷ [all seq, head-sharded]
  so any full-sequence attention kernel (flash, block-sparse) runs
  unchanged on H/n heads.

Both come as a ``*_local`` form for use inside an existing ``shard_map``
(how the engine composes them) and a standalone wrapper that builds the
``shard_map`` over a mesh.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.pallas.flash_attention import (
    DEFAULT_MASK_VALUE,
    flash_attention,
)


def ring_attention_local(q, k, v, axis_name, causal=True, sm_scale=None):
    """Ring attention over ``axis_name``; call inside ``shard_map``.

    q, k, v: [B, T_local, H, D] — this device's sequence shard. Returns the
    local [B, T_local, H, D] attention output, exactly equal to the
    corresponding slice of full attention over the global sequence.
    """
    B, Tloc, H, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)

    qf = q.astype(jnp.float32) * sm_scale
    q_pos = idx * Tloc + jnp.arange(Tloc)            # global q positions
    perm = [(j, (j + 1) % n) for j in range(n)]

    def compute_chunk(acc, m, l, kc, vc, src):
        k_pos = src * Tloc + jnp.arange(Tloc)
        s = jnp.einsum("bthd,bshd->bhts", qf, kc.astype(jnp.float32))
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]  # [Tloc, Tloc] global
            s = jnp.where(mask[None, None], s, DEFAULT_MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc = acc * corr[..., None] + \
            jnp.einsum("bhts,bshd->bhtd", p, vc.astype(jnp.float32))
        return acc, m_new, l_new

    def step(carry, t):
        acc, m, l, kc, vc = carry
        # rotation first: t=0 (own chunk) is handled outside the scan, so
        # only n-1 ppermutes ever ship data
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        # after t rotations this device holds the chunk of owner (idx - t)
        src = jnp.mod(idx - t, n)
        if causal:
            # chunks entirely in the future are all-masked: skip their
            # matmuls (predicate varies per device; branch is local math)
            acc, m, l = jax.lax.cond(
                src <= idx,
                lambda a, mm, ll: compute_chunk(a, mm, ll, kc, vc, src),
                lambda a, mm, ll: (a, mm, ll),
                acc, m, l)
        else:
            acc, m, l = compute_chunk(acc, m, l, kc, vc, src)
        return (acc, m, l, kc, vc), None

    acc0 = jnp.zeros((B, H, Tloc, D), jnp.float32)
    m0 = jnp.full((B, H, Tloc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tloc), jnp.float32)
    acc, m, l = compute_chunk(acc0, m0, l0, k, v, idx)   # own (diagonal) chunk
    (acc, m, l, _, _), _ = jax.lax.scan(
        step, (acc, m, l, k, v), jnp.arange(1, n))
    # causal rows always see the diagonal chunk (t=0), so l > 0 everywhere
    out = acc / l[..., None]
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name, causal=True, sm_scale=None,
                            attn_fn=None):
    """Ulysses sequence parallelism; call inside ``shard_map``.

    q, k, v: [B, T_local, H, D] seq shards with H divisible by the axis
    size. all_to_all → [B, T, H/n, D], run ``attn_fn`` (default
    :func:`flash_attention`) on the full sequence, all_to_all back.
    """
    n = jax.lax.psum(1, axis_name)
    H = q.shape[2]
    assert H % n == 0, f"heads {H} must divide seq-parallel degree {n}"
    if attn_fn is None:
        attn_fn = flash_attention   # "auto": Pallas on TPU, XLA elsewhere

    def scatter_heads(x):   # [B, Tloc, H, D] → [B, T, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    out = attn_fn(scatter_heads(q), scatter_heads(k), scatter_heads(v),
                  causal=causal, sm_scale=sm_scale)
    # [B, T, H/n, D] → [B, Tloc, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def _seq_sharded_call(local_fn, mesh, q, k, v, seq_axis, data_axis):
    specs = P(data_axis, seq_axis, None, None)
    fn = jax.shard_map(local_fn, mesh=mesh, in_specs=(specs, specs, specs),
                       out_specs=specs, check_vma=False)
    return fn(q, k, v)


def ring_attention(q, k, v, mesh, causal=True, sm_scale=None,
                   seq_axis="seq", data_axis="data"):
    """Standalone ring attention: q,k,v [B, T, H, D] global arrays sharded
    [data, seq] over ``mesh``."""
    local = functools.partial(ring_attention_local, axis_name=seq_axis,
                              causal=causal, sm_scale=sm_scale)
    return _seq_sharded_call(local, mesh, q, k, v, seq_axis, data_axis)


def ulysses_attention(q, k, v, mesh, causal=True, sm_scale=None,
                      seq_axis="seq", data_axis="data", attn_fn=None):
    """Standalone Ulysses attention: q,k,v [B, T, H, D] sharded [data, seq]."""
    local = functools.partial(ulysses_attention_local, axis_name=seq_axis,
                              causal=causal, sm_scale=sm_scale,
                              attn_fn=attn_fn)
    return _seq_sharded_call(local, mesh, q, k, v, seq_axis, data_axis)
