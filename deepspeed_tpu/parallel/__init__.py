"""Parallelism primitives: mesh bootstrap + sequence/context parallelism."""

from deepspeed_tpu.parallel.mesh import (
    MESH_AXES,
    build_mesh,
    initialize_distributed,
    normalize_mesh_shape,
    single_device_mesh,
)
from deepspeed_tpu.parallel.sequence import (
    ring_attention,
    ring_attention_local,
    ulysses_attention,
    ulysses_attention_local,
)
