"""Manual-collective building blocks shared by every layer that runs
inside the pipeline's ``shard_map`` (tensor-parallel blocks in
`parallel/pipe_tp.py`, the expert-parallel FFN in `moe/expert_pipe.py`).

The Megatron ``f``/``g`` conjugate pair (reference posture: TP delegated
to Megatron's ColumnParallelLinear/RowParallelLinear,
`deepspeed/__init__.py:76-77`) in functional-JAX form:

- :func:`psum_grad` — identity forward, psum backward (``f``): repairs
  partial cotangents of replicated tensors consumed by axis-partitioned
  compute.
- :func:`psum_combine` — psum forward, identity backward (``g``):
  combines axis-partitioned partial outputs. Raw ``lax.psum`` is wrong
  here because its transpose is another psum — a replicated cotangent
  would come back multiplied by the axis size.

Manual mode is an explicit, trace-time property: the pipeline enters
:func:`manual_axes` around its ``shard_map`` body, and layers ask
:func:`axis_is_manual` — replacing the round-3 ``lax.axis_index``
NameError probe, which misfired whenever a caller happened to bind the
axis name for unrelated reasons (and depended on an exception message
contract).
"""

import contextlib

import jax
from jax import lax

_MANUAL_AXES = ()


@contextlib.contextmanager
def manual_axes(axes):
    """Declare mesh axes as manual (inside ``shard_map``) for layers
    traced within this context. Trace-time only — the pipeline wraps its
    device function, so the flag is active exactly while layer bodies
    trace."""
    global _MANUAL_AXES
    prev = _MANUAL_AXES
    _MANUAL_AXES = prev + tuple(a for a in axes if a not in prev)
    try:
        yield
    finally:
        _MANUAL_AXES = prev


def axis_is_manual(axis_name):
    """True iff ``axis_name`` was declared manual by :func:`manual_axes`
    (i.e. we are tracing inside the pipeline's shard_map and collectives
    over this axis are both legal and required)."""
    return axis_name in _MANUAL_AXES


def scatter_to_chunk_servers(tree, axis_name):
    """Chunk-server scatter: every leaf is a ``[world, ...]`` stack of
    per-destination rows; rank r receives every rank's row r.

    One ``all_to_all`` per leaf — the reduce-scatter half of the 2-phase
    chunk-server topology shared by the 1-bit path
    (`runtime/comm/compressed.py`, the reference's igather to chunk
    servers at custom_collectives.py:23) and the int8 quantized path
    (`runtime/comm/quantized.py`). Must run inside ``shard_map``."""
    return jax.tree_util.tree_map(
        lambda v: lax.all_to_all(v, axis_name, split_axis=0, concat_axis=0),
        tree)


def gather_from_chunk_servers(tree, axis_name):
    """Chunk-server gather: every rank contributes its served (reduced)
    chunk; all ranks receive the ``[world, ...]`` stack.

    One ``all_gather`` per leaf — the second phase of the chunk-server
    topology (the reference's final allgather, onebit_adam.py:200-228).
    Must run inside ``shard_map``."""
    return jax.tree_util.tree_map(
        lambda v: lax.all_gather(v, axis_name), tree)


def psum_grad(x, axis_name):
    """Identity in forward; ``psum`` of the cotangent over ``axis_name`` in
    backward. Makes grads of tensors consumed by axis-partitioned compute
    exact (each rank's backward contributes only its shard's part)."""

    @jax.custom_vjp
    def _f(y):
        return y

    def _fwd(y):
        return y, None

    def _bwd(_, g):
        return (lax.psum(g, axis_name),)

    _f.defvjp(_fwd, _bwd)
    return _f(x)


def psum_combine(x, axis_name):
    """``psum`` in forward; *identity* in backward.

    The dual of :func:`psum_grad`, for combining axis-partitioned partial
    outputs that are then consumed replicated. Raw ``lax.psum`` is wrong
    here: its transpose is another psum, so a replicated cotangent comes
    back multiplied by the axis size. With the output replicated, the true
    cotangent of each rank's partial is exactly the output's cotangent —
    identity."""

    @jax.custom_vjp
    def _f(y):
        return lax.psum(y, axis_name)

    def _fwd(y):
        return lax.psum(y, axis_name), None

    def _bwd(_, g):
        return (g,)

    _f.defvjp(_fwd, _bwd)
    return _f(x)
