"""Manual-collective building blocks shared by every layer that runs
inside the pipeline's ``shard_map`` (tensor-parallel blocks in
`parallel/pipe_tp.py`, the expert-parallel FFN in `moe/expert_pipe.py`).

The Megatron ``f``/``g`` conjugate pair (reference posture: TP delegated
to Megatron's ColumnParallelLinear/RowParallelLinear,
`deepspeed/__init__.py:76-77`) in functional-JAX form:

- :func:`psum_grad` — identity forward, psum backward (``f``): repairs
  partial cotangents of replicated tensors consumed by axis-partitioned
  compute.
- :func:`psum_combine` — psum forward, identity backward (``g``):
  combines axis-partitioned partial outputs. Raw ``lax.psum`` is wrong
  here because its transpose is another psum — a replicated cotangent
  would come back multiplied by the axis size.

Manual mode is an explicit, trace-time property: the pipeline enters
:func:`manual_axes` around its ``shard_map`` body, and layers ask
:func:`axis_is_manual` — replacing the round-3 ``lax.axis_index``
NameError probe, which misfired whenever a caller happened to bind the
axis name for unrelated reasons (and depended on an exception message
contract).

Latency-hiding collective matmul (DeepCompile, arXiv:2504.09983; the
standard TPU transformation): the monolithic blocking collectives above
leave the MXU idle for the whole exchange. The overlap primitives below
(:func:`matmul_psum_overlap`, :func:`matmul_reduce_scatter`,
:func:`all_gather_matmul_overlap`, :func:`all_to_all_overlap`) split the
contraction into ``chunks`` pieces and software-pipeline the
``ppermute`` of chunk *i* against the matmul of chunk *i+1*, so
communication hides behind dependent compute. Each carries a
``custom_vjp`` whose backward runs the *transposed* overlapped schedule
(reduce-scatter ↔ all-gather duality); ``chunks=1`` reproduces the
monolithic collective bit-for-bit. Layers opt in per call site through
the trace-time :func:`overlap_scope` / :func:`overlap_plan` pair, which
mirrors :func:`manual_axes` and is driven by the engine's
``tensor_parallel.overlap`` config block.

Quantized wire (EQuARX, arXiv:2506.17615): every ring primitive takes a
``wire_dtype`` hook naming a codec from the shared registry
(``runtime/comm/codecs.py`` — ``int8`` / ``f8e4m3fn`` / ``f8e5m2``). With
a codec set, each rotate step moves the chunk *encoded* — payload and
per-chunk f32 scales byte-packed into one u8 buffer riding a single
``ppermute`` — and the receiver dequantize-accumulates in fp32
concurrently with the next chunk's matmul, so the quantize/dequantize
work pipelines *inside* the collective instead of bracketing it. A
rank's own contribution is always taken exactly, and reducing rings
encode each contribution exactly once at its origin (the rotating buffer
is forwarded unchanged); the traveling-accumulator ring of
:func:`matmul_reduce_scatter` is the one documented exception (it
re-encodes per hop — the EQuARX accuracy/bandwidth trade). With
``chunks=1`` the wire routes through the bracketed
quantize→monolithic-collective path (ascending-rank decode-sum, own
contribution exact) — the bit-identity reference the parity tests pin.
"""

import contextlib
import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.runtime.comm.codecs import (
    decode_wire, encode_wire, get_codec)

_MANUAL_AXES = ()


@contextlib.contextmanager
def manual_axes(axes):
    """Declare mesh axes as manual (inside ``shard_map``) for layers
    traced within this context. Trace-time only — the pipeline wraps its
    device function, so the flag is active exactly while layer bodies
    trace."""
    global _MANUAL_AXES
    prev = _MANUAL_AXES
    _MANUAL_AXES = prev + tuple(a for a in axes if a not in prev)
    try:
        yield
    finally:
        _MANUAL_AXES = prev


def axis_is_manual(axis_name):
    """True iff ``axis_name`` was declared manual by :func:`manual_axes`
    (i.e. we are tracing inside the pipeline's shard_map and collectives
    over this axis are both legal and required)."""
    return axis_name in _MANUAL_AXES


def scatter_to_chunk_servers(tree, axis_name):
    """Chunk-server scatter: every leaf is a ``[world, ...]`` stack of
    per-destination rows; rank r receives every rank's row r.

    One ``all_to_all`` per leaf — the reduce-scatter half of the 2-phase
    chunk-server topology shared by the 1-bit path
    (`runtime/comm/compressed.py`, the reference's igather to chunk
    servers at custom_collectives.py:23) and the int8 quantized path
    (`runtime/comm/quantized.py`). Must run inside ``shard_map``."""
    return jax.tree_util.tree_map(
        lambda v: lax.all_to_all(v, axis_name, split_axis=0, concat_axis=0),
        tree)


def gather_from_chunk_servers(tree, axis_name):
    """Chunk-server gather: every rank contributes its served (reduced)
    chunk; all ranks receive the ``[world, ...]`` stack.

    One ``all_gather`` per leaf — the second phase of the chunk-server
    topology (the reference's final allgather, onebit_adam.py:200-228).
    Must run inside ``shard_map``."""
    return jax.tree_util.tree_map(
        lambda v: lax.all_gather(v, axis_name), tree)


def psum_grad(x, axis_name, chunks=1, bidirectional=False,
              wire_dtype=None, wire_chunk=512):
    """Identity in forward; ``psum`` of the cotangent over ``axis_name`` in
    backward. Makes grads of tensors consumed by axis-partitioned compute
    exact (each rank's backward contributes only its shard's part).

    ``chunks > 1`` replaces the backward's monolithic all-reduce with the
    chunked rotate-accumulate ring (:func:`ring_psum`) so the cotangent
    exchange can overlap adjacent backward matmuls; ``chunks=1`` (the
    default) keeps ``lax.psum`` — bit-identical to the historical
    behavior. ``wire_dtype`` quantizes the cotangent exchange through the
    codec registry (see :func:`ring_psum`)."""

    @jax.custom_vjp
    def _f(y):
        return y

    def _fwd(y):
        return y, None

    def _bwd(_, g):
        if chunks > 1 or wire_dtype is not None:
            return (ring_psum(g, axis_name, chunks=chunks,
                              bidirectional=bidirectional,
                              wire_dtype=wire_dtype,
                              wire_chunk=wire_chunk),)
        return (lax.psum(g, axis_name),)

    _f.defvjp(_fwd, _bwd)
    return _f(x)


def psum_combine(x, axis_name):
    """``psum`` in forward; *identity* in backward.

    The dual of :func:`psum_grad`, for combining axis-partitioned partial
    outputs that are then consumed replicated. Raw ``lax.psum`` is wrong
    here: its transpose is another psum, so a replicated cotangent comes
    back multiplied by the axis size. With the output replicated, the true
    cotangent of each rank's partial is exactly the output's cotangent —
    identity."""

    @jax.custom_vjp
    def _f(y):
        return lax.psum(y, axis_name)

    def _fwd(y):
        return lax.psum(y, axis_name), None

    def _bwd(_, g):
        return (g,)

    _f.defvjp(_fwd, _bwd)
    return _f(x)


# ---------------------------------------------------------------------------
# overlap plan: trace-time opt-in for the chunked collective matmuls
# ---------------------------------------------------------------------------

# The rewired call sites. Per-site overrides in the config's
# ``tensor_parallel.overlap.sites`` are validated against this tuple.
#   row_parallel    — the Megatron "g" combine in pipe_tp.row_parallel
#                     (and the grad ring of psum_grad at its column dual)
#   column_parallel — the Megatron "f" backward grad-psum feeding
#                     column-parallel compute (pipe_tp.replicated_input)
#   expert_combine  — the expert-output combine in moe/expert_pipe.py
#   ulysses         — the all_to_all brackets of Ulysses attention
#                     (parallel/sequence.py); ``bidirectional`` is a
#                     no-op here: the decomposed shift-h ppermutes
#                     already use both ring directions
OVERLAP_SITES = ("row_parallel", "column_parallel", "expert_combine",
                 "ulysses")


@dataclasses.dataclass(frozen=True)
class SitePlan:
    """Resolved overlap parameters for one call site. ``wire_dtype``
    (a codec name, or None for full-precision wire) and ``wire_chunk``
    (the per-scale chunk length) select the quantized-wire path."""
    chunks: int = 1
    bidirectional: bool = False
    wire_dtype: str = None
    wire_chunk: int = 512


@dataclasses.dataclass(frozen=True)
class OverlapPlan:
    """The ``tensor_parallel.overlap`` block, resolved: global chunk
    count / ring direction / wire codec plus per-site overrides
    (``{site: {"enabled", "chunks", "bidirectional", "wire_dtype",
    "wire_chunk"}}``)."""
    chunks: int = 4
    bidirectional: bool = False
    sites: dict = dataclasses.field(default_factory=dict)
    wire_dtype: str = None
    wire_chunk: int = 512

    def site(self, name):
        """SitePlan for ``name``, or None when the site is disabled."""
        ov = (self.sites or {}).get(name) or {}
        if ov.get("enabled", True) is False:
            return None
        wd = ov.get("wire_dtype", self.wire_dtype)
        return SitePlan(
            chunks=int(ov.get("chunks", self.chunks)),
            bidirectional=bool(ov.get("bidirectional", self.bidirectional)),
            wire_dtype=(str(wd) if wd else None),
            wire_chunk=int(ov.get("wire_chunk", self.wire_chunk)))


_OVERLAP_PLAN = None


@contextlib.contextmanager
def overlap_scope(plan):
    """Declare an :class:`OverlapPlan` active for layers traced within
    this context (trace-time only, exactly like :func:`manual_axes` —
    the pipeline wraps its device function with both). ``plan=None``
    keeps overlap off."""
    global _OVERLAP_PLAN
    prev = _OVERLAP_PLAN
    _OVERLAP_PLAN = plan
    try:
        yield
    finally:
        _OVERLAP_PLAN = prev


def overlap_plan(site):
    """The active :class:`SitePlan` for ``site``, or None when no
    :func:`overlap_scope` is active or the site is disabled."""
    if _OVERLAP_PLAN is None:
        return None
    return _OVERLAP_PLAN.site(site)


# ---------------------------------------------------------------------------
# chunk / ring helpers
# ---------------------------------------------------------------------------

def _chunk_slices(size, chunks):
    """(start, size) pairs splitting ``size`` into at most ``chunks``
    contiguous pieces; a non-dividing size spreads the remainder over
    the leading chunks (e.g. 10/4 → 3,3,2,2)."""
    k = max(1, min(int(chunks), int(size)))
    base, rem = divmod(int(size), k)
    out, start = [], 0
    for i in range(k):
        sz = base + (1 if i < rem else 0)
        out.append((start, sz))
        start += sz
    return out


def _ring_perm(n, reverse=False):
    shift = -1 if reverse else 1
    return [(i, (i + shift) % n) for i in range(n)]


@jax.custom_vjp
def _barrier_pair(x, dep):
    x, _ = lax.optimization_barrier((x, dep))
    return x


def _barrier_pair_fwd(x, dep):
    return _barrier_pair(x, dep), dep


def _barrier_pair_bwd(dep, g):
    # ``optimization_barrier`` has no AD rule, so plain-AD users of the
    # chain (all_to_all_overlap, the Ulysses brackets inside a stage
    # vjp) need this custom transpose. ``x``'s cotangent is identity.
    # ``dep``'s cotangent is mathematically zero — but emitting it WITH
    # a dataflow edge on ``g`` re-chains the TRANSPOSED collectives in
    # reverse order: dep's producer transposes only after g exists, so
    # the backward permutes serialize exactly like the forward ones
    # (same global-rendezvous hazard, mirrored).
    zeros = jax.tree_util.tree_map(jnp.zeros_like, dep)
    zeros, _ = lax.optimization_barrier((zeros, g))
    return g, zeros


_barrier_pair.defvjp(_barrier_pair_fwd, _barrier_pair_bwd)


def barrier_after(x, dep):
    """Give ``x`` (and everything downstream of it) a dataflow edge on
    ``dep``: collectives consuming ``x`` cannot issue before ``dep`` is
    produced. The overlap library chains every ``ppermute`` it emits
    through this — two *independent* in-flight collectives are exactly
    what deadlocks the in-process CPU runtime's global rendezvous
    (different device threads pick them up in different orders; see the
    auto_axes gate in runtime/pipe/engine.py). Chaining comm→comm costs
    nothing we need: the latency hiding comes from compute overlapping
    the chain, not from concurrent rings."""
    if dep is None:
        return x
    return _barrier_pair(x, dep)


# ---------------------------------------------------------------------------
# trace-time collective-site log (consumed by analysis.jaxpr / audit)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SiteRecord:
    """One collective group emitted by an overlap/ring helper while a
    program traced: which helper (``site``), over which mesh axis, the
    lowered primitive family, how many chunk rings / hops it fans out to,
    and whether its permutes ride the ``barrier_after`` dep-chain. The
    static analyzer's unordered-permute check proves the chain invariant
    from the jaxpr itself; these records give its findings (and the audit
    stats) source-level attribution the flat jaxpr no longer carries."""
    site: str
    axis: str
    primitive: str
    chunks: int = 1
    hops: int = 1
    chained: bool = True


_SITE_LOG = None


@contextlib.contextmanager
def record_collective_sites():
    """Collect :class:`SiteRecord`\\ s while tracing. Trace the program
    (``jax.jit(...).trace`` / ``jax.make_jaxpr``) inside this context and
    the helpers below append one record per collective group they emit;
    yields the list. Nestable — the innermost recorder wins."""
    global _SITE_LOG
    prev, log = _SITE_LOG, []
    _SITE_LOG = log
    try:
        yield log
    finally:
        _SITE_LOG = prev


def log_collective_site(site, axis, primitive, chunks=1, hops=1,
                        chained=True):
    """Append to the active :func:`record_collective_sites` log (no-op
    when none is active). Exposed so out-of-module collective emitters
    (the pipeline stage transfer) report through the same channel."""
    if _SITE_LOG is not None:
        _SITE_LOG.append(SiteRecord(site, str(axis), primitive,
                                    int(chunks), int(hops), bool(chained)))


def _ordered_ppermute(buf, axis_name, perm, dep):
    out = lax.ppermute(barrier_after(buf, dep), axis_name, perm)
    return out, out


# ---------------------------------------------------------------------------
# quantized wire: bracketed monolithic references + chunked wire rings
# ---------------------------------------------------------------------------

def _wire_psum_monolithic(x, axis_name, codec, wire_chunk,
                          dep=None, site="ring_psum"):
    """Bracketed quantize→monolithic-collective all-reduce: encode the
    local contribution once, ``all_gather`` the packed u8 wire buffers,
    then decode-sum in ascending rank order with this rank's own
    contribution taken exactly (fp32 accumulate, cast back at the end).
    This IS the reference semantics the chunked wire rings route to at
    ``chunks=1`` — the parity tests reproduce it literally."""
    codec = get_codec(codec)
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    wire = encode_wire(x, codec, wire_chunk)
    log_collective_site(site, axis_name, "all_gather")
    rows = lax.all_gather(barrier_after(wire, dep), axis_name, axis=0)
    xf = x.astype(jnp.float32)
    acc = jnp.zeros(x.shape, jnp.float32)
    for i in range(n):
        dec = decode_wire(rows[i], codec, x.shape, jnp.float32, wire_chunk)
        acc = acc + jnp.where(jnp.equal(i, r), xf, dec)
    return acc.astype(x.dtype)


def _wire_all_gather_monolithic(x, axis_name, axis, codec, wire_chunk,
                                dep=None, site="ring_all_gather"):
    """Bracketed quantized all-gather: encode the local shard once,
    ``all_gather`` the wire buffers, decode each row into its owner's
    slot — the own row placed exactly. Returns ``(gathered, dep)``."""
    codec = get_codec(codec)
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    k_loc = x.shape[axis]
    wire = encode_wire(x, codec, wire_chunk)
    log_collective_site(site, axis_name, "all_gather")
    rows = lax.all_gather(barrier_after(wire, dep), axis_name, axis=0)
    out_shape = list(x.shape)
    out_shape[axis] = n * k_loc
    out = jnp.zeros(out_shape, x.dtype)
    for i in range(n):
        dec = decode_wire(rows[i], codec, x.shape, x.dtype, wire_chunk)
        piece = jnp.where(jnp.equal(i, r), x, dec)
        out = lax.dynamic_update_slice_in_dim(out, piece, i * k_loc,
                                              axis=axis)
    return out, rows


def _ring_psum_wire(x, axis_name, chunks, bidirectional, codec,
                    wire_chunk):
    """Chunked quantized rotate-accumulate ring: each chunk is encoded
    exactly once at its origin; the packed wire buffer (payload +
    scales) rotates unchanged while every receiver decode-accumulates
    into an fp32 accumulator seeded with its own exact piece."""
    codec = get_codec(codec)
    n = lax.psum(1, axis_name)
    slices = _chunk_slices(x.shape[-1], chunks)
    k = len(slices)
    hops = n - 1
    log_collective_site("ring_psum", axis_name, "ppermute",
                        chunks=k, hops=hops)
    state = [None] * k            # (fp32 acc, wire buf, piece shape)
    dep = None
    for step in range(k + hops):
        if step < k:
            st, sz = slices[step]
            piece = lax.slice_in_dim(x, st, st + sz, axis=-1)
            state[step] = (piece.astype(jnp.float32),
                           encode_wire(piece, codec, wire_chunk),
                           piece.shape)
        for j in range(max(0, step - hops), min(step, k)):
            acc, buf, shp = state[j]
            buf, dep = _ordered_ppermute(
                buf, axis_name,
                _ring_perm(n, bidirectional and j % 2 == 1), dep)
            acc = acc + decode_wire(buf, codec, shp, jnp.float32,
                                    wire_chunk)
            state[j] = (acc, buf, shp)
    pieces = [acc.astype(x.dtype) for acc, _, _ in state]
    if k == 1:
        return pieces[0]
    return jnp.concatenate(pieces, axis=-1)


def ring_psum(x, axis_name, chunks=1, bidirectional=False,
              wire_dtype=None, wire_chunk=512):
    """Rotate-accumulate ring psum: ``buf = ppermute(buf); acc += buf``
    for n-1 hops — each hop forwards the value just *received*, so after
    n-1 hops every rank holds the full sum as n-1 ``collective-permute``s
    instead of one blocking ``all-reduce``.

    ``chunks > 1`` splits the trailing dim into independent ring
    pipelines (wavefront-interleaved in trace order: chunk *i*'s hops
    issue against chunk *i+1*'s slicing/adds, and XLA's scheduler can
    overlap them with adjacent compute). ``bidirectional`` sends
    even-indexed chunks one way around the ring and odd-indexed chunks
    the other, halving the per-direction ring latency.

    ``wire_dtype`` names a codec from the shared registry
    (``runtime/comm/codecs.py``): the exchange then moves quantized
    payloads + packed per-chunk scales instead of ``x.dtype``.
    ``chunks <= 1`` with a wire routes through the bracketed
    quantize→monolithic-collective reference; ``chunks > 1`` runs the
    encode-once quantized ring pipelined exactly like the full-precision
    wavefront."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return x
    if wire_dtype is not None:
        if x.ndim == 0 or chunks <= 1:
            return _wire_psum_monolithic(x, axis_name, wire_dtype,
                                         wire_chunk)
        return _ring_psum_wire(x, axis_name, chunks, bidirectional,
                               wire_dtype, wire_chunk)
    if x.ndim == 0 or chunks <= 1:
        slices = [None]          # one ring over the whole tensor
    else:
        slices = _chunk_slices(x.shape[-1], chunks)
    k = len(slices)
    hops = n - 1
    log_collective_site("ring_psum", axis_name, "ppermute",
                        chunks=k, hops=hops)
    state = [None] * k
    dep = None
    for step in range(k + hops):
        if step < k:
            sl = slices[step]
            piece = x if sl is None else lax.slice_in_dim(
                x, sl[0], sl[0] + sl[1], axis=-1)
            state[step] = (piece, piece)
        for j in range(max(0, step - hops), min(step, k)):
            acc, buf = state[j]
            buf, dep = _ordered_ppermute(
                buf, axis_name,
                _ring_perm(n, bidirectional and j % 2 == 1), dep)
            state[j] = (acc + buf, buf)
    if k == 1:
        return state[0][0]
    return jnp.concatenate([acc for acc, _ in state], axis=-1)


def ring_all_gather(x, axis_name, axis=0, chunks=1, bidirectional=False,
                    dep=None, site="ring_all_gather", wire_dtype=None,
                    wire_chunk=512):
    """Gather every rank's shard of ``x`` along ``axis``, returning
    ``(gathered, dep)`` where ``dep`` threads the :func:`barrier_after`
    chain to the caller (pass it into the next gather so consecutive
    rings issue in a fixed order — the ZeRO-3 prefetch schedule and the
    CPU-rendezvous safety invariant at once).

    ``chunks <= 1`` is a single tiled ``lax.all_gather`` — bit-identical
    to the spec-sharded baseline's gather. ``chunks > 1`` splits the
    local shard into stripes, each rotated around the ring by n-1
    dep-chained ``ppermute`` hops and placed into the output at its
    owner's offset, so stripe transfers interleave with the consuming
    compute instead of blocking on one monolithic collective.
    ``bidirectional`` alternates ring direction per stripe.

    ``wire_dtype`` names a codec from the shared registry: stripes move
    quantized (payload + packed scales in one u8 buffer) and decode on
    arrival; the local stripe is placed exactly. ``chunks <= 1`` with a
    wire is the bracketed encode→``all_gather``→decode reference."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return x, dep
    k_loc = x.shape[axis]
    if wire_dtype is not None and (chunks <= 1 or k_loc < 2):
        return _wire_all_gather_monolithic(x, axis_name, axis, wire_dtype,
                                           wire_chunk, dep=dep, site=site)
    if chunks <= 1 or k_loc < 2:
        log_collective_site(site, axis_name, "all_gather")
        out = lax.all_gather(barrier_after(x, dep), axis_name,
                             axis=axis, tiled=True)
        return out, out
    codec = get_codec(wire_dtype)
    slices = _chunk_slices(k_loc, chunks)
    log_collective_site(site, axis_name, "ppermute",
                        chunks=len(slices), hops=n - 1)
    r = lax.axis_index(axis_name)
    out_shape = list(x.shape)
    out_shape[axis] = n * k_loc
    out = jnp.zeros(out_shape, x.dtype)
    for j, (st, sz) in enumerate(slices):
        rev = bidirectional and j % 2 == 1
        shift = -1 if rev else 1
        perm = _ring_perm(n, rev)
        stripe = lax.slice_in_dim(x, st, st + sz, axis=axis)
        buf = stripe if codec is None else encode_wire(stripe, codec,
                                                       wire_chunk)
        for h in range(n):
            if h:
                buf, dep = _ordered_ppermute(buf, axis_name, perm, dep)
            src = jnp.mod(r - shift * h, n)   # owner of the stripe in buf
            if codec is None:
                piece = buf
            elif h == 0:
                piece = stripe            # own stripe: exact, no decode
            else:
                piece = decode_wire(buf, codec, stripe.shape, x.dtype,
                                    wire_chunk)
            out = lax.dynamic_update_slice_in_dim(
                out, piece, src * k_loc + st, axis=axis)
    return out, dep


# ---------------------------------------------------------------------------
# collective matmul: psum / reduce-scatter / all-gather forms
# ---------------------------------------------------------------------------

def _local_matmul_chunked(a, b, chunks):
    """The purely local chunked product ``concat_j(a @ b[..., sl_j])``.
    The overlap primitives' backward is ``jax.vjp`` of this — the
    transposed schedule stays chunk-granular for free."""
    slices = _chunk_slices(b.shape[-1], chunks)
    if len(slices) == 1:
        return jnp.matmul(a, b)
    return jnp.concatenate(
        [jnp.matmul(a, lax.slice_in_dim(b, st, st + sz, axis=-1))
         for st, sz in slices], axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _matmul_psum_overlap(a, b, axis_name, chunks, bidirectional,
                         wire_dtype, wire_chunk):
    n = lax.psum(1, axis_name)
    if chunks <= 1 or n == 1 or b.shape[-1] < 2:
        if n > 1 and wire_dtype is not None:
            # bracketed quantized reference: local product, then the
            # encode→monolithic-gather→decode-sum all-reduce
            return _wire_psum_monolithic(
                jnp.matmul(a, b), axis_name, wire_dtype, wire_chunk,
                site="matmul_psum_overlap")
        # monolithic path: bit-identical to psum_combine(a @ b)
        if n > 1:
            log_collective_site("matmul_psum_overlap", axis_name, "psum")
        return lax.psum(jnp.matmul(a, b), axis_name)
    codec = get_codec(wire_dtype)
    slices = _chunk_slices(b.shape[-1], chunks)
    k = len(slices)
    hops = n - 1
    log_collective_site("matmul_psum_overlap", axis_name, "ppermute",
                        chunks=k, hops=hops)
    state = [None] * k
    dep = None
    # Wavefront: at trace step s the matmul of chunk s issues alongside
    # one ring hop for every in-flight chunk s-hops..s-1 — the literal
    # "ppermute of chunk i against the matmul of chunk i+1" interleave.
    # The matmuls are free of the permute chain; the permutes order
    # among themselves (barrier_after) for the CPU rendezvous. With a
    # wire codec, the quantize of chunk s and the dequantize-accumulate
    # of arriving chunks sit on the same wavefront steps — the EQuARX
    # "quantization work inside the collective" schedule.
    for step in range(k + hops):
        if step < k:
            st, sz = slices[step]
            p = jnp.matmul(a, lax.slice_in_dim(b, st, st + sz, axis=-1))
            if codec is None:
                state[step] = (p, p)
            else:
                state[step] = (p.astype(jnp.float32),
                               encode_wire(p, codec, wire_chunk),
                               p.shape, p.dtype)
        for j in range(max(0, step - hops), min(step, k)):
            if codec is None:
                acc, buf = state[j]
                buf, dep = _ordered_ppermute(
                    buf, axis_name,
                    _ring_perm(n, bidirectional and j % 2 == 1), dep)
                state[j] = (acc + buf, buf)
            else:
                acc, buf, shp, dt = state[j]
                buf, dep = _ordered_ppermute(
                    buf, axis_name,
                    _ring_perm(n, bidirectional and j % 2 == 1), dep)
                acc = acc + decode_wire(buf, codec, shp, jnp.float32,
                                        wire_chunk)
                state[j] = (acc, buf, shp, dt)
    if codec is None:
        return jnp.concatenate([s[0] for s in state], axis=-1)
    return jnp.concatenate(
        [s[0].astype(s[3]) for s in state], axis=-1)


def _mpo_fwd(a, b, axis_name, chunks, bidirectional, wire_dtype,
             wire_chunk):
    return _matmul_psum_overlap(a, b, axis_name, chunks, bidirectional,
                                wire_dtype, wire_chunk), (a, b)


def _mpo_bwd(axis_name, chunks, bidirectional, wire_dtype, wire_chunk,
             res, g):
    # The combine's transpose is identity (output consumed replicated —
    # same convention as psum_combine); the matmul transposes
    # chunk-for-chunk through the vjp of the local chunked product. No
    # collective here, so the wire codec doesn't appear in the backward.
    a, b = res
    _, vjp = jax.vjp(
        lambda aa, bb: _local_matmul_chunked(aa, bb, chunks), a, b)
    return vjp(g)


_matmul_psum_overlap.defvjp(_mpo_fwd, _mpo_bwd)


def matmul_psum_overlap(a, b, axis_name, chunks=1, bidirectional=False,
                        wire_dtype=None, wire_chunk=512):
    """Overlapped ``psum_combine(a @ b)``: the row-parallel contraction
    with the output dim split into ``chunks`` pieces, each reduced by a
    rotate-accumulate ``ppermute`` ring that software-pipelines against
    the next chunk's matmul.

    ``a``: [..., K] local partial input; ``b``: [K, M] or batched
    [..., K, M] (this rank's shard of the contraction). Output [..., M]
    replicated across ``axis_name``. Backward: identity transpose of the
    combine + the chunk-granular transposed matmuls (no collective).
    ``chunks=1`` is bit-identical to ``psum_combine(a @ b)``.

    ``wire_dtype`` quantizes each chunk's ring exchange through the
    shared codec registry (per-chunk scales packed into the same
    ``ppermute`` payload, fp32 accumulate, own contribution exact and
    encoded exactly once); ``chunks=1`` with a wire is the bracketed
    quantize→monolithic-collective reference."""
    return _matmul_psum_overlap(
        a, b, axis_name, int(chunks), bool(bidirectional),
        None if wire_dtype is None else str(wire_dtype), int(wire_chunk))


def _wire_reduce_scatter_monolithic(y, axis_name, codec, wire_chunk,
                                    site="matmul_reduce_scatter"):
    """Bracketed quantized reduce-scatter of the full local product
    ``y`` [..., M]: per-destination shards are encoded once and exchanged
    by a single ``all_to_all`` over the stacked wire buffers, then each
    rank decode-sums its received shards in ascending rank order with its
    own contribution exact (fp32 accumulate). The ``chunks=1`` reference
    for the traveling-accumulator wire ring."""
    codec = get_codec(codec)
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    m_loc = y.shape[-1] // n
    shards = [lax.slice_in_dim(y, d * m_loc, (d + 1) * m_loc, axis=-1)
              for d in range(n)]
    wires = jnp.stack(
        [encode_wire(s, codec, wire_chunk) for s in shards], axis=0)
    log_collective_site(site, axis_name, "all_to_all")
    recv = lax.all_to_all(wires, axis_name, split_axis=0, concat_axis=0)
    own = lax.dynamic_slice_in_dim(
        y, r * m_loc, m_loc, axis=-1).astype(jnp.float32)
    acc = jnp.zeros(shards[0].shape, jnp.float32)
    for i in range(n):
        dec = decode_wire(recv[i], codec, shards[0].shape, jnp.float32,
                          wire_chunk)
        acc = acc + jnp.where(jnp.equal(i, r), own, dec)
    return acc.astype(y.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _matmul_reduce_scatter(a, b, axis_name, chunks, bidirectional,
                           wire_dtype, wire_chunk):
    n = lax.psum(1, axis_name)
    if n == 1:
        return jnp.matmul(a, b)
    M = b.shape[-1]
    assert M % n == 0, (
        f"matmul_reduce_scatter: output dim {M} must divide the axis "
        f"size {n}")
    m_loc = M // n
    if chunks <= 1 or m_loc < 2:
        y = jnp.matmul(a, b)
        if wire_dtype is not None:
            return _wire_reduce_scatter_monolithic(y, axis_name,
                                                   wire_dtype, wire_chunk)
        log_collective_site("matmul_reduce_scatter", axis_name,
                            "reduce_scatter")
        return lax.psum_scatter(y, axis_name,
                                scatter_dimension=y.ndim - 1, tiled=True)
    codec = get_codec(wire_dtype)
    log_collective_site("matmul_reduce_scatter", axis_name, "ppermute",
                        chunks=chunks, hops=n - 1)
    r = lax.axis_index(axis_name)
    outs = []
    dep = None
    for j, (st, sz) in enumerate(_chunk_slices(m_loc, chunks)):
        rev = bidirectional and j % 2 == 1
        shift = -1 if rev else 1
        perm = _ring_perm(n, rev)
        # Ring reduce-scatter for this column stripe: the accumulator
        # destined for rank d visits every rank once and lands at d; at
        # step t this rank adds its contribution for destination
        # (r - shift*(1+t)) mod n. The hop of step t's accumulator is
        # independent of step t's contribution matmul — the pipeline.
        # With a wire codec the hop moves the accumulator quantized —
        # re-encoded per hop, since the sum-so-far is what travels (the
        # EQuARX accuracy/bandwidth trade for reduce-scatter rings;
        # accumulation stays fp32 between hops).
        acc = None
        for t in range(n):
            dest = jnp.mod(r - shift * (1 + t), n)
            contrib = jnp.matmul(a, lax.dynamic_slice_in_dim(
                b, dest * m_loc + st, sz, axis=-1))
            if codec is not None:
                contrib = contrib.astype(jnp.float32)
            if t == 0:
                acc = contrib
            elif codec is None:
                hop, dep = _ordered_ppermute(acc, axis_name, perm, dep)
                acc = hop + contrib
            else:
                hop, dep = _ordered_ppermute(
                    encode_wire(acc, codec, wire_chunk), axis_name,
                    perm, dep)
                acc = decode_wire(hop, codec, contrib.shape, jnp.float32,
                                  wire_chunk) + contrib
        outs.append(acc if codec is None else
                    acc.astype(jnp.result_type(a, b)))
    return jnp.concatenate(outs, axis=-1)


def _mrs_fwd(a, b, axis_name, chunks, bidirectional, wire_dtype,
             wire_chunk):
    return _matmul_reduce_scatter(a, b, axis_name, chunks, bidirectional,
                                  wire_dtype, wire_chunk), (a, b)


def _mrs_bwd(axis_name, chunks, bidirectional, wire_dtype, wire_chunk,
             res, g):
    # Transposed schedule (reduce-scatter ↔ all-gather duality): ring-
    # gather the output-shard cotangent, overlapping each arriving shard
    # with its transposed matmul piece (vjp of a @ b[:, shard_src]).
    # With a wire codec the cotangent shards travel quantized too — the
    # transposed quantized schedule: each shard encoded once at its
    # origin, this rank's own shard used exactly.
    a, b = res
    n = lax.psum(1, axis_name)
    codec = get_codec(wire_dtype)
    if n == 1:
        _, vjp = jax.vjp(jnp.matmul, a, b)
        return vjp(g)
    m_loc = g.shape[-1]
    r = lax.axis_index(axis_name)
    if chunks <= 1:
        if codec is None:
            ghat = lax.all_gather(g, axis_name, axis=g.ndim - 1,
                                  tiled=True)
        else:
            ghat, _ = _wire_all_gather_monolithic(
                g, axis_name, g.ndim - 1, codec, wire_chunk,
                site="matmul_reduce_scatter")
        _, vjp = jax.vjp(jnp.matmul, a, b)
        return vjp(ghat)
    perm = _ring_perm(n)
    buf = g if codec is None else encode_wire(g, codec, wire_chunk)
    dep = None
    ga = gb = None
    for h in range(n):
        if h:
            buf, dep = _ordered_ppermute(buf, axis_name, perm, dep)
        src = jnp.mod(r - h, n)      # whose output-shard cotangent arrived
        if codec is None:
            shard = buf
        elif h == 0:
            shard = g                 # own cotangent shard: exact
        else:
            shard = decode_wire(buf, codec, g.shape, g.dtype, wire_chunk)

        def piece(aa, bb, src=src):
            return jnp.matmul(aa, lax.dynamic_slice_in_dim(
                bb, src * m_loc, m_loc, axis=-1))

        _, vjp = jax.vjp(piece, a, b)
        dga, dgb = vjp(shard)
        ga = dga if ga is None else ga + dga
        gb = dgb if gb is None else gb + dgb
    return ga, gb


_matmul_reduce_scatter.defvjp(_mrs_fwd, _mrs_bwd)


def matmul_reduce_scatter(a, b, axis_name, chunks=1, bidirectional=False,
                          wire_dtype=None, wire_chunk=512):
    """Overlapped ``psum_scatter(a @ b)``: each rank ends with its
    output-dim shard of the reduced product. ``chunks > 1`` stripes the
    local shard width and runs an overlapped ring reduce-scatter per
    stripe (contribution matmuls pipeline against the accumulator hops);
    ``chunks=1`` is the monolithic matmul + ``lax.psum_scatter``.

    ``a``: [..., K] local input; ``b``: [K, M] / [..., K, M] local shard
    of the contraction, M divisible by the axis size. Output
    [..., M/n]. Backward ring-gathers the cotangent with the transposed
    overlapped schedule (all-gather ↔ reduce-scatter duality).

    ``wire_dtype`` quantizes the exchange through the shared codec
    registry: the chunked ring re-encodes the traveling accumulator per
    hop (fp32 between hops), ``chunks=1`` routes through the bracketed
    encode→``all_to_all``→decode-sum reference, and the backward carries
    the transposed quantized gather."""
    return _matmul_reduce_scatter(
        a, b, axis_name, int(chunks), bool(bidirectional),
        None if wire_dtype is None else str(wire_dtype), int(wire_chunk))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _all_gather_matmul(x, w, axis_name, chunks, bidirectional):
    n = lax.psum(1, axis_name)
    if n == 1:
        return jnp.matmul(x, w)
    k_loc = x.shape[-1]
    assert w.shape[-2] == n * k_loc, (
        f"all_gather_matmul_overlap: w contraction dim {w.shape[-2]} != "
        f"axis size {n} x local width {k_loc}")
    if chunks <= 1 or k_loc < 2:
        log_collective_site("all_gather_matmul", axis_name, "all_gather")
        xhat = lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)
        return jnp.matmul(xhat, w)
    log_collective_site("all_gather_matmul", axis_name, "ppermute",
                        chunks=chunks, hops=n - 1)
    r = lax.axis_index(axis_name)
    out = None
    dep = None
    for j, (st, sz) in enumerate(_chunk_slices(k_loc, chunks)):
        rev = bidirectional and j % 2 == 1
        shift = -1 if rev else 1
        perm = _ring_perm(n, rev)
        buf = lax.slice_in_dim(x, st, st + sz, axis=-1)
        for h in range(n):
            if h:
                buf, dep = _ordered_ppermute(buf, axis_name, perm, dep)
            src = jnp.mod(r - shift * h, n)   # owner of the stripe in buf
            rows = lax.dynamic_slice_in_dim(w, src * k_loc + st, sz,
                                            axis=-2)
            t = jnp.matmul(buf, rows)
            out = t if out is None else out + t
    return out


def _agm_fwd(x, w, axis_name, chunks, bidirectional):
    return _all_gather_matmul(x, w, axis_name, chunks, bidirectional), \
        (x, w)


def _agm_bwd(axis_name, chunks, bidirectional, res, g):
    # Replicated-output convention (the conjugate of psum_combine): the
    # cotangent g is THE cotangent, taken once. dx is the purely local
    # s = r piece; dw needs the full gathered x again — re-run the ring,
    # overlapping each arriving x shard with its transposed dw matmul
    # (the transposed overlapped schedule).
    x, w = res
    n = lax.psum(1, axis_name)
    if n == 1:
        _, vjp = jax.vjp(jnp.matmul, x, w)
        return vjp(g)
    k_loc = x.shape[-1]
    r = lax.axis_index(axis_name)
    if chunks <= 1:
        xhat = lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)
        _, vjp = jax.vjp(jnp.matmul, xhat, w)
        dxhat, gw = vjp(g)
        gx = lax.dynamic_slice_in_dim(dxhat, r * k_loc, k_loc, axis=-1)
        return gx, gw
    perm = _ring_perm(n)
    buf = x
    dep = None
    gx = gw = None
    for h in range(n):
        if h:
            buf, dep = _ordered_ppermute(buf, axis_name, perm, dep)
        src = jnp.mod(r - h, n)

        def piece(xx, ww, src=src):
            return jnp.matmul(xx, lax.dynamic_slice_in_dim(
                ww, src * k_loc, k_loc, axis=-2))

        _, vjp = jax.vjp(piece, buf, w)
        dxx, dgw = vjp(g)
        if h == 0:
            gx = dxx                  # the s = r term is the local one
        gw = dgw if gw is None else gw + dgw
    return gx, gw


_all_gather_matmul.defvjp(_agm_fwd, _agm_bwd)


def all_gather_matmul_overlap(x, w, axis_name, chunks=1,
                              bidirectional=False):
    """Overlapped ``matmul(all_gather(x), w)`` — the conjugate
    decomposition for gather-then-matmul sites: rotate the contraction
    shards around the ring, multiplying each arriving stripe against its
    matching row block of ``w`` while the next stripe is in flight.

    ``x``: [..., K/n] this rank's shard of the contraction dim;
    ``w``: [K, M] replicated. Output [..., M] replicated. The cotangent
    is taken once (replicated-output convention, the conjugate of
    :func:`psum_combine`): dx is the local row block of ``g @ w.T`` and
    dw re-gathers x through the transposed overlapped ring.
    ``chunks=1`` is bit-identical to ``all_gather`` + ``matmul``."""
    return _all_gather_matmul(x, w, axis_name, int(chunks),
                              bool(bidirectional))


def all_to_all_overlap(x, axis_name, split_axis, concat_axis, chunks=1):
    """Tiled ``all_to_all`` decomposed into n-1 shift-``ppermute``s plus
    the local slice, so each peer exchange is an independently
    schedulable transfer XLA can overlap with chunked compute (the
    Ulysses bracket decomposition). ``chunks <= 1`` keeps the monolithic
    ``lax.all_to_all``. Pure data movement — a permutation of elements —
    so plain AD transposes it exactly (no ``custom_vjp`` needed).
    Shift-h perms already use both ring directions, so there is no
    separate bidirectional variant."""
    n = lax.psum(1, axis_name)
    if n == 1:
        return x
    if chunks <= 1:
        log_collective_site("all_to_all_overlap", axis_name, "all_to_all")
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)
    log_collective_site("all_to_all_overlap", axis_name, "ppermute",
                        hops=n - 1)
    size = x.shape[split_axis]
    assert size % n == 0, (
        f"all_to_all_overlap: split dim {size} not divisible by axis "
        f"size {n}")
    piece = size // n
    keep = x.shape[concat_axis]
    r = lax.axis_index(axis_name)
    out_shape = list(x.shape)
    out_shape[split_axis] = piece
    out_shape[concat_axis] = keep * n
    out = jnp.zeros(out_shape, x.dtype)
    dep = None
    for h in range(n):
        dst = jnp.mod(r + h, n)
        send = lax.dynamic_slice_in_dim(x, dst * piece, piece,
                                        axis=split_axis)
        if h == 0:
            recv = send
        else:
            recv, dep = _ordered_ppermute(
                send, axis_name,
                [(i, (i + h) % n) for i in range(n)], dep)
        src = jnp.mod(r - h, n)       # tiled semantics: block src of out
        out = lax.dynamic_update_slice_in_dim(out, recv, src * keep,
                                              axis=concat_axis)
    return out
