"""Tensor parallelism inside the compiled pipeline (dp x pp x tp — 3D).

The reference's 3D story is Megatron TP layers wrapped in DeepSpeed
pipeline stages (`docs/_tutorials/megatron.md`; PipelineModule over
Megatron's ColumnParallel/RowParallel). Our GSPMD TP layer library
(`parallel/tensor_parallel.py`) relies on sharding constraints, which are
inert inside the pipeline's manual ``shard_map`` — so the pipeline body
needs TP written with explicit collectives, like the expert-parallel FFN
(`moe/expert_pipe.py`):

- ``mp_*``-named param leaves carry their shard dim FIRST and are split
  over the ``model`` mesh axis by the pipeline's body specs
  (`runtime/pipe/pipeline.py:body_param_specs`);
- column-parallel matmuls produce head/hidden shards with no comm;
  row-parallel matmuls produce partial sums combined by one
  ``psum_combine`` (psum forward, identity backward — the Megatron
  ``g`` function);
- ``psum_grad`` on the replicated input repairs the partial cotangents
  from the column-parallel consumers (Megatron's ``f`` function).
"""

import jax
import jax.numpy as jnp
from jax import lax
import flax.linen as nn

from deepspeed_tpu.moe.expert_pipe import psum_combine, psum_grad
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention


def _axis_bound(ax):
    """Manual-mode probe — outside shard_map (build-time shape inference,
    sequential oracles) the layer runs replicated with no collectives."""
    try:
        lax.axis_index(ax)
        return True
    except NameError:
        return False


class TPBlockLayer:
    """GPT-2-style transformer block, tensor-parallel over ``model``.

    Param leaves (shard dim first, split over ``model`` by body specs):
      ``mp_qkv``   [n_head_local * 3 * D, M]   column-parallel QKV,
                                               packed HEAD-major (H, 3, D)
                                               so the model-axis split
                                               keeps whole heads (q,k,v
                                               together per head)
      ``mp_qkv_b`` [n_head_local * 3 * D]
      ``mp_proj``  [n_head_local * D, M]       row-parallel attn out
      ``mp_fc``    [ffn_local, M]              column-parallel MLP in
      ``mp_fc_b``  [ffn_local]
      ``mp_fc_out`` [ffn_local, M]             row-parallel MLP out
    Replicated: ``ln1/ln2`` scale+bias, ``proj_b``, ``fc_out_b`` [M]
    (row-parallel biases add once, after the psum).

    ``n_head`` must divide by the model-axis size. Attention runs on the
    LOCAL heads (flash on TPU) — the Megatron head-partition.
    """

    def __init__(self, d_model, n_head, ffn_mult=4, axis_name="model",
                 use_flash=False):
        assert d_model % n_head == 0
        self.d_model = d_model
        self.n_head = n_head
        self.ffn = ffn_mult * d_model
        self.axis_name = axis_name
        self.use_flash = use_flash

    def init(self, rng, x):
        M, H = self.d_model, self.n_head
        D = M // H
        ks = jax.random.split(rng, 4)
        init = nn.initializers.normal(0.02)
        return {
            "ln1_scale": jnp.ones((M,), jnp.float32),
            "ln1_bias": jnp.zeros((M,), jnp.float32),
            "ln2_scale": jnp.ones((M,), jnp.float32),
            "ln2_bias": jnp.zeros((M,), jnp.float32),
            "mp_qkv": init(ks[0], (3 * H * D, M), jnp.float32),
            "mp_qkv_b": jnp.zeros((3 * H * D,), jnp.float32),
            "mp_proj": init(ks[1], (H * D, M), jnp.float32),
            "proj_b": jnp.zeros((M,), jnp.float32),
            "mp_fc": init(ks[2], (self.ffn, M), jnp.float32),
            "mp_fc_b": jnp.zeros((self.ffn,), jnp.float32),
            "mp_fc_out": init(ks[3], (self.ffn, M), jnp.float32),
            "fc_out_b": jnp.zeros((M,), jnp.float32),
        }

    @staticmethod
    def _ln(x, scale, bias):
        mean = x.mean(-1, keepdims=True)
        var = ((x - mean) ** 2).mean(-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + 1e-5) * scale + bias

    def apply(self, params, x, rng=None):
        ax = self.axis_name
        bound = _axis_bound(ax)
        B, T, M = x.shape
        dtype = x.dtype
        three_hd = params["mp_qkv"].shape[0]        # H_local * 3 * D
        D = M // self.n_head
        h_local = three_hd // (3 * D)

        # ---- attention (column-parallel QKV, local heads, row proj) ----
        h = self._ln(x, params["ln1_scale"], params["ln1_bias"]).astype(dtype)
        if bound:
            h = psum_grad(h, ax)                    # Megatron "f"
        qkv = h @ params["mp_qkv"].T.astype(dtype) + \
            params["mp_qkv_b"].astype(dtype)        # [B,T,hl*3*D]
        qkv = qkv.reshape(B, T, h_local, 3, D)      # head-major packing
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        if self.use_flash:
            y = flash_attention(q, k, v, causal=True)
        else:
            scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
            s = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s * scale, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(dtype)
            y = jnp.einsum("bhts,bshd->bthd", p, v)
        y = y.reshape(B, T, h_local * D)
        part = y @ params["mp_proj"].astype(dtype)  # [B,T,M] partial
        if bound:
            part = psum_combine(part, ax)           # Megatron "g"
        x = x + part + params["proj_b"].astype(dtype)

        # ---- MLP (column fc, row fc_out) -------------------------------
        h2 = self._ln(x, params["ln2_scale"], params["ln2_bias"]).astype(dtype)
        if bound:
            h2 = psum_grad(h2, ax)
        ff = jax.nn.gelu(h2 @ params["mp_fc"].T.astype(dtype) +
                         params["mp_fc_b"].astype(dtype))
        part2 = ff @ params["mp_fc_out"].astype(dtype)
        if bound:
            part2 = psum_combine(part2, ax)
        return x + part2 + params["fc_out_b"].astype(dtype)
