"""Tensor parallelism inside the compiled pipeline (dp x pp x tp — 3D).

The reference's 3D story is Megatron TP layers wrapped in DeepSpeed
pipeline stages (`docs/_tutorials/megatron.md`; PipelineModule over
Megatron's ColumnParallel/RowParallel). Our GSPMD TP layer library
(`parallel/tensor_parallel.py`) relies on sharding constraints, which are
inert inside the pipeline's manual ``shard_map`` — so the pipeline body
needs TP written with explicit collectives.

This module provides the reusable manual-collective layer functions
(round 4 — previously they were fused into one bespoke GPT-2 block):

- :func:`replicated_input` — Megatron ``f`` (identity fwd, grad-psum bwd)
  on a replicated tensor about to be consumed by column-parallel compute;
- :func:`column_parallel` / :func:`row_parallel` — the conjugate matmul
  pair (column: output-dim sharded, no comm; row: input-dim sharded, one
  ``psum_combine`` — Megatron ``g``);
- :func:`split_qkv_heads` / :func:`local_attention` — head-major QKV
  packing and the local-head attention core (the Megatron
  head-partition);

and two block architectures built from them: :class:`TPBlockLayer`
(GPT-2-style pre-LN causal) and :class:`TPBertBlockLayer` (BERT-style
post-LN bidirectional). Manual mode is declared by the pipeline via
``parallel.collectives.manual_axes``; outside it (build-time shape
inference, sequential oracles) every layer runs replicated with no
collectives.

Param-leaf convention shared with the pipeline's body specs
(`runtime/pipe/pipeline.py:body_param_specs`): ``mp_*``-named leaves
carry their shard dim FIRST and are split over the ``model`` mesh axis.
"""

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.parallel.collectives import (axis_is_manual,
                                                matmul_psum_overlap,
                                                overlap_plan, psum_combine,
                                                psum_grad)
from deepspeed_tpu.ops.fp8 import (fp8_dot_general, fp8_plan,
                                   in_qdq_current, out_qdq_current)
from deepspeed_tpu.ops.pallas import flash_attention


# ---------------------------------------------------------------------------
# reusable manual-collective layer functions
# ---------------------------------------------------------------------------

def _local_dot(x, w, site):
    """Shard-local GEMM through the fp8 entry point: under an active
    ``fp8_scope`` (the pipeline threads its plan into the shard_map
    trace) the operands qdq via current scaling — the manual path has no
    per-site state threading; with no scope this IS ``lax.dot_general``."""
    return fp8_dot_general(x, w, (((x.ndim - 1,), (0,)), ((), ())),
                           site=site)


def _fp8_bracket(x, w, site):
    """Operand qdq for the overlapped primitives, which fuse the GEMM
    with the ring (no inner dot to swap out): returns quantize-
    dequantized operands plus an unquantizer bracketing the output so
    the backward cotangent qdq-quantizes to ``f8e5m2`` exactly like the
    :func:`fp8_dot_general` path."""
    plan = fp8_plan()
    if plan is None or not plan.site_enabled(site):
        return x, w, lambda y: y
    m = plan.margin
    return (in_qdq_current(x, m), in_qdq_current(w, m),
            lambda y: out_qdq_current(y, m))

def replicated_input(h, axis_name):
    """Megatron ``f``: identity forward; in manual mode, psum of the
    cotangent over ``axis_name`` in backward. Apply ONCE to each
    replicated tensor feeding column-parallel compute.

    Under an active ``column_parallel`` overlap plan the backward's
    monolithic all-reduce becomes the chunked rotate-accumulate
    ``ppermute`` ring (latency-hiding against the adjacent backward
    matmuls)."""
    if not axis_is_manual(axis_name):
        return h
    plan = overlap_plan("column_parallel")
    if plan is not None and (plan.chunks > 1 or plan.wire_dtype):
        return psum_grad(h, axis_name, chunks=plan.chunks,
                         bidirectional=plan.bidirectional,
                         wire_dtype=plan.wire_dtype,
                         wire_chunk=plan.wire_chunk)
    return psum_grad(h, axis_name)


def column_parallel(h, w, b=None):
    """Column-parallel matmul: ``w`` [out_local, M] (shard dim first) →
    [B, T, out_local], no communication. The caller is responsible for
    :func:`replicated_input` on ``h`` (once per consumed tensor). The
    local GEMM goes through the fp8 entry point (site
    ``column_parallel``) — a no-op without an active fp8 plan."""
    y = _local_dot(h, w.T.astype(h.dtype), "column_parallel")
    if b is not None:
        y = y + b.astype(h.dtype)
    return y


def row_parallel(y, w, b, axis_name):
    """Row-parallel matmul: ``w`` [in_local, M] (shard dim first) →
    partial [B, T, M] summed across ``axis_name`` (Megatron ``g``, one
    psum_combine) in manual mode. ``b`` [M] is replicated and added once,
    after the combine.

    Under an active ``row_parallel`` overlap plan the matmul + monolithic
    all-reduce is replaced by :func:`matmul_psum_overlap`: the output dim
    is split into chunks whose ``ppermute`` ring reductions software-
    pipeline against the next chunk's matmul."""
    if axis_is_manual(axis_name):
        plan = overlap_plan("row_parallel")
        if plan is not None and (plan.chunks > 1 or plan.wire_dtype):
            yq, wq, unq = _fp8_bracket(y, w.astype(y.dtype),
                                       "row_parallel")
            part = unq(matmul_psum_overlap(yq, wq, axis_name,
                                           chunks=plan.chunks,
                                           bidirectional=plan.bidirectional,
                                           wire_dtype=plan.wire_dtype,
                                           wire_chunk=plan.wire_chunk))
        else:
            part = psum_combine(
                _local_dot(y, w.astype(y.dtype), "row_parallel"),
                axis_name)
    else:
        part = _local_dot(y, w.astype(y.dtype), "row_parallel")
    if b is not None:
        part = part + b.astype(y.dtype)
    return part


def split_qkv_heads(qkv, d_head):
    """Head-major unpack: [B, T, h_local * 3 * D] → (q, k, v), each
    [B, T, h_local, D]. HEAD-major packing (H, 3, D) keeps whole heads
    (q, k, v together per head) under the model-axis split of
    ``mp_qkv``."""
    B, T, three_hd = qkv.shape
    h_local = three_hd // (3 * d_head)
    qkv = qkv.reshape(B, T, h_local, 3, d_head)
    return qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]


def local_attention(q, k, v, causal, use_flash=False, dropout_rate=0.0,
                    dropout_seed=None, head_offset=0, n_heads_global=None):
    """Attention over the LOCAL heads (the Megatron head-partition);
    flash kernels on TPU when ``use_flash``. Returns [B, T, hl * D].

    Attention-prob dropout uses the shared counter-based hash at GLOBAL
    head coordinates (``head_offset`` = this rank's first head,
    ``n_heads_global`` = total heads), so the mask is invariant to the
    model-axis sharding — a sharded run reproduces the replicated run
    bitwise. Since round 5 the flash kernels take the global coordinates
    directly (``dropout_head_offset``/``dropout_num_heads``), so
    ``use_flash`` keeps the fused O(T)-memory path under dropout too."""
    B, T, h_local, D = q.shape
    if use_flash:
        y = flash_attention(
            q, k, v, causal=causal, dropout_rate=dropout_rate,
            dropout_seed=dropout_seed, dropout_head_offset=head_offset,
            dropout_num_heads=n_heads_global)
    else:
        # Same globalized dropout coordinates, reference math — one
        # implementation of the global-bh formula, not two.
        from deepspeed_tpu.ops.pallas.flash_attention import (
            dense_attention)
        y = dense_attention(q, k, v, causal=causal,
                            dropout_rate=dropout_rate,
                            dropout_seed=dropout_seed,
                            dropout_head_offset=head_offset,
                            dropout_num_heads=n_heads_global)
    return y.reshape(B, T, h_local * D)


def layer_norm(x, scale, bias, eps=1e-5):
    mean = x.mean(-1, keepdims=True)
    var = ((x - mean) ** 2).mean(-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * scale + bias


def tp_attention_params(rng, d_model, n_head):
    """The attention half of the TP param-leaf contract (names double as
    the sharding contract — see module docstring). Shared by the dense
    TP blocks here and the MoE composition (`pipe_tp_moe.py`), so the
    head-major packing and init scale live in exactly one place."""
    M, H = d_model, n_head
    D = M // H
    ks = jax.random.split(rng, 2)
    init = nn.initializers.normal(0.02)
    return {
        "ln1_scale": jnp.ones((M,), jnp.float32),
        "ln1_bias": jnp.zeros((M,), jnp.float32),
        "mp_qkv": init(ks[0], (3 * H * D, M), jnp.float32),
        "mp_qkv_b": jnp.zeros((3 * H * D,), jnp.float32),
        "mp_proj": init(ks[1], (H * D, M), jnp.float32),
        "proj_b": jnp.zeros((M,), jnp.float32),
    }


def _tp_block_params(rng, d_model, n_head, ffn):
    """The shared param-leaf set of both dense TP blocks: attention half
    plus the column/row-parallel MLP."""
    M = d_model
    ka, km = jax.random.split(rng)
    ks = jax.random.split(km, 2)
    init = nn.initializers.normal(0.02)
    p = tp_attention_params(ka, d_model, n_head)
    p.update({
        "ln2_scale": jnp.ones((M,), jnp.float32),
        "ln2_bias": jnp.zeros((M,), jnp.float32),
        "mp_fc": init(ks[0], (ffn, M), jnp.float32),
        "mp_fc_b": jnp.zeros((ffn,), jnp.float32),
        "mp_fc_out": init(ks[1], (ffn, M), jnp.float32),
        "fc_out_b": jnp.zeros((M,), jnp.float32),
    })
    return p


# ---------------------------------------------------------------------------
# block architectures
# ---------------------------------------------------------------------------

class TPBlockLayer:
    """GPT-2-style pre-LN causal transformer block, tensor-parallel over
    ``model`` — composed from the layer functions above.

    Param leaves (shard dim first, split over ``model`` by body specs):
      ``mp_qkv``   [n_head_local * 3 * D, M]   column-parallel QKV,
                                               packed HEAD-major
      ``mp_qkv_b`` [n_head_local * 3 * D]
      ``mp_proj``  [n_head_local * D, M]       row-parallel attn out
      ``mp_fc``    [ffn_local, M]              column-parallel MLP in
      ``mp_fc_b``  [ffn_local]
      ``mp_fc_out`` [ffn_local, M]             row-parallel MLP out
    Replicated: ``ln1/ln2`` scale+bias, ``proj_b``, ``fc_out_b`` [M]
    (row-parallel biases add once, after the psum).

    ``n_head`` must divide by the model-axis size.
    """

    causal = True

    def __init__(self, d_model, n_head, ffn_mult=4, axis_name="model",
                 use_flash=False, dropout=0.0):
        assert d_model % n_head == 0
        self.d_model = d_model
        self.n_head = n_head
        self.ffn = ffn_mult * d_model
        self.axis_name = axis_name
        self.use_flash = use_flash
        self.dropout = dropout

    def init(self, rng, x):
        return _tp_block_params(rng, self.d_model, self.n_head, self.ffn)

    def _drop_ctx(self, params, rng):
        """(rate, attn_seed, head_offset, hidden_drop_fn) —
        sharding-invariant dropout: attention masks hash GLOBAL head
        coordinates and hidden masks come from the rng key, which is
        identical on every MODEL rank (replicated activations must drop
        the same units) but folded with the DATA rank so different batch
        shards draw independent noise (the pipeline's mb_rng folds
        microbatch + stage only)."""
        if rng is None or self.dropout == 0.0:
            return 0.0, None, 0, lambda t, sub: t
        from deepspeed_tpu.ops.pallas.flash_attention import (
            dropout_seed_from_rng)
        if axis_is_manual("data"):
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
        seed = dropout_seed_from_rng(rng)
        rank = (jax.lax.axis_index(self.axis_name)
                if axis_is_manual(self.axis_name) else 0)
        D = self.d_model // self.n_head
        h_local = params["mp_qkv"].shape[0] // (3 * D)
        keep = 1.0 - self.dropout

        def hidden_drop(t, sub):
            mask = jax.random.bernoulli(
                jax.random.fold_in(rng, sub), keep, t.shape)
            return jnp.where(mask, t / keep, 0.0).astype(t.dtype)

        return self.dropout, seed, rank * h_local, hidden_drop

    def apply(self, params, x, rng=None):
        ax = self.axis_name
        dtype = x.dtype
        D = self.d_model // self.n_head
        rate, seed, head_off, hidden_drop = self._drop_ctx(params, rng)

        # ---- attention (column QKV, local heads, row proj) ----------
        h = layer_norm(x, params["ln1_scale"],
                       params["ln1_bias"]).astype(dtype)
        h = replicated_input(h, ax)                 # Megatron "f"
        qkv = column_parallel(h, params["mp_qkv"], params["mp_qkv_b"])
        q, k, v = split_qkv_heads(qkv, D)
        y = local_attention(q, k, v, causal=self.causal,
                            use_flash=self.use_flash,
                            dropout_rate=rate, dropout_seed=seed,
                            head_offset=head_off,
                            n_heads_global=self.n_head)
        att = row_parallel(y, params["mp_proj"], params["proj_b"], ax)
        x = x + hidden_drop(att, 1)

        # ---- MLP (column fc, row fc_out) ----------------------------
        h2 = layer_norm(x, params["ln2_scale"],
                        params["ln2_bias"]).astype(dtype)
        h2 = replicated_input(h2, ax)
        ff = jax.nn.gelu(column_parallel(h2, params["mp_fc"],
                                         params["mp_fc_b"]))
        out = row_parallel(ff, params["mp_fc_out"],
                           params["fc_out_b"], ax)
        return x + hidden_drop(out, 2)


class TPBertBlockLayer(TPBlockLayer):
    """BERT-style post-LN bidirectional encoder block, tensor-parallel
    over ``model`` — the second architecture composed from the same layer
    functions (round-4 proof that pipeline-TP is a library, not one
    hand-written block). Shares constructor, param init and the param-leaf
    contract with :class:`TPBlockLayer` (``ln1`` = post-attention LN,
    ``ln2`` = post-FFN LN); only the block wiring differs."""

    causal = False

    def apply(self, params, x, rng=None):
        ax = self.axis_name
        dtype = x.dtype
        D = self.d_model // self.n_head
        rate, seed, head_off, hidden_drop = self._drop_ctx(params, rng)

        # ---- attention, then residual + post-LN ---------------------
        h = replicated_input(x, ax)
        qkv = column_parallel(h, params["mp_qkv"], params["mp_qkv_b"])
        q, k, v = split_qkv_heads(qkv, D)
        y = local_attention(q, k, v, causal=False,
                            use_flash=self.use_flash,
                            dropout_rate=rate, dropout_seed=seed,
                            head_offset=head_off,
                            n_heads_global=self.n_head)
        att = row_parallel(y, params["mp_proj"], params["proj_b"], ax)
        x = layer_norm(x + hidden_drop(att, 1), params["ln1_scale"],
                       params["ln1_bias"]).astype(dtype)

        # ---- FFN, then residual + post-LN ---------------------------
        h2 = replicated_input(x, ax)
        ff = jax.nn.gelu(column_parallel(h2, params["mp_fc"],
                                         params["mp_fc_b"]))
        out = row_parallel(ff, params["mp_fc_out"], params["fc_out_b"], ax)
        return layer_norm(x + hidden_drop(out, 2), params["ln2_scale"],
                          params["ln2_bias"]).astype(dtype)


def tp_pipeline_module(vocab, d_model, n_head, seq_len, n_blocks=2,
                       num_stages=None, ids_key="input_ids",
                       block_cls=TPBlockLayer):
    """PipelineModule wiring TP blocks (the dp x pp x tp composition):
    embed -> ``n_blocks`` x ``block_cls`` -> head, with a masked
    next-token CE in the weighted ``(loss_sum, count)`` form (final
    position ignored, no wraparound)."""
    import numpy as np
    from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

    class _Embed:
        def init(self, rng, micro):
            return {"emb": jax.random.normal(
                rng, (vocab, d_model), jnp.float32) * 0.1}

        def apply(self, p, micro, rng=None):
            return p["emb"][micro[ids_key]]

    class _Head:
        def init(self, rng, x):
            return {"w": jax.random.normal(
                rng, (d_model, vocab), jnp.float32) * 0.1}

        def apply(self, p, x, rng=None):
            return x @ p["w"]

    def loss(logits, micro):
        ids = micro[ids_key]
        B, T = ids.shape
        labels = jnp.concatenate(
            [ids[:, 1:], jnp.full((B, 1), -100, ids.dtype)], axis=1)
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok = -jnp.take_along_axis(lp, safe[..., None], -1).squeeze(-1)
        tok = jnp.where(valid, tok, 0.0)
        return tok.sum(), valid.sum().astype(jnp.float32)

    return PipelineModule(
        layers=[LayerSpec(_Embed)] +
               [LayerSpec(block_cls, d_model, n_head)
                for _ in range(n_blocks)] +
               [LayerSpec(_Head)],
        num_stages=num_stages, loss_fn=loss,
        example_input={ids_key: np.zeros((2, seq_len), np.int32)})
