"""User-composable tensor parallelism inside the compiled pipeline.

Round 5, VERDICT r4 next-round #7 option (a): lift an UNMODIFIED flax
module into the 1F1B pipeline body with its GSPMD partition metadata
intact. Until now the pipeline's ``shard_map`` was manual over every
mesh axis, which made the GSPMD TP layer library
(`parallel/tensor_parallel.py`) inert inside it — TP blocks had to be
hand-assembled from `parallel/pipe_tp.py`'s explicit-collective pieces.

The mechanism is ``jax.shard_map``'s partial-manual mode
(``axis_names``): ``PipelineModule(auto_axes=("model",))`` keeps
pipe/data manual (the 1F1B's ppermute schedule and batch sharding)
while the ``model`` axis stays in GSPMD (auto) mode — arrays are global
along it inside the body, the layer's ``nn.with_partitioning``
annotations shard params over it AT REST (the adapter exposes them to
``build_pipeline_parts`` for placement), and XLA inserts the Megatron
collectives exactly as it does outside the pipeline. No hand-written
``psum``/``replicated_input`` anywhere in the user's model.

Usage (the tested surface — the standalone pipeline program)::

    from deepspeed_tpu.parallel.tensor_parallel import TPTransformerBlock
    from deepspeed_tpu.parallel.pipe_auto import FlaxPipelineLayer

    module = PipelineModule(
        layers=[LayerSpec(_Embed)] +
               [LayerSpec(FlaxPipelineLayer, TPTransformerBlock,
                          n_head=4) for _ in range(n_blocks)] +
               [LayerSpec(_Head)],
        num_stages=2, loss_fn=..., example_input=...,
        auto_axes=("model",))
    parts = build_pipeline_parts(module, 2, rng, example_micro)
    vag = make_pipeline_value_and_grad_fn(parts, mesh, M,
                                          auto_axes=("model",))
    loss, grads = jax.jit(vag)(parts.params, batch, None, scale)

STATUS (round 5): the compute-level composition is proven — losses and
grads match the model=1 oracle exactly (`tests/unit/test_pipe_auto.py`)
and the adapter's partition metadata flows into the placement specs.
The ENGINE integration is gated off: placing body params sharded over
the auto axis deadlocks the in-process CPU runtime's collective
rendezvous (devices split 4/4 across the fwd/bwd ppermutes; XLA aborts
after its 40 s timeout), so `deepspeed_tpu.initialize` raises a clear
NotImplementedError for `auto_axes` rather than crash. Real-TPU
behavior (a different collective runtime) is untested pending tunnel
access. The production dp x pp x tp path remains the manual-collective
library (`parallel/pipe_tp.py`), which the reference posture — TP
delegated wholesale to Megatron
(`/root/reference/deepspeed/__init__.py:76-77`) — never had either.
"""

import jax
import flax.linen as nn
from flax.core import meta


class FlaxPipelineLayer:
    """Adapter: a flax ``nn.Module`` (constructor + kwargs) as a pipeline
    body layer. ``init`` records the module's partition metadata
    (``nn.get_partition_spec``) and returns raw arrays;
    ``param_partition_specs`` hands the per-leaf specs to
    ``build_pipeline_parts`` so the stacked body is PLACED sharded over
    the annotated axes (memory savings at rest, not just in compute).

    The wrapped module's ``__call__`` must be ``(x) -> y``; a dropout rng
    is threaded as ``rngs={"dropout": rng}`` when the pipeline provides
    one.
    """

    def __init__(self, module_ctor, *args, **kwargs):
        self.module = module_ctor(*args, **kwargs)
        self._layer_specs = None

    def init(self, rng, x):
        variables = self.module.init({"params": rng}, x)
        self._layer_specs = nn.get_partition_spec(variables["params"])
        return meta.unbox(variables["params"])

    def param_partition_specs(self, params):
        assert self._layer_specs is not None, "init() first"
        return self._layer_specs

    def apply(self, params, x, rng=None):
        rngs = {"dropout": rng} if rng is not None else {}
        return self.module.apply({"params": params}, x, rngs=rngs)
