"""Reusable tensor-parallel layer library (Megatron-style, GSPMD-native).

The reference delegates tensor parallelism to an external Megatron ``mpu``
object — it ships no TP layers of its own, only consumes the groups
(`deepspeed/__init__.py:76-77`, `runtime/engine.py:513-524`). Here TP is
first-class: column-/row-parallel linears and a full transformer block
whose params carry ``flax.linen.Partitioned`` metadata naming the mesh
axis each dim is sharded over. GSPMD then inserts the all-reduces Megatron
hand-codes (the psum after a row-parallel matmul is exactly Megatron's
``reduce_from_model_parallel_region``).

Usage::

    block = TPTransformerBlock(n_head=16, axis="model")
    variables = block.init(rng, x)                    # boxed params
    params = unbox_params(variables["params"])        # raw arrays
    specs = partition_specs(variables["params"])      # PartitionSpec tree
    engine, *_ = deepspeed_tpu.initialize(..., params=params,
                                          param_specs=specs, mesh=mesh)

The ``logical_constraint`` helper pins activations when XLA's propagation
needs a hint (e.g. sequence-parallel LayerNorm inputs).
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn
from flax.core import meta
from jax.sharding import PartitionSpec as P


def unbox_params(tree):
    """Strip ``nn.Partitioned`` boxes → raw array pytree (what the engine
    and optimizer consume)."""
    return meta.unbox(tree)


def partition_specs(tree):
    """Boxed params → PartitionSpec pytree aligned with
    :func:`unbox_params` output (feeds ``initialize(param_specs=...)``)."""
    return nn.get_partition_spec(tree)


def _axes_of(entry):
    """Mesh axis names referenced by one PartitionSpec entry — an entry
    may be None, a single name, or a TUPLE of names (a dim sharded over
    several axes at once, e.g. ``('data', 'model')``)."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def logical_constraint(x, *spec, mesh=None):
    """``with_sharding_constraint`` that degrades to a no-op when no mesh
    axis of that name exists (lets TP modules run unsharded in tests).
    Tuple entries constrain one array dim over several mesh axes:
    ``logical_constraint(x, ('data', 'model'), None, mesh=mesh)``."""
    if mesh is None:
        return x
    names = set(mesh.axis_names)
    for entry in spec:
        if not all(a in names for a in _axes_of(entry)):
            return x
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, P(*spec)))


class ColumnParallelLinear(nn.Module):
    """Linear with the output dim sharded over ``axis`` (Megatron column
    parallel): kernel [in, out@axis]; output activations land sharded, no
    collective needed. Pair with :class:`RowParallelLinear`."""

    features: int
    axis: Optional[str] = "model"
    use_bias: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.normal(0.02)

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (None, self.axis)),
            (x.shape[-1], self.features), self.param_dtype)
        y = x @ jnp.asarray(kernel, self.dtype)
        if self.use_bias:
            bias = self.param(
                "bias", nn.with_partitioning(nn.initializers.zeros,
                                             (self.axis,)),
                (self.features,), self.param_dtype)
            y = y + jnp.asarray(bias, self.dtype)
        return y


class RowParallelLinear(nn.Module):
    """Linear with the input dim sharded over ``axis`` (Megatron row
    parallel): kernel [in@axis, out]; each shard computes a partial
    product and GSPMD inserts the psum (Megatron's
    ``reduce_from_model_parallel_region``). Bias is replicated and added
    after the reduction."""

    features: int
    axis: Optional[str] = "model"
    use_bias: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.normal(0.02)

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (self.axis, None)),
            (x.shape[-1], self.features), self.param_dtype)
        y = x @ jnp.asarray(kernel, self.dtype)
        if self.use_bias:
            bias = self.param(
                "bias", nn.with_partitioning(nn.initializers.zeros,
                                             (None,)),
                (self.features,), self.param_dtype)
            y = y + jnp.asarray(bias, self.dtype)
        return y


class TPMultiHeadAttention(nn.Module):
    """Self-attention with heads sharded over ``axis``: column-parallel
    QKV (each shard owns n_head/axis_size heads end-to-end),
    row-parallel output projection.

    ``use_flash`` routes the score/softmax/value contraction through
    :func:`deepspeed_tpu.ops.pallas.flash_attention.flash_attention`
    (Pallas kernel on TPU, XLA fallback elsewhere) instead of
    materializing the [B, H, T, T] score matrix — same math, O(T)
    memory. The head partition is unchanged: the kernel only ever sees
    this shard's heads."""

    n_head: int
    axis: Optional[str] = "model"
    causal: bool = True
    use_flash: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, mesh=None):
        B, T, C = x.shape
        H = self.n_head
        qkv = ColumnParallelLinear(
            3 * C, axis=self.axis, dtype=self.dtype,
            param_dtype=self.param_dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, H, C // H)

        q, k, v = heads(q), heads(k), heads(v)
        # head dim sharded over the model axis
        q = logical_constraint(q, None, None, self.axis, None, mesh=mesh)
        if self.use_flash:
            from deepspeed_tpu.ops.pallas.flash_attention import \
                flash_attention
            y = flash_attention(q, k, v, causal=self.causal)
            y = y.astype(self.dtype).reshape(B, T, C)
        else:
            scale = 1.0 / jnp.sqrt(jnp.asarray(C // H, jnp.float32))
            att = jnp.einsum("bthd,bshd->bhts",
                             q, k).astype(jnp.float32) * scale
            if self.causal:
                mask = jnp.tril(jnp.ones((T, T), bool))
                att = jnp.where(mask[None, None], att,
                                jnp.finfo(jnp.float32).min)
            att = jax.nn.softmax(att, axis=-1).astype(self.dtype)
            y = jnp.einsum("bhts,bshd->bthd", att, v).reshape(B, T, C)
        return RowParallelLinear(
            C, axis=self.axis, dtype=self.dtype,
            param_dtype=self.param_dtype, name="c_proj")(y)


class TPMLP(nn.Module):
    """Column-parallel up-projection + row-parallel down-projection (the
    Megatron MLP split: the hidden dim never crosses shards)."""

    hidden_mult: int = 4
    axis: Optional[str] = "model"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        C = x.shape[-1]
        h = ColumnParallelLinear(
            self.hidden_mult * C, axis=self.axis, dtype=self.dtype,
            param_dtype=self.param_dtype, name="c_fc")(x)
        h = nn.gelu(h, approximate=True)
        return RowParallelLinear(
            C, axis=self.axis, dtype=self.dtype,
            param_dtype=self.param_dtype, name="c_proj")(h)


class TPTransformerBlock(nn.Module):
    """Pre-LN transformer block from the TP pieces; LayerNorms replicated
    (their params are tiny), residual stream replicated."""

    n_head: int
    axis: Optional[str] = "model"
    causal: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, mesh=None):
        x = x + TPMultiHeadAttention(
            self.n_head, axis=self.axis, causal=self.causal,
            dtype=self.dtype, param_dtype=self.param_dtype,
            name="attn")(nn.LayerNorm(dtype=self.dtype, name="ln_1")(x),
                         mesh=mesh)
        x = x + TPMLP(
            axis=self.axis, dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="mlp")(nn.LayerNorm(dtype=self.dtype, name="ln_2")(x))
        return x
