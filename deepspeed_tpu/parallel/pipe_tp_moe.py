"""TP attention + expert-parallel MoE FFN in ONE pipeline block —
the dp x pp x tp x ep composition (round 5, VERDICT r4 next-round #7b).

The reference's deepest composition is 3D (dp x pp x Megatron-TP,
`docs/_tutorials/megatron.md`); its MoE postdates v0.3.2 and never rode
the pipeline. This block goes one further: inside the pipeline's
``shard_map`` (every axis manual), the attention half is Megatron-style
tensor parallel over ``model`` (column QKV / local heads / row proj from
`parallel/pipe_tp.py`) and the FFN half is an expert-parallel MoE bank
over ``expert`` (`moe/expert_pipe.py`) — four mesh axes cooperating in
one compiled 1F1B program.

Cross-axis cotangent discipline (why this composes without new
collectives):
- over ``model``: only the attention path is sharded; ``replicated_input``
  / ``row_parallel`` psum exactly that path's cotangents/partials. The
  MoE half is replicated over ``model`` — its cotangents are full
  duplicates, no psum wanted.
- over ``expert``: only the FFN path is sharded; the
  ``ExpertParallelFFNLayer`` already psums its partial cotangents
  (``psum_grad`` on h/gate) and partial outputs (``psum_combine``). The
  attention half is replicated over ``expert`` — identical full
  cotangents per rank, again no psum wanted.
Each axis's collectives therefore wrap precisely the tensors consumed by
compute sharded on THAT axis, and the composition is exact (pinned by
`tests/unit/test_pipe_tp_moe.py` against the model=1, expert=1 oracle).

Param-leaf contract (`runtime/pipe/pipeline.py:body_param_specs`):
``mp_*`` leaves shard dim FIRST over ``model``; ``expert_*`` leaves bank
dim first over ``expert``; everything else replicated.
"""

import jax

from deepspeed_tpu.moe.expert_pipe import ExpertParallelFFNLayer
from deepspeed_tpu.moe.layer import MoEConfig
from deepspeed_tpu.parallel.pipe_tp import (column_parallel, layer_norm,
                                            local_attention,
                                            replicated_input, row_parallel,
                                            split_qkv_heads,
                                            tp_attention_params)


class TPMoEBlockLayer:
    """Pre-LN causal block: TP attention + MoE FFN (see module docstring).

    Param leaves:
      ``ln1_scale/ln1_bias`` [M]            replicated (attention pre-LN)
      ``mp_qkv``   [3 * H * D, M]           column-parallel, HEAD-major
      ``mp_qkv_b`` [3 * H * D]
      ``mp_proj``  [H * D, M]               row-parallel attention out
      ``proj_b``   [M]                      replicated (added post-psum)
      ``ln_scale/ln_bias/gate``             replicated (MoE pre-LN + router)
      ``expert_w1/b1/w2/b2`` [E, ...]       sharded over ``expert``

    Activations may be ``(hidden, aux)`` tuples — the Switch aux loss
    rides the pipeline exactly as in :class:`ExpertParallelFFNLayer`.
    Attention dropout is not supported here (compose at dropout=0 or use
    :class:`~deepspeed_tpu.parallel.pipe_tp.TPBlockLayer` for the dense
    dropout path).
    """

    causal = True

    def __init__(self, d_model, n_head, hidden_dim=None,
                 moe: MoEConfig = None, model_axis="model",
                 expert_axis="expert", use_flash=False):
        assert d_model % n_head == 0
        self.d_model = d_model
        self.n_head = n_head
        self.model_axis = model_axis
        self.use_flash = use_flash
        self.ffn = ExpertParallelFFNLayer(
            d_model, hidden_dim or 4 * d_model, moe, expert_axis)

    def init(self, rng, x):
        ka, kf = jax.random.split(rng)
        p = tp_attention_params(ka, self.d_model, self.n_head)
        p.update(self.ffn.init(kf, x[0] if isinstance(x, tuple) else x))
        return p

    def apply(self, params, x, rng=None):
        aux_in = None
        if isinstance(x, tuple):
            x, aux_in = x
        ax = self.model_axis
        dtype = x.dtype
        D = self.d_model // self.n_head

        # ---- TP attention (over `model`) ----------------------------
        h = layer_norm(x, params["ln1_scale"],
                       params["ln1_bias"]).astype(dtype)
        h = replicated_input(h, ax)                 # Megatron "f"
        qkv = column_parallel(h, params["mp_qkv"], params["mp_qkv_b"])
        q, k, v = split_qkv_heads(qkv, D)
        y = local_attention(q, k, v, causal=self.causal,
                            use_flash=self.use_flash)
        att = row_parallel(y, params["mp_proj"], params["proj_b"], ax)
        x = x + att

        # ---- MoE FFN (over `expert`; handles its own LN + residual
        #      + aux accounting; reads only its own leaves) ------------
        return self.ffn.apply(
            params, x if aux_in is None else (x, aux_in), rng)
