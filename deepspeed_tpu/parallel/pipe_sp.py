"""Sequence parallelism inside the compiled pipeline (pp x sp x dp).

Long-context composed with pipeline parallelism — beyond the reference,
whose long-context story is block-sparse attention only (SURVEY.md §5.7)
and whose pipeline knows nothing of sequence sharding. Inside the
pipeline's ``shard_map`` every axis is manual, so sequence parallelism
takes the same form as the TP/EP compositions (`parallel/pipe_tp.py`,
`moe/expert_pipe.py`): explicit collectives, gated by
``parallel.collectives.axis_is_manual``.

- :class:`SPEmbedLayer` (prologue) embeds the full microbatch sequence
  and slices this rank's token chunk — activations flow through the
  pipeline at [B, T/n, M], so stage-transfer ppermutes and attention
  memory shrink by the seq degree;
- :class:`SPBlockLayer` runs **Ulysses** attention over the ``seq`` axis
  (`parallel/sequence.py:ulysses_attention_local` — two all_to_alls
  re-shard tokens ⟷ heads) with weights replicated;
- :class:`SPHeadLayer` + :func:`make_sp_token_loss` produce the weighted
  ``(loss_sum, token_count)`` form the pipeline reduces exactly across
  seq shards (`runtime/pipe/pipeline.py` psums the seq axis for weighted
  losses — partial-sum semantics).

Why Ulysses and not ring here: the 1F1B gates stage bodies behind
stage-dependent ``lax.cond`` predicates (warmup/cooldown ticks,
last-stage special-casing), so a collective inside a body only executes
on the pipe ranks whose predicate is true that tick. Group-scoped
collectives whose participants all share the predicate are fine — TP's
``psum`` over ``model`` and Ulysses' ``all_to_all`` over ``seq`` both
group within a fixed pipe rank. Ring attention's ``ppermute`` is not:
its rendezvous spans the full device set (pairs semantics), so pipe
ranks on the skip-branch deadlock the ranks executing it (observed as
an XLA CPU rendezvous abort; the same hazard exists for any
non-uniform collective under SPMD). Ring remains the right tool in the
engine's UNgated train step (`parallel/sequence.py:ring_attention`).

``n_head`` must divide by the seq degree (the Ulysses head split). At
seq degree 1 every piece degenerates to the dense computation, so one
module definition serves both the sharded run and its oracle.
"""

import jax
import jax.numpy as jnp
from jax import lax
import flax.linen as nn

from deepspeed_tpu.parallel.collectives import axis_is_manual
from deepspeed_tpu.parallel.pipe_tp import layer_norm
from deepspeed_tpu.parallel.sequence import ulysses_attention_local


SEQ_AXIS = "seq"
# The pipeline's weighted-loss reductions psum the LITERAL ``seq`` mesh
# axis (runtime/pipe/pipeline.py) — the SP layers are fixed to it; a
# configurable axis name would silently break those reductions.


def _seq_info():
    """(n, idx) for the seq axis in manual mode, else (1, 0). ``n`` is a
    static int (axis sizes are mesh metadata)."""
    if axis_is_manual(SEQ_AXIS):
        return lax.axis_size(SEQ_AXIS), lax.axis_index(SEQ_AXIS)
    return 1, 0


class SPEmbedLayer:
    """Prologue: token + position embedding, sliced to this seq rank's
    chunk. Param leaves: ``wte`` [V, M], ``wpe`` [max_pos, M]
    (replicated; their grads are per-shard partials the pipeline psums
    over ``seq``)."""

    def __init__(self, vocab, d_model, max_pos, ids_key="input_ids"):
        self.vocab = vocab
        self.d_model = d_model
        self.max_pos = max_pos
        self.ids_key = ids_key

    def init(self, rng, micro):
        k1, k2 = jax.random.split(rng)
        return {
            "wte": jax.random.normal(k1, (self.vocab, self.d_model),
                                     jnp.float32) * 0.02,
            "wpe": jax.random.normal(k2, (self.max_pos, self.d_model),
                                     jnp.float32) * 0.01,
        }

    def apply(self, p, micro, rng=None):
        ids = micro[self.ids_key]                       # [B, T] full
        B, T = ids.shape
        x = p["wte"][ids] + p["wpe"][jnp.arange(T)]
        n, idx = _seq_info()
        assert T % n == 0, (
            f"seq len {T} must divide the seq-parallel degree {n}")
        Tloc = T // n
        return lax.dynamic_slice_in_dim(x, idx * Tloc, Tloc, axis=1)


class SPBlockLayer:
    """Pre-LN causal transformer block on seq-LOCAL activations
    [B, T/n, M]; attention is Ulysses over the ``seq`` axis (exactly full
    causal attention over the global sequence — see the module docstring
    for why not ring inside the 1F1B). All weights replicated.

    ``dropout``: attention-prob dropout runs inside the Ulysses inner
    kernel (per-head-group folded seeds — decorrelated, seq-degree
    VARIANT noise) and hidden dropout hashes GLOBAL token coordinates —
    invariant to the seq split, so a hidden-dropout-only block still
    matches its seq=1 oracle bitwise."""

    def __init__(self, d_model, n_head, ffn_mult=4, dropout=0.0,
                 attn_dropout=None):
        assert d_model % n_head == 0
        self.d_model = d_model
        self.n_head = n_head
        self.ffn = ffn_mult * d_model
        self.dropout = dropout
        self.attn_dropout = dropout if attn_dropout is None else attn_dropout

    def init(self, rng, x):
        M = self.d_model
        ks = jax.random.split(rng, 4)
        init = nn.initializers.normal(0.02)
        return {
            "ln1_scale": jnp.ones((M,), jnp.float32),
            "ln1_bias": jnp.zeros((M,), jnp.float32),
            "ln2_scale": jnp.ones((M,), jnp.float32),
            "ln2_bias": jnp.zeros((M,), jnp.float32),
            "qkv": init(ks[0], (M, 3 * M), jnp.float32),
            "qkv_b": jnp.zeros((3 * M,), jnp.float32),
            "proj": init(ks[1], (M, M), jnp.float32),
            "proj_b": jnp.zeros((M,), jnp.float32),
            "fc": init(ks[2], (M, self.ffn), jnp.float32),
            "fc_b": jnp.zeros((self.ffn,), jnp.float32),
            "fc_out": init(ks[3], (self.ffn, M), jnp.float32),
            "fc_out_b": jnp.zeros((M,), jnp.float32),
        }

    def _attention(self, q, k, v, rate, seed):
        if axis_is_manual(SEQ_AXIS):
            return ulysses_attention_local(q, k, v, SEQ_AXIS, causal=True,
                                           dropout_rate=rate,
                                           dropout_seed=seed)
        # oracle / build-time path: plain full-sequence causal attention
        from deepspeed_tpu.ops.pallas.flash_attention import dense_attention
        return dense_attention(q, k, v, causal=True,
                               dropout_rate=rate, dropout_seed=seed)

    def _hidden_drop(self, t, seed, sub):
        """Hidden dropout hashed at GLOBAL (token, feature) coordinates —
        the mask a given token draws is independent of which seq shard
        holds it, keeping seq-degree invariance under dropout. The seed
        is re-mixed per sublayer so the hidden coordinate space cannot
        collide with the attention masks' (same hash, same step seed)."""
        from deepspeed_tpu.ops.pallas.flash_attention import (
            dropout_multiplier, fold_in_seed)
        B, Tloc, M = t.shape
        n, idx = _seq_info()
        pos = idx * Tloc + jnp.arange(Tloc)
        return t * dropout_multiplier(
            fold_in_seed(seed, 1000 + sub),
            jnp.arange(B)[:, None, None], pos[None, :, None],
            jnp.arange(M)[None, None, :], self.dropout).astype(t.dtype)

    def apply(self, params, x, rng=None):
        B, Tloc, M = x.shape
        H = self.n_head
        D = M // H
        dtype = x.dtype
        attn_rate, seed = 0.0, None
        hidden_drop = lambda t, sub: t
        if rng is not None and (self.dropout > 0.0 or
                                self.attn_dropout > 0.0):
            from deepspeed_tpu.ops.pallas.flash_attention import (
                dropout_seed_from_rng)
            # The pipeline's mb_rng folds (microbatch, stage, section)
            # only — fold the data rank here so batch shards draw
            # independent noise (same contract as pipe_tp._drop_ctx;
            # identical on both sides of the seq-invariance test, so the
            # invariance is untouched).
            if axis_is_manual("data"):
                rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            seed = dropout_seed_from_rng(rng)
            attn_rate = self.attn_dropout
            if self.dropout > 0.0:
                hidden_drop = lambda t, sub: self._hidden_drop(t, seed, sub)

        h = layer_norm(x, params["ln1_scale"],
                       params["ln1_bias"]).astype(dtype)
        qkv = h @ params["qkv"].astype(dtype) + params["qkv_b"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        y = self._attention(q.reshape(B, Tloc, H, D),
                            k.reshape(B, Tloc, H, D),
                            v.reshape(B, Tloc, H, D),
                            attn_rate, seed).reshape(B, Tloc, M)
        att = y @ params["proj"].astype(dtype) + \
            params["proj_b"].astype(dtype)
        x = x + hidden_drop(att, 1)

        h2 = layer_norm(x, params["ln2_scale"],
                        params["ln2_bias"]).astype(dtype)
        ff = jax.nn.gelu(h2 @ params["fc"].astype(dtype) +
                         params["fc_b"].astype(dtype))
        out = ff @ params["fc_out"].astype(dtype) + \
            params["fc_out_b"].astype(dtype)
        return x + hidden_drop(out, 2)


class SPHeadLayer:
    """Epilogue: [B, T/n, M] → seq-local logits [B, T/n, V]."""

    def __init__(self, d_model, vocab):
        self.d_model = d_model
        self.vocab = vocab

    def init(self, rng, x):
        return {"w": jax.random.normal(rng, (self.d_model, self.vocab),
                                       jnp.float32) * 0.02}

    def apply(self, p, x, rng=None):
        return x @ p["w"]


def make_sp_token_loss(ids_key="input_ids"):
    """Weighted next-token CE over this rank's token chunk:
    ``(loss_sum, count)`` — the form the pipeline psums over ``seq`` for
    the exact global mean. Labels come from the FULL microbatch ids, so
    chunk boundaries shift correctly (the last token of chunk r is
    supervised by the first id of chunk r+1); only the global last token
    is ignored."""

    def loss(logits, micro):
        ids = micro[ids_key]                            # [B, T] full
        B, T = ids.shape
        n, idx = _seq_info()
        Tloc = T // n
        start = idx * Tloc
        labels_full = jnp.concatenate(
            [ids[:, 1:], jnp.full((B, 1), -100, ids.dtype)], axis=1)
        labels = lax.dynamic_slice_in_dim(labels_full, start, Tloc, axis=1)
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok = -jnp.take_along_axis(lp, safe[..., None], -1).squeeze(-1)
        tok = jnp.where(valid, tok, 0.0)
        return tok.sum(), valid.sum().astype(jnp.float32)

    return loss


def sp_pipeline_module(vocab, d_model, n_head, seq_len, n_blocks=2,
                       num_stages=None, ids_key="input_ids",
                       dropout=0.0, attn_dropout=None):
    """PipelineModule wiring the SP layers (pp x sp x dp composition)."""
    import numpy as np
    from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule

    return PipelineModule(
        layers=[LayerSpec(SPEmbedLayer, vocab, d_model, seq_len, ids_key)] +
               [LayerSpec(SPBlockLayer, d_model, n_head,
                          dropout=dropout, attn_dropout=attn_dropout)
                for _ in range(n_blocks)] +
               [LayerSpec(SPHeadLayer, d_model, vocab)],
        num_stages=num_stages, loss_fn=make_sp_token_loss(ids_key),
        example_input={ids_key: np.zeros((2, seq_len), np.int32)})
