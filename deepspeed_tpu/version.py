"""Version info (reference: `deepspeed/git_version_info.py`)."""

version = "0.1.0"
git_hash = None
git_branch = None

try:
    import subprocess
    _out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                          capture_output=True, text=True, timeout=2)
    if _out.returncode == 0:
        git_hash = _out.stdout.strip()
    _out = subprocess.run(["git", "rev-parse", "--abbrev-ref", "HEAD"],
                          capture_output=True, text=True, timeout=2)
    if _out.returncode == 0:
        git_branch = _out.stdout.strip()
except Exception:
    pass
