"""Version info (reference: `deepspeed/git_version_info.py:1-20` — try the
build-time-stamped module first, fall back to live git in a checkout)."""

version = "0.3.0"
git_hash = None
git_branch = None

try:
    # Written by setup.py's build_py at install time.
    from deepspeed_tpu.git_version_info_installed import (  # noqa: F401
        version, git_hash, git_branch)
except ImportError:
    try:
        import os
        import subprocess
        _cwd = os.path.dirname(os.path.abspath(__file__))
        _out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                              capture_output=True, text=True, timeout=2,
                              cwd=_cwd)
        if _out.returncode == 0:
            git_hash = _out.stdout.strip()
        _out = subprocess.run(["git", "rev-parse", "--abbrev-ref", "HEAD"],
                              capture_output=True, text=True, timeout=2,
                              cwd=_cwd)
        if _out.returncode == 0:
            git_branch = _out.stdout.strip()
    except Exception:
        pass
