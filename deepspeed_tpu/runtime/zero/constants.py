"""ZeRO config keys and defaults.

Mirrors the reference's ``deepspeed/runtime/zero/constants.py``. On TPU,
bucket-size knobs are accepted for config compatibility but sharding is
expressed through GSPMD annotations, so XLA chooses the collective schedule.
"""

ZERO_FORMAT = """
ZeRO optimization should be enabled as:
"zero_optimization": {
  "stage": [0|1|2|3],
  "allgather_partitions": [true|false],
  "allgather_bucket_size": 500000000,
  "reduce_scatter": [true|false],
  "contiguous_gradients": [true|false],
  "overlap_comm": [true|false],
  "reduce_bucket_size": 500000000,
  "load_from_fp32_weights": [true|false],
  "cpu_offload": [true|false],
  "gather_on_use": [true|false],
  "gather_chunks": 1,
  "prefetch": [true|false],
  "bidirectional": [true|false]
}
"""

ZERO_OPTIMIZATION = "zero_optimization"
ZERO_OPTIMIZATION_DISABLED = 0
ZERO_OPTIMIZATION_OPTIMIZER_STATES = 1
ZERO_OPTIMIZATION_GRADIENTS = 2
ZERO_OPTIMIZATION_WEIGHTS = 3
# The reference caps at stage 2 (`zero/constants.py:33`); the TPU framework
# also implements stage 3 (parameter sharding) since on TPU it is the same
# GSPMD annotation mechanism.
MAX_STAGE_ZERO_OPTIMIZATION = ZERO_OPTIMIZATION_WEIGHTS

ZERO_OPTIMIZATION_STAGE = "stage"
ZERO_OPTIMIZATION_STAGE_DEFAULT = ZERO_OPTIMIZATION_DISABLED

ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS = "allgather_partitions"
ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT = True

ZERO_OPTIMIZATION_REDUCE_SCATTER = "reduce_scatter"
ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT = True

ZERO_OPTIMIZATION_OVERLAP_COMM = "overlap_comm"
ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT = False

ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS = "contiguous_gradients"
ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT = False

ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE = "reduce_bucket_size"
ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT = 500000000

ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT = 500000000
ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED = "allgather_size"

ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS = "load_from_fp32_weights"
ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT = True

ZERO_OPTIMIZATION_CPU_OFFLOAD = "cpu_offload"
ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT = False

# Move device->host gradients at 16 bit (the reference ALWAYS moves fp16
# grads to pinned host memory, stage2.py:793; fp32 here is the stricter
# default, 16-bit halves the D2H wire for big offload models).
ZERO_OPTIMIZATION_OFFLOAD_16BIT_GRADS = "offload_16bit_grads"
ZERO_OPTIMIZATION_OFFLOAD_16BIT_GRADS_DEFAULT = False

ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT = "elastic_checkpoint"
ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT = True

# Chunk size (MB of fp32 elements) of the offload host-phase pipeline:
# D2H of chunk k+1 overlaps the C++ Adam + bf16 convert (+ the chunked
# param H2D upload) of chunk k. Smaller chunks overlap at finer grain
# but pay more per-call overhead; the reference's analogous knob buckets
# its async grad copies (stage2.py allreduce/allgather bucket sizes).
ZERO_OPTIMIZATION_OFFLOAD_CHUNK_MB = "offload_chunk_mb"
ZERO_OPTIMIZATION_OFFLOAD_CHUNK_MB_DEFAULT = 64

# --- stage-3 gather-on-use schedule ---------------------------------------
# When True (and stage >= 3 with a 16-bit compute dtype), parameters are
# all-gathered explicitly at point of use through the ring primitives in
# `parallel/collectives.py` instead of leaving gather placement to GSPMD.
# False falls back to the legacy spec-sharded caster
# (`zero/sharding.py:make_param_caster`) — kept as the A/B baseline.
ZERO_OPTIMIZATION_GATHER_ON_USE = "gather_on_use"
ZERO_OPTIMIZATION_GATHER_ON_USE_DEFAULT = True

# Ring chunking of each per-leaf gather: 1 = a single tiled all-gather
# (bit-identical to the spec-sharded baseline); k > 1 splits every leaf
# into k stripes moved by dep-chained ppermute rings so stripe transfers
# interleave with the consuming matmuls.
ZERO_OPTIMIZATION_GATHER_CHUNKS = "gather_chunks"
ZERO_OPTIMIZATION_GATHER_CHUNKS_DEFAULT = 1

# Dep-chain the per-leaf gathers so leaf i+1's gather is issued behind
# leaf i's (the prefetch schedule). Required when gather_chunks > 1: the
# chain is also the rendezvous-safety invariant for concurrent rings.
ZERO_OPTIMIZATION_PREFETCH = "prefetch"
ZERO_OPTIMIZATION_PREFETCH_DEFAULT = True

# Alternate ring direction per chunk so both link directions carry
# stripes simultaneously (even stripes clockwise, odd counter-clockwise).
ZERO_OPTIMIZATION_BIDIRECTIONAL = "bidirectional"
ZERO_OPTIMIZATION_BIDIRECTIONAL_DEFAULT = False

ZERO_OPTIMIZATION_DEFAULT = {
    ZERO_OPTIMIZATION_STAGE: ZERO_OPTIMIZATION_STAGE_DEFAULT,
    ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS:
        ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT,
    ZERO_OPTIMIZATION_REDUCE_SCATTER: ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT,
    ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE:
        ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT,
    ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS:
        ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT,
    ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE:
        ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT,
    ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS:
        ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT,
    ZERO_OPTIMIZATION_CPU_OFFLOAD: ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT,
    ZERO_OPTIMIZATION_OFFLOAD_16BIT_GRADS:
        ZERO_OPTIMIZATION_OFFLOAD_16BIT_GRADS_DEFAULT,
    ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT:
        ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT,
    ZERO_OPTIMIZATION_OFFLOAD_CHUNK_MB:
        ZERO_OPTIMIZATION_OFFLOAD_CHUNK_MB_DEFAULT,
    ZERO_OPTIMIZATION_GATHER_ON_USE:
        ZERO_OPTIMIZATION_GATHER_ON_USE_DEFAULT,
    ZERO_OPTIMIZATION_GATHER_CHUNKS:
        ZERO_OPTIMIZATION_GATHER_CHUNKS_DEFAULT,
    ZERO_OPTIMIZATION_PREFETCH: ZERO_OPTIMIZATION_PREFETCH_DEFAULT,
    ZERO_OPTIMIZATION_BIDIRECTIONAL:
        ZERO_OPTIMIZATION_BIDIRECTIONAL_DEFAULT,
}
