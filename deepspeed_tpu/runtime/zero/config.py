"""ZeRO optimization config object.

Mirrors the reference's ``DeepSpeedZeroConfig`` (`runtime/zero/config.py:11`),
including acceptance of the legacy boolean form and the deprecated
``allgather_size`` key.
"""

from deepspeed_tpu.runtime.config_utils import get_scalar_param
from deepspeed_tpu.runtime.zero.constants import (
    ZERO_FORMAT,
    ZERO_OPTIMIZATION,
    ZERO_OPTIMIZATION_BIDIRECTIONAL,
    ZERO_OPTIMIZATION_BIDIRECTIONAL_DEFAULT,
    ZERO_OPTIMIZATION_GATHER_CHUNKS,
    ZERO_OPTIMIZATION_GATHER_CHUNKS_DEFAULT,
    ZERO_OPTIMIZATION_GATHER_ON_USE,
    ZERO_OPTIMIZATION_GATHER_ON_USE_DEFAULT,
    ZERO_OPTIMIZATION_PREFETCH,
    ZERO_OPTIMIZATION_PREFETCH_DEFAULT,
    ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
    ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT,
    ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED,
    ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
    ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT,
    ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
    ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT,
    ZERO_OPTIMIZATION_CPU_OFFLOAD,
    ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT,
    ZERO_OPTIMIZATION_OFFLOAD_16BIT_GRADS,
    ZERO_OPTIMIZATION_OFFLOAD_16BIT_GRADS_DEFAULT,
    ZERO_OPTIMIZATION_OFFLOAD_CHUNK_MB,
    ZERO_OPTIMIZATION_OFFLOAD_CHUNK_MB_DEFAULT,
    ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
    ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT,
    ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS,
    ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT,
    ZERO_OPTIMIZATION_OPTIMIZER_STATES,
    ZERO_OPTIMIZATION_OVERLAP_COMM,
    ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT,
    ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
    ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT,
    ZERO_OPTIMIZATION_REDUCE_SCATTER,
    ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT,
    ZERO_OPTIMIZATION_STAGE,
    ZERO_OPTIMIZATION_STAGE_DEFAULT,
)


class DeepSpeedZeroConfig:
    def __init__(self, param_dict):
        self.stage = None
        self.contiguous_gradients = None
        self.reduce_scatter = None
        self.reduce_bucket_size = None
        self.allgather_partitions = None
        self.allgather_bucket_size = None
        self.overlap_comm = None
        self.load_from_fp32_weights = None
        self.cpu_offload = None
        self.offload_16bit_grads = None
        self.offload_chunk_mb = None
        self.elastic_checkpoint = None
        self.gather_on_use = None
        self.gather_chunks = None
        self.prefetch = None
        self.bidirectional = None

        if ZERO_OPTIMIZATION in param_dict:
            zero_config_dict = param_dict[ZERO_OPTIMIZATION]
            if isinstance(zero_config_dict, bool):
                zero_config_dict = self.read_zero_config_deprecated(param_dict)
        else:
            zero_config_dict = {}
        self._initialize(zero_config_dict)

    def read_zero_config_deprecated(self, param_dict):
        # Legacy `"zero_optimization": true` boolean form → stage 1.
        zero_config_dict = {
            ZERO_OPTIMIZATION_STAGE:
                ZERO_OPTIMIZATION_OPTIMIZER_STATES
                if param_dict[ZERO_OPTIMIZATION] else 0
        }
        if ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED in param_dict:
            zero_config_dict[ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE] = \
                param_dict[ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEPRECATED]
        return zero_config_dict

    def _initialize(self, zero_config_dict):
        self.stage = get_scalar_param(zero_config_dict,
                                      ZERO_OPTIMIZATION_STAGE,
                                      ZERO_OPTIMIZATION_STAGE_DEFAULT)
        self.contiguous_gradients = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS,
            ZERO_OPTIMIZATION_CONTIGUOUS_GRADIENTS_DEFAULT)
        self.reduce_bucket_size = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE,
            ZERO_OPTIMIZATION_REDUCE_BUCKET_SIZE_DEFAULT)
        self.reduce_scatter = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_REDUCE_SCATTER,
            ZERO_OPTIMIZATION_REDUCE_SCATTER_DEFAULT)
        self.overlap_comm = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_OVERLAP_COMM,
            ZERO_OPTIMIZATION_OVERLAP_COMM_DEFAULT)
        self.allgather_partitions = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS,
            ZERO_OPTIMIZATION_ALLGATHER_PARTITIONS_DEFAULT)
        self.allgather_bucket_size = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE,
            ZERO_OPTIMIZATION_ALLGATHER_BUCKET_SIZE_DEFAULT)
        self.load_from_fp32_weights = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS,
            ZERO_OPTIMIZATION_LOAD_FROM_FP32_WEIGHTS_DEFAULT)
        self.cpu_offload = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_CPU_OFFLOAD,
            ZERO_OPTIMIZATION_CPU_OFFLOAD_DEFAULT)
        self.offload_16bit_grads = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_OFFLOAD_16BIT_GRADS,
            ZERO_OPTIMIZATION_OFFLOAD_16BIT_GRADS_DEFAULT)
        self.offload_chunk_mb = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_OFFLOAD_CHUNK_MB,
            ZERO_OPTIMIZATION_OFFLOAD_CHUNK_MB_DEFAULT)
        self.elastic_checkpoint = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT,
            ZERO_OPTIMIZATION_ELASTIC_CHECKPOINT_DEFAULT)
        self.gather_on_use = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_GATHER_ON_USE,
            ZERO_OPTIMIZATION_GATHER_ON_USE_DEFAULT)
        self.gather_chunks = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_GATHER_CHUNKS,
            ZERO_OPTIMIZATION_GATHER_CHUNKS_DEFAULT)
        self.prefetch = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_PREFETCH,
            ZERO_OPTIMIZATION_PREFETCH_DEFAULT)
        self.bidirectional = get_scalar_param(
            zero_config_dict,
            ZERO_OPTIMIZATION_BIDIRECTIONAL,
            ZERO_OPTIMIZATION_BIDIRECTIONAL_DEFAULT)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return str(self.__dict__)
