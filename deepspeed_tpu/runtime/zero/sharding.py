"""ZeRO as GSPMD sharding declarations.

The reference implements ZeRO-1/2 with hand-coded flatten/partition/
reduce-scatter/all-gather machinery driven by per-param backward hooks
(`runtime/zero/stage1.py:104`, `stage2.py:92`). On TPU the same capabilities
are sharding *declarations* over the ``data`` mesh axis (the ZeRO-DP ≡
weight-update-sharding equivalence; see PAPERS.md "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training"):

- stage 1 — optimizer state (fp32 masters + moments) sharded over ``data``;
  XLA emits a reduce-scatter of grads into the shard and an all-gather of
  updated params, exactly the collectives stage1.py hand-codes at :533,:692.
- stage 2 — gradients additionally constrained to the sharded layout inside
  the step (``with_sharding_constraint``), so the full replicated gradient
  never materializes — the IPG-bucket capability of stage2.py:613.
- stage 3 — parameters themselves sharded over ``data`` (beyond the
  reference, which caps at stage 2); XLA all-gathers weights just-in-time
  per layer.

Overlap of grad communication with backward compute (stage2's
``overlap_comm``) falls out of XLA's latency-hiding scheduler rather than a
dedicated reduction stream.
"""

from jax.sharding import NamedSharding, PartitionSpec

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.compat import shard_map


def zero_partition_spec(shape, base_spec, mesh, axis="data"):
    """Augment ``base_spec`` by sharding one more dimension over ``axis``.

    Picks the largest dimension that (a) is not already sharded by
    ``base_spec`` and (b) divides evenly by the axis size; returns the base
    spec unchanged when nothing qualifies (small params stay replicated —
    the analog of the reference's padding of sub-partitions, without the
    padding).

    ``mesh`` may also be a plain int axis size: the offline resharder
    (`runtime/elastic/reshard.py`) re-solves specs for a world size that
    has no live mesh. The decision depends only on the axis size, so the
    int form is exactly equivalent.
    """
    axis_size = mesh if isinstance(mesh, int) else mesh.shape[axis]
    if axis_size == 1 or not shape:
        return base_spec
    spec = tuple(base_spec) if base_spec else ()
    spec = spec + (None,) * (len(shape) - len(spec))
    best_dim, best_size = None, 0
    for dim, size in enumerate(shape):
        if spec[dim] is not None:
            continue
        if size % axis_size == 0 and size > best_size:
            best_dim, best_size = dim, size
    if best_dim is None:
        return _canonical(spec)
    new_spec = list(spec)
    new_spec[best_dim] = axis
    return _canonical(new_spec)


def _canonical(spec):
    # Strip trailing Nones: jit canonicalizes output shardings the same
    # way, and an equivalent-but-unequal spec (('data', None) vs
    # ('data',)) on the placed optimizer state forces a full retrace +
    # recompile on the second step.
    spec = list(spec)
    while spec and spec[-1] is None:
        spec.pop()
    return PartitionSpec(*spec)


def build_zero_shardings(params, base_specs, mesh, stage, axis="data"):
    """Per-leaf NamedShardings for params / optimizer state / gradients.

    Returns a dict with ``param``, ``opt``, ``grad`` pytrees of NamedSharding.
    """
    def base_of(path_leaf_spec):
        return path_leaf_spec if path_leaf_spec is not None else PartitionSpec()

    def param_spec(leaf, spec):
        if stage >= 3:
            return zero_partition_spec(leaf.shape, base_of(spec), mesh, axis)
        return base_of(spec)

    def opt_spec(leaf, spec):
        if stage >= 1:
            return zero_partition_spec(leaf.shape, base_of(spec), mesh, axis)
        return base_of(spec)

    def grad_spec(leaf, spec):
        if stage >= 2:
            return zero_partition_spec(leaf.shape, base_of(spec), mesh, axis)
        return base_of(spec)

    def shard(fn):
        # base_specs has PartitionSpec leaves at params' leaf positions;
        # flatten_up_to keeps each spec whole (PartitionSpec is a tuple
        # subclass, so a plain tree_map over it would descend into it).
        treedef = jax.tree_util.tree_structure(params)
        leaves = treedef.flatten_up_to(base_specs)
        spec_tree = jax.tree_util.tree_unflatten(treedef, leaves)
        return jax.tree_util.tree_map(
            lambda leaf, spec: NamedSharding(mesh, fn(leaf, spec)),
            params, spec_tree)

    return {
        "param": shard(param_spec),
        "opt": shard(opt_spec),
        "grad": shard(grad_spec),
    }


def constrain_tree(tree, sharding_tree):
    """Apply with_sharding_constraint leaf-wise (inside jit)."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.lax.with_sharding_constraint(x, s),
        tree, sharding_tree)


def _gather_cast_leaf(mesh, spec, dtype, axis):
    """Cast-then-gather for one stage-3 param leaf: the fp32 shard is cast
    to the compute dtype LOCALLY and the all-gather moves the 16-bit
    copy, halving per-use param traffic vs XLA's default gather-then-cast
    (a plain ``with_sharding_constraint`` cannot express this: sharding
    propagation walks the replicated constraint back through the convert
    and gathers fp32). Bitwise-exact — cast is elementwise, so
    cast∘gather == gather∘cast. The reference's analog is stage 1's fp16
    param all-gather (`stage1.py:692`: updated fp16 shards, not fp32
    masters, ride NCCL).

    Backward is pinned by custom_vjp to the EXACT path: the compute-dtype
    cotangent is cast to fp32 first, then reduced/resharded in fp32 —
    the 16-bit wire never touches gradient accumulation numerics.
    """
    dim = list(spec).index(axis)
    out_spec = PartitionSpec(*[None if s == axis else s for s in spec])

    def inner(xs):
        return jax.lax.all_gather(xs.astype(dtype), axis, axis=dim,
                                  tiled=True)

    fwd_impl = shard_map(inner, mesh=mesh, in_specs=(spec,),
                             out_specs=out_spec, check_vma=False)

    @jax.custom_vjp
    def gather16(x):
        return fwd_impl(x)

    def fwd(x):
        return fwd_impl(x), None

    def bwd(_, ct):
        ctf = ct.astype(jnp.float32)
        return (jax.lax.with_sharding_constraint(
            ctf, NamedSharding(mesh, spec)),)

    gather16.defvjp(fwd, bwd)
    return gather16


def make_param_caster(params, param_shardings, mesh, dtype, axis="data"):
    """``cast(params) -> compute-dtype params`` for ZeRO-3 train steps.

    Leaves sharded over ``axis`` (per ``param_shardings``) take the
    cast-then-gather path; everything else is a plain astype. Returns
    None when nothing is sharded over ``axis`` (stages < 3, fp32
    compute, or a 1-device data axis) so callers can keep the default
    cast.
    """
    if mesh.shape.get(axis, 1) == 1:
        return None

    found = {"gather": False}

    def leaf_fn(leaf, sharding):
        spec = tuple(sharding.spec)
        # Only plain `axis` entries are handled; tuple sub-specs (e.g.
        # ("data", "model") on one dim) fall back to the default cast.
        if axis in spec:
            found["gather"] = True
            return _gather_cast_leaf(mesh, PartitionSpec(*spec), dtype, axis)
        return lambda x: x.astype(dtype)

    fns = jax.tree_util.tree_map(leaf_fn, params, param_shardings)
    if not found["gather"]:
        return None

    def cast(p):
        return jax.tree_util.tree_map(lambda f, x: f(x), fns, p)

    return cast
