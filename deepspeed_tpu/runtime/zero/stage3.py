"""Explicit ZeRO-3: gather-on-use parameter sharding with prefetch.

The GSPMD spec-sharded stage 3 (`zero/sharding.py:make_param_caster`)
leaves gather *placement* to XLA: nothing stops the scheduler from
hoisting every param all-gather to the top of the step (peak = all
gathered copies live at once) and nothing re-gathers in the backward —
XLA saves the gathered 16-bit copies as residuals, paying the gathered
footprint across the whole fwd+bwd interval. This module pins the
schedule instead (the DeepCompile argument, arXiv:2504.09983):

- one ``shard_map`` over ALL sharded leaves runs per-leaf cast-then-
  gathers through :func:`parallel.collectives.ring_all_gather`,
  dep-chained in leaf order — leaf *i+1*'s gather issues behind leaf
  *i*'s (the prefetch schedule), and with ``gather_chunks > 1`` each
  leaf moves as ppermute ring stripes that interleave with the
  consuming matmuls;
- every gathered leaf is tagged :func:`jax.ad_checkpoint.checkpoint_name`
  so the engine's remat policy (:func:`zero3_remat_policy`) drops the
  gathered copy at the fwd/bwd boundary and the backward *re-gathers*
  from the always-live fp32 shards — the gathered footprint is
  per-use, never saved;
- the ``custom_vjp`` backward casts the compute-dtype cotangents to
  fp32 and constrains them straight back to the sharded layout
  (GSPMD lowers that to the reduce-scatter; an explicit in-graph
  ``psum_scatter`` would double-count — at the jit level the cotangent
  is one logical array, and GSPMD would materialize it with its own
  all-reduce first) — the full fp32 param gradient never exists
  replicated;
- both emitters register in the PR 6 ``SiteRecord`` trace-time log
  (sites ``zero3_gather`` / ``zero3_reshard``) so the audit's
  deadlock/resharding rules can attribute the traffic.

``gather_chunks=1`` lowers each leaf to the same tiled ``all_gather``
as the legacy caster — bit-identical numerics, schedule still pinned.
"""

import dataclasses

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.collectives import (
    log_collective_site,
    ring_all_gather,
)
from deepspeed_tpu.utils.compat import shard_map

# The checkpoint_name tag on every gathered leaf; the remat policy
# excludes exactly this name from the saved residuals.
GATHERED_NAME = "zero3_gathered"


def zero3_remat_policy():
    """Remat policy for the stage-3 step: save every residual EXCEPT the
    gathered 16-bit params. Forward activations stay saved (no compute
    is re-done beyond the gathers); the backward re-gathers each leaf
    from its fp32 shard right where the transposed matmul needs it."""
    return jax.checkpoint_policies.save_anything_except_these_names(
        GATHERED_NAME)


@dataclasses.dataclass(frozen=True)
class Zero3Plan:
    """Static facts about the gather-on-use schedule, produced next to
    the caster and consumed by the audit (`analysis/audit.py` feeds them
    into ``StepContext`` so `analysis/rules.py` can pin per-leaf gather
    sizes/counts against the HLO)."""
    gather_leaves: int           # sharded leaves gathered per use
    gather_chunks: int           # ring stripes per leaf (1 = all-gather)
    prefetch: bool               # dep-chained leaf order
    bidirectional: bool          # alternate ring direction per stripe
    max_gather_bytes: int        # largest single gathered leaf (compute dtype)
    total_gather_bytes: int      # all gathered leaves (compute dtype)
    wire_dtype: str = None       # codec name when gathers move quantized

    def to_dict(self):
        return dataclasses.asdict(self)


def make_gather_on_use_caster(params, param_shardings, mesh, dtype,
                              axis="data", chunks=1, prefetch=True,
                              bidirectional=False, wire_dtype=None,
                              wire_chunk=512):
    """``(cast, Zero3Plan)`` for the explicit stage-3 step, or
    ``(None, None)`` when nothing is sharded over ``axis`` (callers keep
    the default cast, exactly like ``make_param_caster``).

    ``cast(params)`` returns the compute-dtype param tree: leaves
    sharded over ``axis`` ride the single-shard_map gather described in
    the module docstring; everything else is a plain ``astype``.

    ``wire_dtype`` (a codec name from ``runtime/comm/codecs.py``) moves
    each gather's payload quantized — per-chunk scales packed into the
    same collective operand, the local shard placed exactly; the
    backward reduce-scatter stays full precision (grad accumulation
    numerics are never quantized here).
    """
    assert chunks <= 1 or prefetch, (
        "zero3: gather_chunks > 1 requires the prefetch dep-chain "
        "(rendezvous-safety invariant; enforced by config validation)")
    if mesh.shape.get(axis, 1) == 1:
        return None, None

    leaves, treedef = jax.tree_util.tree_flatten(params)
    shard_leaves = treedef.flatten_up_to(param_shardings)
    gathered_idx, in_specs, out_specs, dims = [], [], [], []
    for i, (leaf, sharding) in enumerate(zip(leaves, shard_leaves)):
        spec = tuple(sharding.spec)
        # Only plain `axis` entries are gathered; tuple sub-specs (e.g.
        # ("data", "model") on one dim) fall back to the default cast.
        if axis in spec:
            gathered_idx.append(i)
            in_specs.append(PartitionSpec(*spec))
            out_specs.append(PartitionSpec(
                *[None if s == axis else s for s in spec]))
            dims.append(spec.index(axis))
    if not gathered_idx:
        return None, None

    itemsize = jnp.dtype(dtype).itemsize
    sizes = [int(leaves[i].size) * itemsize for i in gathered_idx]
    plan = Zero3Plan(
        gather_leaves=len(gathered_idx), gather_chunks=int(chunks),
        prefetch=bool(prefetch), bidirectional=bool(bidirectional),
        max_gather_bytes=max(sizes), total_gather_bytes=sum(sizes),
        wire_dtype=(str(wire_dtype) if wire_dtype else None))

    def inner(shards):
        # Per-leaf cast-then-gather, dep-chained in leaf order: the chain
        # is the prefetch schedule (leaf i+1's transfer issues behind
        # leaf i's, ahead of leaf i+1's consumer) and — for the ring
        # form — the invariant that keeps concurrent ppermutes off the
        # in-process rendezvous.
        outs, dep = [], None
        for buf, dim in zip(shards, dims):
            full, d = ring_all_gather(
                buf.astype(dtype), axis, axis=dim, chunks=chunks,
                bidirectional=bidirectional,
                dep=dep if prefetch else None, site="zero3_gather",
                wire_dtype=wire_dtype, wire_chunk=wire_chunk)
            if prefetch:
                dep = d
            outs.append(full)
        return tuple(outs)

    gather_impl = shard_map(inner, mesh=mesh, in_specs=(tuple(in_specs),),
                            out_specs=tuple(out_specs), check_vma=False)

    @jax.custom_vjp
    def gather16(shards):
        return gather_impl(shards)

    def fwd(shards):
        return gather_impl(shards), None

    def bwd(_, cts):
        # Reduce-scatter straight into the sharded fp32 layout: cast the
        # 16-bit cotangent up FIRST (wire precision never touches grad
        # accumulation numerics), then let GSPMD lower the replicated->
        # sharded constraint to its reduce-scatter. The full fp32 param
        # gradient never materializes replicated.
        log_collective_site("zero3_reshard", axis, "reduce_scatter",
                            chunks=len(in_specs))
        return (tuple(
            jax.lax.with_sharding_constraint(
                ct.astype(jnp.float32), NamedSharding(mesh, spec))
            for ct, spec in zip(cts, in_specs)),)

    gather16.defvjp(fwd, bwd)

    n_axis = int(mesh.shape[axis])

    def declare_sites():
        # SiteRecord registration for the whole schedule, exposed as a
        # hook the engine's accumulator calls OUTSIDE the remat
        # boundary: jax.checkpoint memoizes its body trace (and jax
        # caches the shard_map/custom_vjp traces on the fn objects), so
        # trace-time logging inside any of them goes quiet on an
        # audit's retrace of the long-lived step.
        if chunks > 1:
            log_collective_site("zero3_gather", axis, "ppermute",
                                chunks=int(chunks), hops=n_axis - 1)
        else:
            log_collective_site("zero3_gather", axis, "all_gather")
        log_collective_site("zero3_reshard", axis, "reduce_scatter",
                            chunks=len(in_specs))

    def cast(p):
        p_leaves = treedef.flatten_up_to(p)
        full = gather16(tuple(p_leaves[i] for i in gathered_idx))
        out = [x.astype(dtype) for x in p_leaves]
        for j, i in enumerate(gathered_idx):
            # The name tag is what lets zero3_remat_policy drop the
            # gathered copy at the fwd/bwd boundary (backward re-gathers).
            out[i] = checkpoint_name(full[j], GATHERED_NAME)
        return jax.tree_util.tree_unflatten(treedef, out)

    cast.declare_sites = declare_sites
    return cast, plan
