"""Pipeline engine (under construction).

Analog of the reference's ``PipelineEngine`` (`runtime/pipe/engine.py:152`).
The TPU execution model: per-stage compiled programs over submeshes of the
``pipe`` axis with instruction-list scheduling (see `runtime/pipe/schedule.py`)
— lands in the pipeline milestone; until then construction fails loudly.
"""

from deepspeed_tpu.runtime.engine import DeepSpeedEngine


class PipelineEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "PipelineEngine is not wired up yet in this build; "
            "use DeepSpeedEngine (dp/tp/ZeRO) for now.")
