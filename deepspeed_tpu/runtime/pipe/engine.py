"""PipelineEngine: train a PipelineModule over the ``pipe`` mesh axis.

Analog of the reference's ``PipelineEngine`` (`runtime/pipe/engine.py:152` —
``train_batch``:229, ``eval_batch``:305, ``_exec_schedule``:1144). The
reference interprets instruction lists per rank; here the whole train
batch compiles into one XLA program (see `runtime/pipe/pipeline.py`).
Training executes the hand-scheduled **1F1B** interleave
(``make_pipeline_value_and_grad_fn``: forward and backward ticks in one
``lax.scan``, O(num_stages) activation memory independent of the
microbatch count — the buffer bound of reference `schedule.py:243-247`,
proven by ``test_pipe.py::test_1f1b_memory_independent_of_microbatches``);
eval runs the forward-only GPipe wavefront. The instruction schedules in
`runtime/pipe/schedule.py` remain the introspectable specification of the
executed order.

Everything else — optimizer, ZeRO shardings of the per-stage params, mixed
precision, dynamic loss scale, checkpointing — is inherited from
:class:`DeepSpeedEngine`; the pipeline is "just" a loss function whose
internals shard compute over ``pipe``.
"""

import dataclasses

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.mesh import build_mesh
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.pipe.pipeline import (
    build_pipeline_parts,
    make_pipeline_loss_fn,
    make_pipeline_value_and_grad_fn,
)
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule, InferenceSchedule
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(DeepSpeedEngine):
    """Training engine for :class:`PipelineModule` models."""

    def __init__(self,
                 args=None,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu=None,
                 dist_init_required=None,
                 collate_fn=None,
                 config=None,
                 config_params=None,
                 mesh=None,
                 seed=0):
        assert isinstance(model, PipelineModule), (
            "PipelineEngine requires a PipelineModule")

        if config is None and config_params is not None:
            config = config_params
        if config is None and args is not None and \
                getattr(args, "deepspeed_config", None):
            config = args.deepspeed_config
        assert config is not None, "config (dict or json path) required"

        # Join the multi-host cluster BEFORE the first backend-touching
        # call (build_mesh below) — same contract as the base engine.
        from deepspeed_tpu.parallel.mesh import initialize_distributed
        initialize_distributed()

        mesh_cfg = config.get("mesh") if isinstance(config, dict) else None
        mesh = mesh if mesh is not None else build_mesh(mesh_cfg)
        num_stages = mesh.shape["pipe"]
        if model.num_stages is not None and model.num_stages != num_stages:
            raise ValueError(
                f"PipelineModule(num_stages={model.num_stages}) does not "
                f"match the mesh pipe axis ({num_stages})")
        if num_stages < 2:
            log_dist("pipe axis is 1: pipeline degenerates to sequential "
                     "execution (DataParallelSchedule)", ranks=[0])

        # micro-batches per train batch = gradient accumulation steps
        # (reference pipe/engine.py:229: micro_batches == grad accum).
        probe = DeepSpeedConfig(config, world_size=mesh.shape["data"])
        if probe.pld_enabled:
            raise ValueError(
                "progressive_layer_drop is not supported with "
                "PipelineModule: the hand-scheduled 1F1B program takes no "
                "pld_theta (stage bodies are homogeneous scans)")
        self.micro_batches = probe.gradient_accumulation_steps
        self.num_stages = num_stages

        example = model.example_input
        assert example is not None, (
            "PipelineModule(example_input=...) is required for parameter "
            "shape inference (a microbatch-shaped pytree; row count free)")

        if model.partition_method not in ("uniform", "parameters"):
            log_dist(
                f"partition_method={model.partition_method!r}: the compiled "
                f"pipeline stacks the homogeneous body uniformly (for equal "
                f"layers this equals the parameter-balanced split); the "
                f"requested policy is recorded but not load-bearing",
                ranks=[0])

        self.pipeline_parts = build_pipeline_parts(
            model, num_stages, jax.random.PRNGKey(seed), example)
        if model_parameters is not None:
            # Pretrained weights: must match the built structure
            # (prologue/body/epilogue/tied with the stacked body layout).
            expected = jax.tree_util.tree_structure(self.pipeline_parts.params)
            got = jax.tree_util.tree_structure(model_parameters)
            assert got == expected, (
                f"model_parameters do not match the built pipeline param "
                f"structure:\n  expected {expected}\n  got      {got}")
            self.pipeline_parts.params = model_parameters
        # reference semantics: interval 0 disables rematerialization
        auto_axes = tuple(getattr(model, "auto_axes", ()) or ())
        if auto_axes:
            # The vag-level capability works and is parity-tested
            # (test_pipe_auto.py), but composing it with the engine's
            # compiled train step deadlocks XLA's in-process CPU
            # collective rendezvous when body params are PLACED sharded
            # over the auto axis (devices split 4/4 across the fwd/bwd
            # ppermute rendezvous; repro in the test file's docstring).
            # Real-TPU behavior is untested (different collective
            # runtime) — gate rather than abort the process.
            raise NotImplementedError(
                f"PipelineModule(auto_axes={auto_axes!r}) through the "
                "engine is experimental and currently disabled: the "
                "in-process CPU runtime deadlocks on the pipeline's "
                "ppermutes when params are placed sharded over an auto "
                "axis. Use make_pipeline_value_and_grad_fn(...) directly "
                "(works, see tests/unit/test_pipe_auto.py) or the "
                "manual-collective TP blocks (parallel/pipe_tp.py)")
        # tensor_parallel.overlap: the latency-hiding collective-matmul
        # plan for manual-mode TP/SP/MoE layers, threaded to the trace-
        # time overlap_scope inside the pipeline's shard_map.
        overlap = probe.tensor_parallel.overlap_plan()
        # fp8: route the TP blocks' local matmuls through current-scaling
        # qdq (per-site amax threading isn't available through the
        # hand-written 1F1B backward), and — when fp8.wire is on — carry
        # the ring exchanges quantized by composing the wire codec into
        # the overlap plan the TP blocks already consume.
        fp8_plan = probe.fp8.plan()
        if probe.fp8.wire_enabled and overlap is not None:
            overlap = dataclasses.replace(
                overlap, wire_dtype=probe.fp8.active_wire_dtype(),
                wire_chunk=int(probe.fp8.wire_chunk_size))
        loss_fn = make_pipeline_loss_fn(
            self.pipeline_parts, mesh, self.micro_batches,
            remat=model.activation_checkpoint_interval > 0,
            auto_axes=auto_axes, overlap=overlap, fp8=fp8_plan)
        # Training runs the hand-scheduled 1F1B (loss, grads) program —
        # O(num_stages) activation memory independent of micro_batches;
        # the GPipe loss above remains the eval/forward-only path.
        compute_dtype = jnp.bfloat16 if probe.bf16_enabled else (
            jnp.float16 if probe.fp16_enabled else None)
        loss_fn.direct_value_and_grad = make_pipeline_value_and_grad_fn(
            self.pipeline_parts, mesh, self.micro_batches,
            compute_dtype=compute_dtype, auto_axes=auto_axes,
            overlap=overlap, fp8=fp8_plan)
        # 1-bit Adam composition: same 1F1B program, but gradients come
        # back data-LOCAL (stacked data axis) for the compressed
        # collective to average (engine._make_pipeline_onebit_train_step).
        loss_fn.direct_value_and_grad_local = make_pipeline_value_and_grad_fn(
            self.pipeline_parts, mesh, self.micro_batches,
            compute_dtype=compute_dtype, data_local=True,
            auto_axes=auto_axes, overlap=overlap, fp8=fp8_plan)

        super().__init__(args=args,
                         model=model,
                         optimizer=optimizer,
                         lr_scheduler=lr_scheduler,
                         mpu=mpu,
                         dist_init_required=dist_init_required,
                         training_data=training_data,
                         collate_fn=collate_fn,
                         config=config,
                         config_params=None,
                         loss_fn=loss_fn,
                         params=self.pipeline_parts.params,
                         param_specs=self.pipeline_parts.param_specs,
                         mesh=mesh,
                         seed=seed)
        tied_keys = list(self.pipeline_parts.params["tied"])
        # The engine copied+placed the params; drop the stale init copy.
        self.pipeline_parts.params = None

        log_dist(
            f"PipelineEngine: stages={num_stages}, "
            f"micro_batches={self.micro_batches}, "
            f"layers_per_stage={self.pipeline_parts.layers_per_stage}, "
            f"tied={tied_keys}", ranks=[0])

    # The pipeline consumes the whole train batch in one program; the
    # engine-level accumulation scan collapses to a single iteration.
    def _engine_accum_steps(self):
        return 1

    def _forensics_extra(self):
        """Pipeline topology on run_start events and flight-dump meta —
        a postmortem of a hung 1F1B ring needs stages/micro-batches to
        read the stage-transfer confessions."""
        return {"num_stages": self.num_stages,
                "micro_batches": self.micro_batches}

    # --- reference-parity introspection -------------------------------
    def train_schedule(self, stage_id=0):
        """The 1F1B instruction stream the compiled program implements."""
        return TrainSchedule(micro_batches=self.micro_batches,
                             stages=self.num_stages,
                             stage_id=stage_id)

    def inference_schedule(self, stage_id=0):
        return InferenceSchedule(micro_batches=self.micro_batches,
                                 stages=self.num_stages,
                                 stage_id=stage_id)

    def is_gradient_accumulation_boundary(self):
        """The compiled train batch always ends on the boundary."""
        return True

    def forward(self, *args, **kwargs):
        raise RuntimeError(
            "PipelineEngine executes whole train batches: use "
            "train_batch(batch) / eval_batch(batch) (reference "
            "pipe/engine.py raises the same)")

    def backward(self, *args, **kwargs):
        raise RuntimeError(
            "PipelineEngine executes whole train batches: use "
            "train_batch(batch) (reference pipe/engine.py raises the same)")

    def step(self, *args, **kwargs):
        raise RuntimeError(
            "PipelineEngine executes whole train batches: use "
            "train_batch(batch) (reference pipe/engine.py raises the same)")
