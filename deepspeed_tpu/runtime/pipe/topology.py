"""N-D process/device topology for hybrid parallelism.

Analog of the reference's ``ProcessTopology`` / ``PipeDataParallelTopology``
/ ``PipeModelDataParallelTopology`` / ``PipelineParallelGrid``
(`runtime/pipe/topology.py:12,246,252`). On TPU the *execution* structure is
a named ``jax.sharding.Mesh``; this module is the pure rank-math layer that
(a) mirrors the reference API for parity and tests, and (b) converts a
topology into the mesh axis layout the engines consume.

Axes are named; ranks map to coordinates in row-major (last axis fastest)
order — the same convention ``Mesh`` uses for its device array.
"""

import itertools
from collections import namedtuple

from deepspeed_tpu.parallel.mesh import MESH_AXES


class ProcessTopology:
    """Cartesian product of named axes ↔ global ranks.

    ``axes`` orders dimensions outermost-first; ``dims`` gives their sizes.
    """

    def __init__(self, axes, dims):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(int(d) for d in dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping = {}
        for rank, coord in enumerate(itertools.product(
                *[range(d) for d in self.dims])):
            self.mapping[self.ProcessCoord(*coord)] = rank

    def get_rank(self, **coord_kwargs):
        """Global rank of the process at the given full coordinate."""
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}, "
                             f"got {sorted(coord_kwargs)}")
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"coord {key} not in topology"
        return self.mapping[key]

    def get_axis_names(self):
        return list(self.axes)

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"),
                      inner_sep="_", outer_sep="-"):
        """String like ``model_00`` used in checkpoint filenames (reference
        `topology.py:80`): all axes except the omitted ones."""
        omit = set(omit_axes)
        coord = self.get_coord(rank)
        parts = [f"{axis}{inner_sep}{getattr(coord, axis):02d}"
                 for axis in self.axes if axis not in omit]
        return outer_sep.join(parts)

    def get_dim(self, axis):
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_coord(self, rank):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_comm_lists(self, axis):
        """Groups of ranks that vary only along ``axis`` — the communicator
        building-block (reference `topology.py:107`)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for combo in itertools.product(
                *[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, combo))
            ranks = [self.get_rank(**{axis: i, **fixed})
                     for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs):
        """Ranks whose coordinates match all given axis=value filters."""
        def matches(coord):
            return all(getattr(coord, k) == v
                       for k, v in filter_kwargs.items())
        return sorted(r for c, r in self.mapping.items() if matches(c))

    def get_axis_list(self, axis, idx):
        """Ranks with coordinate ``idx`` along ``axis``."""
        return sorted(r for c, r in self.mapping.items()
                      if getattr(c, axis) == idx)

    def world_size(self):
        n = 1
        for d in self.dims:
            n *= d
        return n

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """pipe × data hybrid (reference `topology.py:236`): pipe outermost so a
    dp group's ranks are ICI neighbors."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """pipe × data × model 3-D hybrid (reference `topology.py:246`)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Rank bookkeeping over a topology: stage ids, per-axis group ranks,
    stage-to-stage neighbors (reference ``PipelineParallelGrid``,
    `topology.py:252`). ``rank`` defaults to 0 (single-controller JAX hosts
    drive all ranks; per-rank views exist for parity and multi-host)."""

    def __init__(self, topology=None, rank=0, world_size=None):
        if topology is None:
            assert world_size is not None, "topology or world_size required"
            topology = PipeDataParallelTopology(num_pp=1, num_dp=world_size)
        self._topo = topology
        self.global_rank = rank
        self.world_size = topology.world_size()

        self.data_parallel_size = max(topology.get_dim("data"), 1)
        self.pipe_parallel_size = max(topology.get_dim("pipe"), 1)
        self.model_parallel_size = max(topology.get_dim("model"), 1)
        assert self.world_size == (self.data_parallel_size *
                                   self.pipe_parallel_size *
                                   self.model_parallel_size)

        self.stage_id = self.get_stage_id()
        self.data_parallel_id = self.get_data_parallel_id()

        # group rank-lists per axis (the reference builds dist groups here;
        # on TPU these become mesh-axis index sets)
        self.dp_groups = topology.get_axis_comm_lists("data")
        self.pp_groups = topology.get_axis_comm_lists("pipe")
        self.mp_groups = (topology.get_axis_comm_lists("model")
                          if "model" in topology.get_axis_names() else [])

        # p2p: successor/predecessor stage for this rank's pipe group
        self.p2p_groups = self._build_p2p_groups()

    def get_stage_id(self):
        if "pipe" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(self.global_rank), "pipe")

    def get_data_parallel_id(self):
        if "data" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(self.global_rank), "data")

    def _build_p2p_groups(self):
        """Consecutive stage pairs within each pipe group (reference
        `topology.py:299`: the 2-rank groups p2p.py sends through)."""
        pairs = []
        for ranks in self.pp_groups:
            for i in range(len(ranks)):
                pairs.append([ranks[i], ranks[(i + 1) % len(ranks)]])
        return pairs

    # --- stage neighbors -------------------------------------------------
    def stage_to_global(self, stage_id, **kwargs):
        """Global rank of ``stage_id`` holding all other coords equal."""
        coord = self._topo.get_coord(self.global_rank)
        me = coord._asdict()
        me.update(kwargs)
        me["pipe"] = stage_id
        return self._topo.get_rank(**me)

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1

    # --- reference-parity accessors --------------------------------------
    def get_global_rank(self):
        return self.global_rank

    def get_pipe_parallel_rank(self):
        return self.stage_id

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_rank(self):
        return self.data_parallel_id

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_model_parallel_rank(self):
        if "model" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(self.global_rank), "model")

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    # --- mesh bridge ------------------------------------------------------
    def mesh_shape(self):
        """{axis: size} dict in canonical mesh-axis order, for
        ``parallel.mesh.build_mesh`` — the point where rank math becomes a
        real device mesh."""
        shape = {axis: 1 for axis in MESH_AXES}
        for axis in self._topo.get_axis_names():
            if axis in shape:
                shape[axis] = self._topo.get_dim(axis)
        return shape
