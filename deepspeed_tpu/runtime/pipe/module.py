"""Pipeline module: layer specs and stage partitioning.

Analog of the reference's ``PipelineModule``/``LayerSpec``/``TiedLayerSpec``
(`runtime/pipe/module.py:85,23,71`). A pipeline model is a sequence of layer
specs; stages are assigned by the same partitioning policies
(``uniform`` / ``parameters`` / ``type:regex``) using the shared
``partition_balanced`` math (`runtime/utils.py:361`).

TPU-native execution model: each layer spec builds a pure
``(params, x, rng) -> x`` callable; the pipeline engine runs stages over the
``pipe`` mesh axis with collective-permute transfers (see
`runtime/pipe/engine.py`), so a "stage" here is a contiguous slice of specs
rather than a process-local nn.Sequential.
"""

import re
from typing import Any, Callable, List, Optional

import jax

from deepspeed_tpu.runtime.utils import partition_balanced, partition_uniform
from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Deferred layer: builds lazily so only the owning stage materializes
    params (the reference's motivation at `pipe/module.py:23`).

    ``typename`` is a factory returning an object with:
      - ``init(rng, x_shape) -> params`` (or a flax Module with .init)
      - ``apply(params, x, rng=None) -> x``
    For flax modules, pass the module class and kwargs; adapters below
    normalize the interface.
    """

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not callable(typename):
            raise RuntimeError("LayerSpec requires a callable typename")

    def build(self, log=False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        name = getattr(self.typename, "__name__", str(self.typename))
        args = ", ".join(
            [repr(a) for a in self.module_args] +
            [f"{k}={v!r}" for k, v in self.module_kwargs.items()])
        return f"LayerSpec({name}, {args})"


class TiedLayerSpec(LayerSpec):
    """Layer whose parameters are shared with every other layer of the same
    ``key`` (reference `pipe/module.py:71`). The pipeline engine keeps one
    owner copy and reduces tied grads across the stages that use it."""

    def __init__(self, key, typename, *module_args,
                 forward_fn=None, tied_weight_attr="embedding",
                 **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


class PipelineModule:
    """A model defined as a list of LayerSpecs partitioned into stages.

    Args mirror the reference (`pipe/module.py:85`): ``layers``,
    ``num_stages``, ``loss_fn``, ``partition_method``,
    ``activation_checkpoint_interval``, ``seed_layers``.
    """

    def __init__(self,
                 layers: List[Any],
                 num_stages: Optional[int] = None,
                 topology=None,
                 loss_fn: Optional[Callable] = None,
                 seed_layers: bool = False,
                 base_seed: int = 1234,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 example_input: Any = None,
                 auto_axes=()):
        self.specs = [
            spec if isinstance(spec, LayerSpec) else LayerSpec(spec)
            if callable(spec) else spec
            for spec in layers
        ]
        self.num_stages = num_stages
        self.topology = topology
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        # Mesh axes the compiled pipeline leaves in GSPMD (auto) mode —
        # typically ("model",) so layers built from plain flax modules
        # with nn.with_partitioning metadata do Megatron TP inside the
        # 1F1B without hand-written collectives (round 5;
        # `parallel/pipe_auto.py`). pipe/data/seq must stay manual.
        self.auto_axes = tuple(auto_axes)
        # Microbatch-shaped pytree for parameter shape inference (JAX builds
        # params from shapes; torch modules carry their own — this is the
        # one addition to the reference signature).
        self.example_input = example_input
        self._partition = None

    def __len__(self):
        return len(self.specs)

    # -- partitioning (reference pipe/module.py:348 `_partition_layers`) ---
    def partition_layers(self, num_stages=None, weights=None):
        """Compute stage boundaries: list of len num_stages+1.

        ``parameters``: balance by per-layer parameter count (caller provides
        ``weights``; falls back to uniform when absent).
        ``uniform``: balance by layer count.
        ``type:regex``: balance by count of layers whose class name matches.
        """
        num_stages = num_stages or self.num_stages
        assert num_stages, "num_stages required"
        method = (self.partition_method or "parameters").lower()

        if method == "uniform":
            parts = partition_uniform(len(self.specs), num_stages)
        elif method == "parameters":
            if weights is None:
                parts = partition_uniform(len(self.specs), num_stages)
            else:
                parts = partition_balanced(weights, num_stages)
        elif method.startswith("type:"):
            layertype = method.split(":", 1)[1]
            binary_weights = [0] * len(self.specs)
            for idx, spec in enumerate(self.specs):
                name = getattr(spec.typename, "__name__", "")
                if re.match(layertype, name, re.IGNORECASE):
                    binary_weights[idx] = 1
            parts = partition_balanced(binary_weights, num_stages)
        elif method == "profile":
            raise NotImplementedError("profile-based partitioning TBD")
        else:
            raise NotImplementedError(f"Partitioning method {method}")

        self._partition = parts
        return parts

    def stage_layers(self, stage_id, num_stages=None, weights=None):
        """Spec slice owned by ``stage_id``."""
        if self._partition is None:
            self.partition_layers(num_stages, weights)
        lo, hi = self._partition[stage_id], self._partition[stage_id + 1]
        return self.specs[lo:hi]

    def tied_keys(self):
        keys = []
        for spec in self.specs:
            if isinstance(spec, TiedLayerSpec) and spec.key not in keys:
                keys.append(spec.key)
        return keys
