"""Compiled pipeline execution over the ``pipe`` mesh axis.

The reference interprets instruction lists rank-by-rank, sending activations
through 2-rank NCCL groups (`runtime/pipe/engine.py:1144`, `pipe/p2p.py`).
The TPU-native execution model compiles the whole train batch into ONE XLA
program: stages live at coordinates of the ``pipe`` mesh axis and
microbatch activations rotate stage-to-stage with ``lax.ppermute`` over
ICI. Two programs are provided:

- :func:`make_pipeline_loss_fn` — a GPipe fill-drain wavefront; the
  backward falls out of AD (ppermute's transpose is the reverse rotation).
  Used for eval/forward-only, and differentiable for tests — but AD runs
  all forwards before any backward, so its train memory is O(M) per stage.
- :func:`make_pipeline_value_and_grad_fn` — the executed **1F1B**
  schedule: one scan interleaving forward and backward ticks with an
  O(S) activation ring buffer independent of M (the instruction ISA of
  `schedule.py`, executed). This is what :class:`PipelineEngine` trains
  with.

Model contract: a :class:`~deepspeed_tpu.runtime.pipe.module.PipelineModule`
whose specs decompose as ``prologue + body + epilogue``:

- **body** — the longest homogeneous run of identical LayerSpecs (the
  transformer blocks). Their params are stacked to a leading
  ``[num_stages, layers_per_stage]`` dim sharded ``P('pipe')``: each device
  holds only its stage's layers — the pipeline memory partitioning of
  `pipe/module.py:348`.
- **prologue/epilogue** — leading/trailing heterogeneous specs (embedding,
  final norm, head). They replicate across ``pipe`` and run only on the
  first/last stage (``lax.cond``); tied specs share one param copy and their
  gradients sum across the stages that use them — the tied-weight
  replication + allreduce of `pipe/module.py:405-474`, done by AD.

Layer protocol: built layer objects expose ``init(rng, x) -> params`` and
``apply(params, x, rng=None) -> y``. Flax modules are adapted automatically.
"""

import contextlib
import dataclasses
from functools import partial
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.fp8 import fp8_scope
from deepspeed_tpu.parallel.collectives import (barrier_after,
                                                log_collective_site,
                                                manual_axes, overlap_scope)
from deepspeed_tpu.utils.compat import axis_size, shard_map
from deepspeed_tpu.runtime.pipe.module import LayerSpec, TiedLayerSpec


# ---------------------------------------------------------------------------
# layer adaptation
# ---------------------------------------------------------------------------
class FlaxLayerAdapter:
    """Wrap a flax ``nn.Module`` into the (init, apply) layer protocol."""

    def __init__(self, module):
        self.module = module

    def init(self, rng, x):
        variables = self.module.init({"params": rng, "dropout": rng}, x)
        return variables["params"]

    def apply(self, params, x, rng=None):
        rngs = {"dropout": rng} if rng is not None else {}
        return self.module.apply({"params": params}, x, rngs=rngs)


def adapt_layer(obj):
    """Normalize a built layer object to the (init, apply) protocol."""
    if hasattr(obj, "init") and hasattr(obj, "apply"):
        return obj
    try:
        import flax.linen as nn
        if isinstance(obj, nn.Module):
            return FlaxLayerAdapter(obj)
    except ImportError:
        pass
    raise TypeError(
        f"pipeline layer {obj!r} must expose init(rng, x) and "
        f"apply(params, x, rng=None), or be a flax Module")


def _spec_signature(spec: LayerSpec):
    """Two specs with the same signature build structurally-identical layers
    (stackable into the homogeneous body)."""
    return (spec.typename, spec.module_args,
            tuple(sorted(spec.module_kwargs.items())),
            isinstance(spec, TiedLayerSpec))


def split_specs(specs: List[LayerSpec]):
    """(prologue, body, epilogue): body = the longest run of
    signature-identical non-tied specs."""
    best_lo, best_hi = 0, 0
    i = 0
    while i < len(specs):
        if isinstance(specs[i], TiedLayerSpec):
            i += 1
            continue
        j = i
        sig = _spec_signature(specs[i])
        while j < len(specs) and _spec_signature(specs[j]) == sig:
            j += 1
        if j - i > best_hi - best_lo:
            best_lo, best_hi = i, j
        i = j
    return specs[:best_lo], specs[best_lo:best_hi], specs[best_hi:]


# ---------------------------------------------------------------------------
# parts: built layers + params + specs
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class PipelineParts:
    """Everything the compiled pipeline needs, derived from a PipelineModule."""
    num_stages: int
    layers_per_stage: int
    prologue_specs: List[LayerSpec]
    epilogue_specs: List[LayerSpec]
    prologue_layers: List[Any]          # adapted layer objects
    body_layer: Any                     # one adapted layer (homogeneous)
    epilogue_layers: List[Any]
    params: Dict[str, Any]              # {prologue, body, epilogue, tied}
    param_specs: Dict[str, Any]         # PartitionSpec pytree, same structure
    loss_fn: Callable                   # loss_fn(output, micro_batch)
    auto_axes: tuple = ()               # GSPMD-mode mesh axes (module's)

    def prologue_apply(self, params, micro, rng=None):
        """tokens/micro-batch → first activation (first stage only)."""
        x = micro
        for idx, (spec, layer) in enumerate(
                zip(self.prologue_specs, self.prologue_layers)):
            p = self._layer_params(params, "prologue", idx, spec)
            x = self._apply_one(spec, layer, p, x, rng)
        return x

    def epilogue_apply(self, params, x, rng=None):
        """last activation → model output (last stage only)."""
        for idx, (spec, layer) in enumerate(
                zip(self.epilogue_specs, self.epilogue_layers)):
            p = self._layer_params(params, "epilogue", idx, spec)
            x = self._apply_one(spec, layer, p, x, rng)
        return x

    def body_apply(self, layer_params, x, rng=None):
        return self.body_layer.apply(layer_params, x, rng)

    def _layer_params(self, params, section, idx, spec):
        if isinstance(spec, TiedLayerSpec):
            return params["tied"][spec.key]
        return params[section][f"layer_{idx}"]

    def _apply_one(self, spec, layer, p, x, rng):
        if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
            return spec.forward_fn(p, x)
        return layer.apply(p, x, rng)



def _leaf_names(path):
    return [str(getattr(q, "key", getattr(q, "idx", q))) for q in path]


def _is_expert_leaf(path, a, local=False):
    """Expert-banked body leaves (named ``expert_*`` with a bank dim, e.g.
    `moe/expert_pipe.py:ExpertParallelFFNLayer`) shard their bank dim over
    the ``expert`` mesh axis instead of replicating. The same predicate
    gates the spec AND the gradient tail reduction — they must agree, or a
    replicated leaf would skip its expert pmean (rank-divergent grads
    under a replicated out-spec).

    ``local=True`` when ``a`` is a device-local stage tree (the stacked
    ``[S]`` stage dim stripped, so the bank dim sits one axis lower) —
    getting this wrong silently cross-mixes shard gradients for low-rank
    leaves like biases."""
    min_ndim = 2 if local else 3
    return (any(n.startswith("expert_") for n in _leaf_names(path))
            and a.ndim >= min_ndim)


def _is_mp_leaf(path, a, local=False):
    """Tensor-parallel body leaves (named ``mp_*``, shard dim first, e.g.
    `parallel/pipe_tp.py:TPBlockLayer`) split that dim over the ``model``
    mesh axis — the Megatron column/row partition inside the pipeline.
    Same spec/tail-reduction coupling (and the same ``local`` caveat) as
    :func:`_is_expert_leaf`."""
    min_ndim = 2 if local else 3
    return (any(n.startswith("mp_") for n in _leaf_names(path))
            and a.ndim >= min_ndim)


def body_param_specs(body_params, auto_axes=()):
    """Per-leaf PartitionSpecs for the stacked body [S, L/S, ...]: stage
    dim over ``pipe``; expert banks additionally put their bank dim (the
    first post-stack dim) over ``expert``.

    ``auto_axes``: mesh axes left in GSPMD (auto) mode by a
    partial-manual ``shard_map`` — their mentions are dropped (shard_map
    in/out specs may only name manual axes; the auto-axis sharding lives
    at the jit level and inside via sharding constraints)."""

    def spec(path, a):
        # No trailing Nones after the sharded dim: the compiled step
        # round-trips these shardings with the trailing Nones normalized
        # away, and a spec that differs only there is a NEW jit cache key
        # — every step after the first would recompile once.
        if _is_expert_leaf(path, a):
            s = P("pipe", None, "expert")
        elif _is_mp_leaf(path, a):
            s = P("pipe", None, "model")
        else:
            s = P("pipe", *([None] * (a.ndim - 1)))
        if auto_axes:
            s = P(*(None if ax in auto_axes else ax for ax in s))
        return s

    return jax.tree_util.tree_map_with_path(spec, body_params)


def build_pipeline_parts(module, num_stages: int, rng,
                         example_micro) -> PipelineParts:
    """Build layers, initialize params, and stack the body.

    ``example_micro``: a microbatch-shaped pytree used for shape inference
    (row count is irrelevant — only trailing dims matter).
    """
    pro_specs, body_specs, epi_specs = split_specs(module.specs)
    if not body_specs:
        raise ValueError("PipelineModule needs a homogeneous run of layer "
                         "specs to pipeline (the transformer blocks)")
    if len(body_specs) % num_stages != 0:
        raise ValueError(
            f"{len(body_specs)} pipelined layers do not divide evenly over "
            f"{num_stages} stages; adjust n_layer or the pipe axis")

    params = {"prologue": {}, "body": None, "epilogue": {}, "tied": {}}
    tied_layers: Dict[str, Any] = {}

    def next_rng(i):
        if module.seed_layers:
            return jax.random.PRNGKey(module.base_seed + i)
        return jax.random.fold_in(rng, i)

    layer_idx = 0
    x = example_micro

    def build_one(spec, section, idx, x):
        nonlocal layer_idx
        layer = adapt_layer(spec.build())
        if isinstance(spec, TiedLayerSpec):
            if spec.key not in params["tied"]:
                params["tied"][spec.key] = layer.init(next_rng(layer_idx), x)
                tied_layers[spec.key] = layer
            p = params["tied"][spec.key]
        else:
            p = layer.init(next_rng(layer_idx), x)
            params[section][f"layer_{idx}"] = p
        layer_idx += 1
        if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
            return layer, spec.forward_fn(p, x)
        return layer, layer.apply(p, x, None)

    prologue_layers = []
    for idx, spec in enumerate(pro_specs):
        layer, x = build_one(spec, "prologue", idx, x)
        prologue_layers.append(layer)

    body_layer = None
    body_params = []
    for spec in body_specs:
        layer = adapt_layer(spec.build())
        if body_layer is None:
            body_layer = layer
        p = layer.init(next_rng(layer_idx), x)
        layer_idx += 1
        x = layer.apply(p, x, None)
        body_params.append(p)

    epilogue_layers = []
    for idx, spec in enumerate(epi_specs):
        layer, x = build_one(spec, "epilogue", idx, x)
        epilogue_layers.append(layer)

    # Stack body params: [L, ...] → [S, L/S, ...], leading dim over 'pipe'.
    lps = len(body_specs) // num_stages
    stacked = jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *body_params)
    params["body"] = jax.tree_util.tree_map(
        lambda a: a.reshape((num_stages, lps) + a.shape[1:]), stacked)

    def spec_of(section):
        return jax.tree_util.tree_map(lambda _: P(), params[section])

    # Body PLACEMENT specs: the name-based contract (mp_*/expert_*), or —
    # when the layer carries GSPMD partition metadata (the flax adapter,
    # `parallel/pipe_auto.py`) AND the module opted into auto axes — the
    # layer's own per-leaf specs with the [stage, layers/stage] stacking
    # dims prepended. Placement specs may name auto axes; the shard_map
    # in/out specs (built per call in `_call_pipeline`) are what must
    # stay manual-only. Without auto_axes the adapter metadata is
    # deliberately IGNORED for placement: sharding body params over an
    # axis the all-manual shard_map treats as replicated would at best
    # resharde every step and at worst hit the CPU runtime's collective
    # rendezvous deadlock the engine gate documents.
    auto_axes = tuple(getattr(module, "auto_axes", ()) or ())
    body_place_specs = body_param_specs(params["body"])
    spec_fn = getattr(body_layer, "param_partition_specs", None)
    if spec_fn is not None and auto_axes:
        layer_specs = spec_fn(body_params[0])
        body_place_specs = jax.tree_util.tree_map(
            lambda sp: P("pipe", None, *tuple(sp)), layer_specs,
            is_leaf=lambda x: isinstance(x, P))

    param_specs = {
        "prologue": spec_of("prologue"),
        "epilogue": spec_of("epilogue"),
        "tied": spec_of("tied"),
        "body": body_place_specs,
    }

    loss_fn = module.loss_fn
    if loss_fn is None:
        raise ValueError("PipelineModule.loss_fn required for training")

    return PipelineParts(num_stages=num_stages,
                         layers_per_stage=lps,
                         prologue_specs=pro_specs,
                         epilogue_specs=epi_specs,
                         prologue_layers=prologue_layers,
                         body_layer=body_layer,
                         epilogue_layers=epilogue_layers,
                         params=params,
                         param_specs=param_specs,
                         loss_fn=loss_fn,
                         auto_axes=auto_axes)


def sequential_loss_fn(parts: PipelineParts, params, micro_batches, rng=None):
    """Non-pipelined reference execution of the same parts (test oracle):
    mean loss over the leading microbatch dim."""
    body = jax.tree_util.tree_map(
        lambda a: a.reshape((-1,) + a.shape[2:]), params["body"])
    n_layers = parts.num_stages * parts.layers_per_stage
    num_total, den_total = 0.0, 0.0
    weighted = None
    M = jax.tree_util.tree_leaves(micro_batches)[0].shape[0]
    for m in range(M):
        micro = jax.tree_util.tree_map(lambda a: a[m], micro_batches)
        x = parts.prologue_apply(params, micro,
                                 None if rng is None
                                 else jax.random.fold_in(rng, m))
        for li in range(n_layers):
            lp = jax.tree_util.tree_map(lambda a: a[li], body)
            x = parts.body_apply(lp, x, None)
        out = parts.epilogue_apply(params, x, None)
        res = parts.loss_fn(out, micro)
        weighted = isinstance(res, tuple)
        if weighted:
            num_total = num_total + res[0]
            den_total = den_total + res[1]
        else:
            num_total = num_total + res
    if weighted:
        return num_total / jnp.maximum(den_total, 1.0)
    return num_total / M


# ---------------------------------------------------------------------------
# the compiled pipeline loss
# ---------------------------------------------------------------------------
def make_pipeline_loss_fn(parts: PipelineParts, mesh, num_micro: int,
                          remat: bool = True, auto_axes=None,
                          overlap=None, fp8=None):
    """Build ``loss_fn(params, batch, rng)`` executing the GPipe rotation.

    ``batch``: pytree of ``[rows, ...]`` arrays, rows divisible by
    ``num_micro``; rows are data-sharded, microbatches run through the
    ``pipe`` axis wavefront. Differentiable end-to-end: ``jax.grad`` of this
    function performs the full backward pipeline (cooldown included).

    ``auto_axes``: GSPMD-mode mesh axes (see ``_call_pipeline``);
    defaults to the module's, recorded on ``parts``.
    ``overlap``: optional ``parallel.collectives.OverlapPlan`` switching
    manual-mode layers to the latency-hiding chunked collectives.
    ``fp8``: optional ``ops.fp8.Fp8Plan`` routing the TP blocks' local
    matmuls through current-scaling fp8 qdq (`ops/fp8.py`).
    """
    auto_axes = _resolve_auto_axes(parts, mesh, auto_axes)
    S = parts.num_stages
    M = num_micro
    T = M + S - 1
    axis_tail = tuple(a for a in mesh.axis_names
                      if a not in ("pipe", "data") and a not in auto_axes)

    def device_fn(body_local, rest, batch_local, rng, use_rng):
        # body_local arrives as [1, L/S, ...] — this stage's shard.
        body_local = jax.tree_util.tree_map(lambda a: a[0], body_local)
        s = lax.axis_index("pipe")

        def micro_at(m):
            return jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
                batch_local)

        def mb_rng(m, section):
            # distinct dropout stream per (microbatch, stage, section)
            if not use_rng:
                return None
            key = jax.random.fold_in(jax.random.fold_in(rng, m), s)
            return jax.random.fold_in(key, section)

        def stage_fwd(x, key):
            if not use_rng:
                def layer(x, lp):
                    return parts.body_apply(lp, x, None), None
                x, _ = lax.scan(layer, x, body_local)
                return x

            def layer(carry, lp):
                x, k = carry
                k, sub = jax.random.split(k)
                return (parts.body_apply(lp, x, sub), k), None
            (x, _), _ = lax.scan(layer, (x, key), body_local)
            return x

        # activation template (shape-only trace; no FLOPs at runtime)
        act = jax.eval_shape(
            lambda p, mb: parts.prologue_apply(p, mb, None), rest,
            micro_at(0))
        zeros = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, a.dtype), act)
        # loss_fn may return a scalar (per-microbatch mean; averaged over
        # microbatches/shards) or (loss_sum, weight) for the exact global
        # weighted mean (e.g. token CE with uneven ignore-index masks).
        loss_probe = jax.eval_shape(
            lambda p, xx, mb: parts.loss_fn(
                parts.epilogue_apply(p, xx, None), mb),
            rest, act, micro_at(0))
        weighted = isinstance(loss_probe, tuple)
        if not weighted and mesh.shape.get("seq", 1) > 1:
            raise ValueError(
                "pipeline on a mesh with seq > 1 requires the weighted "
                "(loss_sum, weight) loss form: a scalar mean loss cannot "
                "express seq-sharded token counts, so losses and grads "
                "would be silently mis-scaled by the seq degree")

        def mb_loss_pair(x, m_oc):
            res = parts.loss_fn(
                parts.epilogue_apply(rest, x, mb_rng(m_oc, 2)),
                micro_at(m_oc))
            if weighted:
                num, den = res
                return num.astype(jnp.float32), den.astype(jnp.float32)
            return res.astype(jnp.float32), jnp.asarray(1.0, jnp.float32)

        def tick(carry, t):
            x_recv, num_acc, den_acc = carry
            m_in = jnp.clip(t - s, 0, M - 1)
            x_in = lax.cond(
                s == 0,
                lambda: parts.prologue_apply(rest, micro_at(m_in),
                                             mb_rng(m_in, 0)),
                lambda: x_recv)
            x = stage_fwd(x_in, mb_rng(m_in, 1))
            m_out = t - (S - 1)
            m_oc = jnp.clip(m_out, 0, M - 1)
            num, den = lax.cond(
                s == S - 1,
                lambda: mb_loss_pair(x, m_oc),
                lambda: (jnp.asarray(0.0, jnp.float32),
                         jnp.asarray(0.0, jnp.float32)))
            valid = (m_out >= 0) & (m_out < M)
            num_acc = num_acc + jnp.where(valid, num, 0.0)
            den_acc = den_acc + jnp.where(valid, den, 0.0)
            x_next = lax.ppermute(
                x, "pipe", [(i, (i + 1) % S) for i in range(S)])
            return (x_next, num_acc, den_acc), None

        tick_fn = jax.checkpoint(tick) if remat else tick
        zero_f = jnp.asarray(0.0, jnp.float32)
        (_, num_sum, den_sum), _ = lax.scan(
            tick_fn, (zeros, zero_f, zero_f), jnp.arange(T))

        # Only the last stage accumulated loss; share it everywhere so the
        # result is replicated, matching out_specs=P().
        if weighted:
            # exact global weighted mean: sum losses / sum weights. The
            # ``seq`` axis joins the psum — sequence-parallel layers hold
            # per-token-shard partial sums; with replicated compute the
            # n-fold num and den cancel (same note as the 1F1B path).
            seq_tail = tuple(a for a in axis_tail if a == "seq")
            loss_axes = ("pipe", "data") + seq_tail
            num = lax.psum(num_sum, loss_axes)
            den = lax.psum(den_sum, loss_axes)
            loss = num / jnp.maximum(den, 1.0)
            rest_tail = tuple(a for a in axis_tail if a != "seq")
        else:
            # mean of per-(microbatch, shard) means
            loss = lax.psum(num_sum, "pipe") / M
            loss = lax.pmean(loss, "data")
            rest_tail = axis_tail
        if rest_tail:
            loss = lax.pmean(loss, rest_tail)
        return loss

    def pipeline_loss(params, batch, rng):
        return _call_pipeline(mesh, M, device_fn, params, batch, rng,
                              out_specs=lambda body_specs, rest_specs: P(),
                              auto_axes=auto_axes, overlap=overlap,
                              fp8=fp8)

    return pipeline_loss


def _resolve_auto_axes(parts, mesh, auto_axes):
    """One source of truth for the GSPMD-mode axes: the module's
    (recorded on ``parts`` by ``build_pipeline_parts``, where the
    placement specs were derived from it). An explicit argument must
    agree — placement and shard_map manualness disagreeing is exactly
    the silent-resharding / rendezvous-deadlock class this prevents."""
    resolved = parts.auto_axes if auto_axes is None else tuple(auto_axes)
    if tuple(resolved) != tuple(parts.auto_axes):
        raise ValueError(
            f"auto_axes {resolved} disagrees with the module's "
            f"{parts.auto_axes} that built these parts (the body placement "
            "specs were derived from the latter)")
    unknown = set(resolved) - set(mesh.axis_names)
    if unknown:
        raise ValueError(
            f"auto_axes {sorted(unknown)} are not mesh axes "
            f"{tuple(mesh.axis_names)} — a typo here would silently "
            "disable tensor parallelism")
    bad = set(resolved) & {"pipe", "data", "seq"}
    if bad:
        raise ValueError(
            f"auto_axes {sorted(bad)} must stay manual: the 1F1B schedule "
            "ppermutes over pipe, batches shard over data, and the "
            "sequence-parallel loss psums over seq")
    return resolved


def _call_pipeline(mesh, M, device_fn, params, batch, rng, extra=(),
                   out_specs=None, auto_axes=(), overlap=None, fp8=None):
    """Shared shard_map wrapper for the pipeline programs: microbatch the
    batch rows, split off the replicated param groups, build the in/out
    specs, and invoke ``device_fn`` over the mesh. ``out_specs`` is a
    callable of (body_specs, rest_specs) so callers returning grads can
    reuse the input layouts.

    ``auto_axes``: mesh axes the shard_map leaves in GSPMD (auto) mode —
    arrays stay global along them inside ``device_fn`` and the user's
    sharding constraints / param shardings drive the partitioning
    (user-composable tensor parallelism: any flax model's GSPMD
    annotations work inside the pipeline; see `parallel/pipe_auto.py`).
    The pipe/data axes must stay manual (ppermute schedule, batch
    sharding)."""
    batch_sharding = NamedSharding(mesh, P(None, "data"))

    def to_micro(a):
        rows = a.shape[0]
        assert rows % M == 0, (
            f"batch rows {rows} not divisible by {M} microbatches")
        return a.reshape((M, rows // M) + a.shape[1:])

    batch_m = jax.tree_util.tree_map(to_micro, batch)
    batch_m = jax.tree_util.tree_map(
        lambda a: lax.with_sharding_constraint(a, batch_sharding),
        batch_m)
    rest = {k: params[k] for k in ("prologue", "epilogue", "tied")}
    use_rng = rng is not None
    key = rng if use_rng else jnp.zeros((2,), jnp.uint32)

    manual = tuple(a for a in mesh.axis_names if a not in auto_axes)
    body_specs = body_param_specs(params["body"], auto_axes)
    rest_specs = jax.tree_util.tree_map(lambda _: P(), rest)
    batch_specs = jax.tree_util.tree_map(
        lambda _: P(None, "data"), batch_m)

    def manual_device_fn(*args, **kwargs):
        # Declare the MANUAL mesh axes while the device body traces:
        # layers with explicit collectives (TP blocks, expert-parallel
        # FFN) switch them on via parallel.collectives.axis_is_manual;
        # auto axes stay GSPMD-driven (axis_is_manual False → manual
        # collectives no-op, constraints rule). ``overlap`` (an
        # OverlapPlan or None) rides the same trace-time channel: layers
        # consult parallel.collectives.overlap_plan to swap monolithic
        # collectives for the chunked latency-hiding form. ``fp8`` (an
        # ops.fp8.Fp8Plan or None) rides the same way: with no state
        # dict the scope selects stateless current scaling — per-site
        # amax threading isn't available through the manual 1F1B
        # program's hand-written backward.
        with manual_axes(manual), overlap_scope(overlap), fp8_scope(fp8):
            return device_fn(*args, **kwargs)

    fn = shard_map(
        partial(manual_device_fn, use_rng=use_rng),
        mesh=mesh,
        in_specs=(body_specs, rest_specs, batch_specs, P()) +
        tuple(P() for _ in extra),
        out_specs=out_specs(body_specs, rest_specs),
        axis_names=set(manual),
        check_vma=False)
    return fn(params["body"], rest, batch_m, key, *extra)


def _tree_ppermute(tree, perm):
    """Stage-transfer ppermute over a pytree with the leaf permutes chained
    (``barrier_after``): two *independent* in-flight collective-permutes
    split the in-process CPU runtime's global rendezvous (half the devices
    arrive at one op_id, half at the other) and deadlock. Chaining costs
    nothing — per-tick latency is bounded by the largest leaf anyway."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    log_collective_site("pipeline.stage_transfer", "pipe", "ppermute",
                        chunks=len(leaves),
                        chained=not _FIXTURE_UNCHAINED_TRANSFER)
    dep, out = None, []
    for leaf in leaves:
        if _FIXTURE_UNCHAINED_TRANSFER:
            dep = None
        leaf = lax.ppermute(barrier_after(leaf, dep), "pipe", perm)
        dep = leaf
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# trace-only regression fixtures: re-introduce the historical deadlocks
# ---------------------------------------------------------------------------
# Two scheduling bugs were fixed in the uniform-tick restructure (see the
# comments inside ``tick`` below):
#   * the stage transfer sat inside stage-divergent control flow, so some
#     devices reached the ppermute rendezvous and others did not;
#   * concurrent in-flight permutes were not chained, splitting the global
#     rendezvous across op_ids.
# These flags revert each bug THROUGH THE PRODUCTION CODE PATH so the
# analysis.jaxpr deadlock checker can be regression-tested against the real
# pipeline jaxpr, at trace time only. Programs traced under the fixture
# must never be executed — they are the deadlock.
_FIXTURE_DIVERGENT_TRANSFER = False
_FIXTURE_UNCHAINED_TRANSFER = False


@contextlib.contextmanager
def pipeline_trace_fixture(divergent_transfer=False, unchained_transfer=False):
    """TRACE-ONLY: rebuild the pre-fix divergent/unchained tick schedule.

    The flags are read while ``tick`` traces, so the ``vag`` fn must be
    built *and traced* (``jax.jit(...).trace`` / ``make_jaxpr``) inside this
    context. Never run the resulting program."""
    global _FIXTURE_DIVERGENT_TRANSFER, _FIXTURE_UNCHAINED_TRANSFER
    prev = (_FIXTURE_DIVERGENT_TRANSFER, _FIXTURE_UNCHAINED_TRANSFER)
    _FIXTURE_DIVERGENT_TRANSFER = divergent_transfer
    _FIXTURE_UNCHAINED_TRANSFER = unchained_transfer
    try:
        yield
    finally:
        _FIXTURE_DIVERGENT_TRANSFER, _FIXTURE_UNCHAINED_TRANSFER = prev


# ---------------------------------------------------------------------------
# executed 1F1B: interleaved forward/backward in ONE compiled scan
# ---------------------------------------------------------------------------
def make_pipeline_value_and_grad_fn(parts: PipelineParts, mesh,
                                    num_micro: int, compute_dtype=None,
                                    data_local=False, auto_axes=None,
                                    overlap=None, fp8=None):
    """Build ``vag(params, batch, rng, scale) -> (loss, grads)`` running a
    hand-scheduled 1F1B pipeline (the reference's ``TrainSchedule``
    interleave, `runtime/pipe/schedule.py:189-241`, executed rather than
    differentiated).

    Why not ``jax.grad`` of the GPipe rotation: AD runs every forward tick
    before any backward tick, so each stage must hold O(M) microbatch
    activations (the blow-up 1F1B exists to prevent — reference buffer
    bound `runtime/pipe/schedule.py:243-247`). Here one ``lax.scan`` over
    ``M + 2S - 2`` ticks interleaves them: at tick ``t`` stage ``s``
    forwards microbatch ``t - s`` and backwards microbatch
    ``t - (2S - 2 - s)`` — the cotangent for microbatch ``m`` reaches stage
    ``s`` exactly ``2(S-1-s)+1`` ticks after its forward, so a ring buffer
    of ``2S - 1`` stage-input activations suffices **independent of M**.
    Stage internals rematerialize in the backward (one ``jax.vjp`` per
    tick), the Megatron-style full-recompute tradeoff.

    Gradient scaling: backward seeds are 1.0 per microbatch loss-sum; the
    final grads are scaled by ``scale / total_weight`` (weighted losses) or
    ``scale / (M * |data|)`` — weights (token counts) don't depend on
    params, so this equals grad of ``scale * mean_loss``.

    ``data_local=True`` (the 1-bit Adam composition): the dense psum over
    ``data`` is SKIPPED — grads come back with a stacked leading ``data``
    axis, scaled so their *mean* over that axis is the true gradient, for
    a compressed collective to average instead (the analog of the
    reference disabling engine allreduce for OnebitAdam,
    onebit_adam.py:372).

    ``auto_axes`` (round 5, user-composable TP): mesh axes left in GSPMD
    mode — no manual collectives reference them (their reductions are
    XLA's job); typically ``("model",)`` so any flax model's
    ``nn.with_partitioning`` / sharding-constraint annotations do Megatron
    TP inside the 1F1B without hand-written collectives. Defaults to the
    module's, recorded on ``parts``.
    """
    auto_axes = _resolve_auto_axes(parts, mesh, auto_axes)
    S = parts.num_stages
    M = num_micro
    T = M + 2 * S - 2
    K = 2 * S - 1
    axis_tail = tuple(a for a in mesh.axis_names
                      if a not in ("pipe", "data") and a not in auto_axes)
    f32 = jnp.float32

    def cast(tree):
        if compute_dtype is None:
            return tree
        return jax.tree_util.tree_map(
            lambda a: a.astype(compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

    def device_fn(body_local, rest, batch_local, rng, scale, use_rng):
        body_local = jax.tree_util.tree_map(lambda a: a[0], body_local)
        s = lax.axis_index("pipe")

        def micro_at(m):
            return jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, m, 0, keepdims=False),
                batch_local)

        def mb_rng(m, section):
            if not use_rng:
                return None
            key = jax.random.fold_in(jax.random.fold_in(rng, m), s)
            return jax.random.fold_in(key, section)

        def stage_fwd(body, x, key):
            if not use_rng:
                def layer(x, lp):
                    return parts.body_apply(cast(lp), x, None), None
                x, _ = lax.scan(layer, x, body)
                return x

            def layer(carry, lp):
                x, k = carry
                k, sub = jax.random.split(k)
                return (parts.body_apply(cast(lp), x, sub), k), None
            (x, _), _ = lax.scan(layer, (x, key if key is not None
                                         else jnp.zeros((2,), jnp.uint32)),
                                 body)
            return x

        def prologue(r, m):
            return parts.prologue_apply(cast(r), micro_at(m), mb_rng(m, 0))

        act = jax.eval_shape(lambda r: prologue(r, 0), rest)
        zeros_act = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, a.dtype), act)
        loss_probe = jax.eval_shape(
            lambda r, xx: parts.loss_fn(
                parts.epilogue_apply(cast(r), xx, None), micro_at(0)),
            rest, act)
        weighted = isinstance(loss_probe, tuple)
        if not weighted and mesh.shape.get("seq", 1) > 1:
            raise ValueError(
                "pipeline on a mesh with seq > 1 requires the weighted "
                "(loss_sum, weight) loss form: a scalar mean loss cannot "
                "express seq-sharded token counts, so losses and grads "
                "would be silently mis-scaled by the seq degree")

        zeros_body_g = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, f32), body_local)
        zeros_rest_g = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, f32), rest)

        def as_pair(res):
            if weighted:
                num, den = res
                return num.astype(f32), den.astype(f32)
            return res.astype(f32), jnp.asarray(1.0, f32)

        def loss_head_pair(y_b, m):
            """vjp of epilogue → loss at the stage OUTPUT (last stage
            only). Contains no model-axis collectives, so it may sit
            inside the stage-divergent cond; the stage vjp itself (which
            does) runs uniformly in the tick body. Seeded with the loss
            scale so fp16 cotangents ride above the underflow floor
            through the whole backward (the reference scales the loss
            before backprop; scaling only at the end in fp32 would make
            dynamic loss scaling a numeric no-op)."""
            def h(r, yy):
                out = parts.epilogue_apply(cast(r), yy, mb_rng(m, 2))
                return as_pair(parts.loss_fn(out, micro_at(m)))
            (num, den), hvjp = jax.vjp(h, rest, y_b)
            gr, gy = hvjp((scale.astype(f32), jnp.asarray(0.0, f32)))
            return gy, gr, num, den

        def prologue_vjp(gx, m):
            _, vjp = jax.vjp(lambda r: prologue(r, m), rest)
            (gr,) = vjp(gx)
            return gr

        def tick(carry, t):
            x_recv, g_recv, buf, gb_acc, gr_acc, num_acc, den_acc = carry

            # ---- forward half: microbatch mf = t - s -----------------
            mf = t - s
            valid_f = (mf >= 0) & (mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            x_in = lax.cond(
                valid_f,
                lambda: lax.cond(s == 0,
                                 lambda: prologue(rest, mf_c),
                                 lambda: x_recv),
                lambda: zeros_act)
            slot_f = mf_c % K
            buf = lax.cond(
                valid_f,
                lambda: jax.tree_util.tree_map(
                    lambda b, xi: lax.dynamic_update_index_in_dim(
                        b, xi, slot_f, 0), buf, x_in),
                lambda: buf)
            # stage_fwd runs UNCONDITIONALLY: TP layers put model-axis
            # collectives inside it, and a collective inside stage-
            # divergent control flow is invalid SPMD — the in-process CPU
            # runtime's global collective-permute rendezvous deadlocks
            # when one stage enters the branch and another doesn't (the
            # seed got away with `s < S - 1` here only because all-reduce
            # rendezvous is per replica group). Bubble ticks and the last
            # stage compute on zeros and the result is discarded.
            y = stage_fwd(body_local, x_in, mb_rng(mf_c, 1))
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            if _FIXTURE_DIVERGENT_TRANSFER:
                # pre-fix schedule: the transfer only fires on "useful"
                # ticks — valid_f depends on s (= axis_index("pipe")), so
                # stages disagree about entering the branch and the
                # ppermute's global rendezvous deadlocks. Kept compilable
                # but never executed; exists for the deadlock-rule tests.
                x_next = lax.cond(
                    valid_f,
                    lambda: _tree_ppermute(y, fwd_perm),
                    lambda: y)
            else:
                x_next = _tree_ppermute(y, fwd_perm)

            # ---- backward half: microbatch mb = t - (2S-2-s) ---------
            mb_ = t - (2 * S - 2 - s)
            valid_b = (mb_ >= 0) & (mb_ < M)
            mb_c = jnp.clip(mb_, 0, M - 1)
            x_b = jax.tree_util.tree_map(
                lambda b: lax.dynamic_index_in_dim(b, mb_c % K, 0,
                                                   keepdims=False), buf)
            # The two halves are data-independent, so the backward half's
            # collectives (TP chunk rings, g_next) would race x_next on
            # the in-process CPU runtime's global rendezvous. Order the
            # whole backward half after the forward stage transfer by
            # barriering its inputs — the tick's collectives then form
            # one chain: fwd TP → x_next → bwd TP → g_next.
            if _FIXTURE_UNCHAINED_TRANSFER:
                # pre-fix schedule: backward half issues with no dataflow
                # edge on x_next, so its g_next ppermute races the
                # forward transfer on the global rendezvous. Trace-only.
                g_in = g_recv
            else:
                (x_b, g_in), _ = lax.optimization_barrier(
                    ((x_b, g_recv), x_next))

            # The stage vjp — the piece holding model-axis collectives —
            # runs UNCONDITIONALLY and uniformly across stages (same SPMD
            # constraint as stage_fwd above; the seed's per-stage
            # last_vjp/mid_vjp branches compile to DIFFERENT permute
            # channels, splitting the rendezvous). Only the collective-
            # free cotangent seed diverges: the last stage seeds from
            # epilogue∘loss at its own output, the rest from the received
            # cotangent. Invalid (bubble) ticks run on buffer garbage and
            # are masked out of the accumulators below.
            y_b, stage_vjp = jax.vjp(
                lambda b, xx: stage_fwd(b, xx, mb_rng(mb_c, 1)),
                body_local, x_b)
            gy, gr, num, den = lax.cond(
                s == S - 1,
                lambda: loss_head_pair(y_b, mb_c),
                lambda: (g_in, zeros_rest_g, jnp.asarray(0.0, f32),
                         jnp.asarray(0.0, f32)))
            gb, gx = stage_vjp(gy)
            gr = lax.cond(
                s == 0,
                lambda: jax.tree_util.tree_map(
                    jnp.add, gr, prologue_vjp(gx, mb_c)),
                lambda: gr)

            def mask(tree):
                return jax.tree_util.tree_map(
                    lambda a: jnp.where(valid_b, a, jnp.zeros_like(a)),
                    tree)

            gb_acc = jax.tree_util.tree_map(jnp.add, gb_acc, mask(gb))
            gr_acc = jax.tree_util.tree_map(jnp.add, gr_acc, mask(gr))
            num_acc = num_acc + jnp.where(valid_b, num, 0.0)
            den_acc = den_acc + jnp.where(valid_b, den, 0.0)
            g_next = _tree_ppermute(
                mask(gx), [(i, (i - 1) % S) for i in range(S)])
            return (x_next, g_next, buf, gb_acc, gr_acc, num_acc,
                    den_acc), None

        buf0 = jax.tree_util.tree_map(
            lambda a: jnp.zeros((K,) + a.shape, a.dtype), zeros_act)
        zero_f = jnp.asarray(0.0, f32)
        carry0 = (zeros_act, zeros_act, buf0, zeros_body_g, zeros_rest_g,
                  zero_f, zero_f)
        (_, _, _, gb_acc, gr_acc, num_sum, den_sum), _ = lax.scan(
            tick, carry0, jnp.arange(T))

        # ---- reductions + scaling --------------------------------------
        # (the loss scale is already in the accumulated grads via the vjp
        # seed; here only the mean-normalization divides through, in fp32)
        #
        # The ``seq`` axis is COMPUTE-partitioned (sequence-parallel
        # layers shard the token dim; weights stay replicated), so in the
        # weighted form its num/den/grads are partial sums → psum, with
        # the global den normalizing. This is exact in BOTH worlds: with
        # replicated compute every seq rank holds identical num/den/g, so
        # the n-fold psum cancels against the n-fold den in gscale.
        seq_tail = tuple(a for a in axis_tail if a == "seq")
        if weighted:
            loss_axes = ("pipe", "data") + seq_tail
            D = lax.psum(den_sum, loss_axes)
            D = jnp.maximum(D, 1.0)
            loss = lax.psum(num_sum, loss_axes) / D
            gscale = 1.0 / D
        else:
            # scalar-mean losses cannot express seq-sharded token counts;
            # sequence-parallel modules must return (loss_sum, weight)
            n_data = axis_size("data")
            loss = lax.pmean(lax.psum(num_sum, "pipe") / M, "data")
            gscale = 1.0 / (M * n_data)
        # body grads stay pipe-sharded; rest grads sum across the stages
        # that touched them (the tied-weight allreduce, module.py:405-474)
        if data_local:
            # Scale so the MEAN over data ranks equals the true gradient:
            # mean_r(n_data * g_r * gscale) = sum_r g_r * gscale.
            n_data = axis_size("data")
            gb_acc = jax.tree_util.tree_map(
                lambda a: a * (gscale * n_data), gb_acc)
            gr_acc = jax.tree_util.tree_map(
                lambda a: lax.psum(a, "pipe") * (gscale * n_data), gr_acc)
        else:
            gb_acc = jax.tree_util.tree_map(
                lambda a: lax.psum(a, "data") * gscale, gb_acc)
            gr_acc = jax.tree_util.tree_map(
                lambda a: lax.psum(lax.psum(a, "pipe"), "data") * gscale,
                gr_acc)
        if weighted and seq_tail:
            # partial-sum semantics (see note above)
            gb_acc = jax.tree_util.tree_map(
                lambda a: lax.psum(a, seq_tail), gb_acc)
            gr_acc = jax.tree_util.tree_map(
                lambda a: lax.psum(a, seq_tail), gr_acc)
        other_tail = tuple(a for a in axis_tail
                           if not (weighted and a == "seq"))
        if other_tail:
            loss = lax.pmean(loss, other_tail)
            # Replicated leaves: identical per-rank grads (expert-partial
            # cotangents are already psum'd in-layer by psum_grad), so
            # pmean is exact. Expert-SHARDED leaves hold genuinely
            # different shards — never mix them across ``expert``.
            def tail_mean(path, a):
                # NB: gb_acc leaves here are stage-LOCAL (no [S] dim).
                axes = tuple(ax for ax in other_tail
                             if not ((ax == "expert" and
                                      _is_expert_leaf(path, a, local=True))
                                     or (ax == "model" and
                                         _is_mp_leaf(path, a, local=True))))
                return lax.pmean(a, axes) if axes else a
            gb_acc = jax.tree_util.tree_map_with_path(tail_mean, gb_acc)
            gr_acc = jax.tree_util.tree_map(
                lambda a: lax.pmean(a, other_tail), gr_acc)
        # restore the leading stage dim the shard_map out_spec strips
        # (+ a stacked data dim in data_local mode)
        gb_acc = jax.tree_util.tree_map(lambda a: a[None], gb_acc)
        if data_local:
            gb_acc = jax.tree_util.tree_map(lambda a: a[None], gb_acc)
            gr_acc = jax.tree_util.tree_map(lambda a: a[None], gr_acc)
        return loss, gb_acc, gr_acc

    def _out_specs(body_specs, rest_specs):
        if not data_local:
            return (P(), body_specs, rest_specs)
        stack = lambda spec: P("data", *tuple(spec))
        return (P(),
                jax.tree_util.tree_map(stack, body_specs),
                jax.tree_util.tree_map(stack, rest_specs))

    def pipeline_value_and_grad(params, batch, rng, scale):
        loss, gb, gr = _call_pipeline(
            mesh, M, device_fn, params, batch, rng,
            extra=(jnp.asarray(scale, jnp.float32),),
            out_specs=_out_specs, auto_axes=auto_axes, overlap=overlap,
            fp8=fp8)
        grads = {"prologue": gr["prologue"], "body": gb,
                 "epilogue": gr["epilogue"], "tied": gr["tied"]}
        return loss, grads

    return pipeline_value_and_grad
